"""The race model — thread-role discovery + field-access lockset facts
over the shared project model (ISSUE 12).

alazrace is the fifth analysis head and deliberately a THIN layer: it
reuses ``tools.alazlint.program.ProgramModel`` (function index, import
maps, ``self.x = Cls(...)`` attr typing, ctor-arg resolution) through
``tools.alazflow.flowmodel.FlowModel`` (element-type queue typing,
entry-surface closure) and layers on exactly what the ALZ050-054 rules
need:

- **thread roles** — every distinct start-of-thread the program can
  reach: resolvable ``threading.Thread(target=...)`` / ``Timer(...,
  fn)`` / ``executor.submit(fn)`` targets, the worker-loop naming
  convention ALZ030 already codified (``*_loop`` / ``*_worker`` /
  ``*_main`` / ``_consume``), HTTP-handler methods (``do_GET`` runs on
  the serving thread), and the serve/CLI entry surface folded into ONE
  ``main`` role. Each role closes over the call graph, so
  ``roles_of(fn)`` answers "which threads can be executing this line".

- **field escape** — per class, every field access site the model can
  attribute: ``self.f`` in the class's own methods (nested ``def run()``
  closures inherit the enclosing method's class — the daemon-thread
  idiom), ``self.attr.f`` through attr typing (the cross-module escape:
  an object constructed in module A, stored by B's constructor, mutated
  from B's worker), and ``local.f`` through local/element typing
  (``stream = self._streams[name]`` where ``_streams`` is a dict of
  ``_Stream(...)``). A class whose sites span ≥2 roles is
  multi-role-reachable — the race candidate surface.

- **locksets** — for every access site, the set of locks HELD there:
  the ``with`` nesting inside the function plus the locks every caller
  provably holds at every resolvable call site (an intersection-over-
  callers fixpoint seeded empty at role roots — the sound "what is
  ALWAYS held on entry" answer, closed over ALZ014's call summaries).

Known precision bounds (ARCHITECTURE §3o): roles are per-CLASS, not
per-instance — N workers sharing one role still race each other, which
is correct, but two pipelines owning PRIVATE instances of one class
merge into one role set, which over-approximates; the sanctioned
``# lockless-ok: <why>`` annotation (field- or class-level, audited by
ALZ053) is the designed pressure valve, exactly like ALZ010's justified
disables. Mutating METHOD calls (``self.d.update(...)``,
``self.q.append(...)``) count as writes in the lockset walk alongside
subscript stores and aug-assigns (the v1 bound ROADMAP carried, closed
by ISSUE 18): a call whose receiver is a field and whose name is a
known mutator records a compound write site — resize/rehash is
multi-op under the hood, same as ``d[k] = v``.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.alazlint.core import FileContext, callee as _callee
from tools.alazlint.program import (
    FunctionInfo,
    ProgramModel,
    _lock_id_for,
    _self_attr,
)
from tools.alazflow.flowmodel import walk_shallow

# the worker-thread naming convention ALZ030 codified, plus the
# HTTP-handler surface (BaseHTTPRequestHandler dispatches do_* on the
# serving thread) — roots even when the Thread() target is dynamic
WORKER_NAME_RE = re.compile(r"(_loop|_worker|_main)$|^_consume$|^do_[A-Z]+$")

# the process entry surface: every cmd_*/main/serve runs on the ONE main
# thread, so they fold into a single role instead of N phantom threads
ENTRY_NAME_RE = re.compile(r"^(cmd_|main$|serve$)")

MAIN_ROLE = "main"

# ``# lockless-ok: <why>`` — the sanctioned intentionally-unsynchronized
# marker ALZ050/051 honor and ALZ053 audits. Field-level on the
# declaration statement, or class-level on the ``class X:`` line.
_LOCKLESS_RE = re.compile(r"#\s*lockless-ok(?::\s*(?P<why>\S.*))?")

# ``# role-private: <why>`` — class-level claim that INSTANCES of this
# class are confined to one thread at a time (the per-shard Aggregator
# pattern: the serial pipeline's instance and each shard worker's
# instance are distinct objects, so the class-level role union is not a
# race). Honored by ALZ050/051/052, audited by ALZ053, and recorded in
# the golden map so the claim is reviewable topology, not a mute button.
_ROLE_PRIVATE_RE = re.compile(r"#\s*role-private(?::\s*(?P<why>\S.*))?")

_MUTATING_SUBSCRIPT_WRITE = "container-write"

# method names that structurally mutate their receiver: a call
# ``self.<field>.<name>(...)`` records a WRITE site on the field in the
# lockset walk (the v1 "mutating method calls are not writes" bound,
# closed). Compound by nature — every one is read-modify-write on the
# container's internals, so they audit like aug-assigns, not plain
# stores. Names shadowed by project classes don't land here: the walk
# only treats a call as a container mutation when it does NOT resolve
# to a project method.
_MUTATING_METHODS = frozenset(
    (
        "add", "append", "appendleft", "clear", "discard", "extend",
        "extendleft", "insert", "pop", "popitem", "popleft", "remove",
        "setdefault", "sort", "update",
    )
)


def _unwrap_optional(ann: ast.AST) -> ast.AST:
    """``Optional[X]`` / ``X | None`` → ``X`` — the common nullable
    parameter shapes; anything else passes through unchanged."""
    if isinstance(ann, ast.Subscript):
        head = ann.value
        name = getattr(head, "id", None) or getattr(head, "attr", None)
        if name == "Optional":
            inner = ann.slice
            # py<3.9 wraps the slice in ast.Index
            inner = getattr(inner, "value", inner)
            return inner
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        parts = [ann.left, ann.right]
        non_none = [
            p
            for p in parts
            if not (isinstance(p, ast.Constant) and p.value is None)
        ]
        if len(non_none) == 1:
            return non_none[0]
    return ann


@dataclass(frozen=True)
class Role:
    name: str  # root qualname, or "main" for the folded entry surface
    kind: str  # thread | timer | executor | process | convention | entry
    roots: Tuple[str, ...]  # root function qualnames


@dataclass
class FieldDecl:
    cls_qn: str
    name: str
    line: int  # declaration anchor (first assignment / AnnAssign)
    ctx: FileContext
    value_kind: str = "other"  # int | float | container | other
    guarded_by: Optional[str] = None  # annotated lock attr (canonical)
    lockless_why: Optional[str] = None  # field-level annotation text
    lockless_line: Optional[int] = None


@dataclass
class Access:
    cls_qn: str
    fieldname: str
    fn_qn: str
    ctx: FileContext
    line: int
    col: int
    write: bool
    rmw: bool  # aug-assign / check-then-act compound
    held: frozenset  # locks held at the site WITHIN the function
    in_init: bool  # inside the declaring class's __init__


class RaceModel:
    """Roles + field accesses + locksets over one invocation's files."""

    def __init__(self, ctxs: Sequence[FileContext]):
        self.model = ProgramModel(ctxs)
        self.ctxs = list(ctxs)
        self._fn_of_node: Dict[int, str] = {
            id(info.node): qn for qn, info in self.model.functions.items()
        }
        # effective class for nested defs: a ``def run()`` inside a
        # method sees the method's ``self`` — attribute it to the class
        self._eff_cls: Dict[str, Optional[ast.ClassDef]] = {}
        for qn, info in self.model.functions.items():
            self._eff_cls[qn] = self._effective_class(info)
        self._elem_types: Dict[str, Dict[str, str]] = {}
        self._infer_element_types()
        self._extend_attr_types()
        self.fields: Dict[Tuple[str, str], FieldDecl] = {}
        self.class_lockless: Dict[str, Tuple[Optional[str], int]] = {}
        self.class_role_private: Dict[str, Tuple[Optional[str], int]] = {}
        self._lockless_lines: Dict[str, Dict[int, Optional[str]]] = {}
        self._role_private_lines: Dict[str, Dict[int, Optional[str]]] = {}
        for ctx in self.ctxs:
            self._lockless_lines[ctx.path] = _scan_marker(ctx, _LOCKLESS_RE)
            self._role_private_lines[ctx.path] = _scan_marker(
                ctx, _ROLE_PRIVATE_RE
            )
        self._collect_fields()
        self.roles: Dict[str, Role] = {}
        self._discover_roles()
        self.calls: Dict[str, List[Tuple[frozenset, str]]] = {}
        self.accesses: List[Access] = []
        for qn, info in self.model.functions.items():
            self._summarize(qn, info)
        self.role_members: Dict[str, Set[str]] = {
            name: self._closure(role.roots) for name, role in self.roles.items()
        }
        self._roles_of: Dict[str, Set[str]] = {}
        for name, members in self.role_members.items():
            for qn in members:
                self._roles_of.setdefault(qn, set()).add(name)
        self.entry_locks = self._entry_lock_fixpoint()

    # -- class / field tables ------------------------------------------------

    def _effective_class(self, info: FunctionInfo) -> Optional[ast.ClassDef]:
        if info.cls is not None:
            return info.cls
        for anc in info.ctx.ancestors(info.node):
            if isinstance(anc, ast.ClassDef):
                return anc
            if isinstance(anc, ast.Module):
                break
        return None

    def _infer_element_types(self) -> None:
        """attr -> element class for container attrs: ``self._streams =
        {k: _Stream(...)}`` / ``[Cls(...) for ...]`` / ``[Cls(...)]`` /
        ``self.partitions.append(Cls(...))`` (the grow-in-a-loop wiring
        shape, ISSUE 14) — the alazflow queue-element idea generalized
        to any project class, so ``stream.sent`` on a dict-valued local
        resolves. An attr whose initializers/appends name more than one
        class stays untyped (conservative)."""
        for cqn, cinfo in self.model.classes.items():
            mod = self.model.module_of[id(cinfo.ctx)]
            candidates: Dict[str, set] = {}
            for node in ast.walk(cinfo.node):
                if isinstance(node, ast.Call):
                    # self.<attr>.append(Cls(...)) — element type via the
                    # grower call, not the (often empty-[]) initializer
                    f = node.func
                    if (
                        isinstance(f, ast.Attribute)
                        and f.attr == "append"
                        and len(node.args) == 1
                        and isinstance(node.args[0], ast.Call)
                    ):
                        attr = _self_attr(f.value)
                        if attr is not None:
                            t = self.model.resolve_class(mod, node.args[0].func)
                            if t is not None:
                                candidates.setdefault(attr, set()).add(t)
                    continue
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets, v = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, v = [node.target], node.value
                else:
                    continue
                elems: List[ast.AST] = []
                if isinstance(v, ast.Dict):
                    elems = list(v.values)
                elif isinstance(v, ast.List):
                    elems = v.elts
                elif isinstance(v, (ast.ListComp, ast.SetComp)):
                    elems = [v.elt]
                elif isinstance(v, ast.DictComp):
                    elems = [v.value]
                if not elems:
                    continue
                classes = set()
                for e in elems:
                    if isinstance(e, ast.Call):
                        t = self.model.resolve_class(mod, e.func)
                        if t is not None:
                            classes.add(t)
                if not classes:
                    continue
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        candidates.setdefault(attr, set()).update(classes)
                        break
            out = {a: cs.pop() for a, cs in candidates.items() if len(cs) == 1}
            if out:
                self._elem_types[cqn] = out

    def _extend_attr_types(self) -> None:
        """Attr typing the base model can't see, run to a fixpoint
        (types only grow):

        - ``self.x = interner or Interner()`` / ``a if c else b`` —
          branch-resolving through BoolOp/IfExp when exactly one project
          class is nameable;
        - constructor args that are NAMES — a local previously assigned
          ``Cls(...)`` in the calling function, or a typed ``self.attr``
          of the calling class — flow their type into the callee's
          ``self.<attr> = <param>`` stores. This is what lets the
          per-process singletons (Interner, Metrics, recorder/ledger
          planes) that are constructed at wiring time and THREADED
          through constructors join the escape closure;
        - ``self.<attr> = <typed expr>`` stores — the expr typed through
          the same local/param/attr-chain resolver the summaries use
          (``self.graph_store = p0.graph_store`` with ``p0 = self.
          partitions[0]``, ``self.tracer = tracer`` after a
          ``tracer = SpanTracer(...)`` branch): the ISSUE 14 partition
          aliasing shape, without which whole planes (SpanTracer,
          FlightRecorder) fall out of the escape closure.
        """

        def branch_type(mod: str, value: ast.AST) -> Optional[str]:
            if isinstance(value, ast.Call):
                return self.model.resolve_class(mod, value.func)
            kinds: Set[str] = set()
            branches: List[ast.AST] = []
            if isinstance(value, ast.BoolOp):
                branches = value.values
            elif isinstance(value, ast.IfExp):
                branches = [value.body, value.orelse]
            for b in branches:
                t = branch_type(mod, b)
                if t is not None:
                    kinds.add(t)
            return kinds.pop() if len(kinds) == 1 else None

        # pass 0: BoolOp/IfExp direct assignments
        for cqn, cinfo in self.model.classes.items():
            mod = self.model.module_of[id(cinfo.ctx)]
            for node in ast.walk(cinfo.node):
                if not isinstance(node, ast.Assign) or isinstance(
                    node.value, ast.Call
                ):
                    continue
                t = branch_type(mod, node.value)
                if t is None:
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None and attr not in cinfo.attr_types:
                        cinfo.attr_types[attr] = t

        # fixpoint: ctor-arg Name/self.attr typing + typed-expr stores
        # (each round can unlock the next hop of an interner-style
        # threading chain, or the next alias in a partition chain)
        for _ in range(6):
            changed = False
            for ctx in self.ctxs:
                mod = self.model.module_of[id(ctx)]
                for node in ast.walk(ctx.tree):
                    if not isinstance(node, ast.Call):
                        continue
                    target_cls = self.model.resolve_class(mod, node.func)
                    if target_cls is None:
                        continue
                    tinfo = self.model.classes[target_cls]
                    if not tinfo.ctor_param_attrs:
                        continue
                    encl_qn, encl_cls = self._enclosing(ctx, node)
                    bound = list(zip(tinfo.ctor_params, node.args))
                    bound += [
                        (kw.arg, kw.value) for kw in node.keywords if kw.arg
                    ]
                    for pname, arg in bound:
                        attr = tinfo.ctor_param_attrs.get(pname)
                        if attr is None or attr in tinfo.attr_types:
                            continue
                        t = self._expr_type(ctx, mod, encl_qn, encl_cls, arg)
                        if t is not None:
                            tinfo.attr_types[attr] = t
                            changed = True
            for cqn, cinfo in self.model.classes.items():
                mod = self.model.module_of[id(cinfo.ctx)]
                for node in ast.walk(cinfo.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    attr = (
                        _self_attr(node.targets[0])
                        if len(node.targets) == 1
                        else None
                    )
                    if attr is None or attr in cinfo.attr_types:
                        continue
                    t = self._stored_expr_type(cinfo, mod, node)
                    if t is not None:
                        cinfo.attr_types[attr] = t
                        changed = True
            if not changed:
                break

    def _stored_expr_type(self, cinfo, mod: str, node: ast.Assign) -> Optional[str]:
        """Type of the value in a ``self.<attr> = <expr>`` store, via
        the enclosing method's typed locals/params and attr chains."""
        encl_qn, encl_cls = self._enclosing(cinfo.ctx, node)
        if encl_qn is None:
            return None
        info = self.model.functions.get(encl_qn)
        if info is None:
            return None
        local_types = self._local_types(info, mod, encl_cls)

        def rc(base: ast.AST) -> Optional[str]:
            if isinstance(base, ast.Name):
                if base.id == "self" and encl_cls is not None:
                    return f"{mod}:{encl_cls.name}"
                return local_types.get(base.id)
            if isinstance(base, ast.Attribute):
                owner = rc(base.value)
                if owner is not None:
                    oinfo = self.model.classes.get(owner)
                    if oinfo is not None:
                        return oinfo.attr_types.get(base.attr)
            if isinstance(base, ast.Subscript):
                owner = rc(base.value) if not isinstance(
                    base.value, ast.Attribute
                ) else None
                attr = _self_attr(base.value)
                if attr is not None and encl_cls is not None:
                    elem = self._elem_types.get(f"{mod}:{encl_cls.name}", {})
                    return elem.get(attr)
                return owner
            return None

        v = node.value
        if isinstance(v, (ast.Name, ast.Attribute, ast.Subscript)):
            return rc(v)
        return None

    def _expr_type(
        self,
        ctx: FileContext,
        mod: str,
        encl_qn: Optional[str],
        encl_cls: Optional[ast.ClassDef],
        arg: ast.AST,
    ) -> Optional[str]:
        """Project class an argument expression evidently carries, in
        the scope of the function that contains the call site."""
        if isinstance(arg, ast.Call):
            return self.model.resolve_class(mod, arg.func)
        attr = _self_attr(arg)
        if attr is not None and encl_cls is not None:
            cinfo = self.model.classes.get(f"{mod}:{encl_cls.name}")
            if cinfo is not None:
                return cinfo.attr_types.get(attr)
            return None
        if isinstance(arg, ast.Name) and encl_qn is not None:
            info = self.model.functions.get(encl_qn)
            if info is None:
                return None
            for node in walk_shallow(info.node):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                t = node.targets[0]
                if (
                    isinstance(t, ast.Name)
                    and t.id == arg.id
                    and isinstance(node.value, ast.Call)
                ):
                    return self.model.resolve_class(mod, node.value.func)
        return None

    def _collect_fields(self) -> None:
        for cqn, cinfo in self.model.classes.items():
            ctx = cinfo.ctx
            lockless = self._lockless_lines.get(ctx.path, {})
            role_private = self._role_private_lines.get(ctx.path, {})
            # class-level markers: on the class line or a decorator line
            for ln in range(
                min(
                    [cinfo.node.lineno]
                    + [d.lineno for d in cinfo.node.decorator_list]
                ),
                cinfo.node.lineno + 1,
            ):
                if ln in lockless and cqn not in self.class_lockless:
                    self.class_lockless[cqn] = (lockless[ln], ln)
                if ln in role_private and cqn not in self.class_role_private:
                    self.class_role_private[cqn] = (role_private[ln], ln)
            for node in ast.walk(cinfo.node):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                is_ann_cls_level = False
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign):
                    targets, value = [node.target], node.value
                    # dataclass-style field declaration: only DIRECT
                    # class-body children count — an annotated LOCAL in
                    # a method body (ast.walk visits those too) must not
                    # become a phantom field that shadows the real
                    # declaration's annotations (review-caught)
                    is_ann_cls_level = isinstance(
                        node.target, ast.Name
                    ) and node in cinfo.node.body
                else:
                    continue
                for t in targets:
                    name = None
                    attr = _self_attr(t)
                    if attr is not None:
                        name = attr
                    elif is_ann_cls_level:
                        name = t.id  # type: ignore[union-attr]
                    if name is None:
                        continue
                    if attr is not None and cinfo.lock_attrs.get(attr):
                        continue  # locks/conditions are not data fields
                    key = (cqn, name)
                    if key in self.fields:
                        continue  # first declaration anchors
                    decl = FieldDecl(cqn, name, node.lineno, ctx)
                    decl.value_kind = _value_kind(value)
                    end = getattr(node, "end_lineno", None) or node.lineno
                    for ln in range(node.lineno, end + 1):
                        g = ctx.guarded_lines.get(ln)
                        if g is not None:
                            decl.guarded_by = g
                        if ln in lockless:
                            decl.lockless_why = lockless[ln]
                            decl.lockless_line = ln
                    self.fields[key] = decl

    # -- role discovery ------------------------------------------------------

    def _discover_roles(self) -> None:
        entry_roots: List[str] = []
        for qn, info in self.model.functions.items():
            short = qn.split(":", 1)[-1].rsplit(".", 1)[-1]
            if WORKER_NAME_RE.search(short):
                self._add_role(qn, "convention")
            elif ENTRY_NAME_RE.search(short):
                entry_roots.append(qn)
        for ctx in self.ctxs:
            mod = self.model.module_of[id(ctx)]
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                _, name = _callee(node)
                target: Optional[ast.AST] = None
                kind = None
                if name == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target, kind = kw.value, "thread"
                elif name == "Process":
                    # multiprocessing.Process / ctx.Process spawn target
                    # (ISSUE 15): a role in the map — the topology must
                    # show it — but its OWN ADDRESS SPACE: process-kind
                    # roles never pair into shared-memory hazards
                    # (FieldReport.multi_role), because nothing reaches
                    # a spawned child except ring bytes and pickles.
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target, kind = kw.value, "process"
                elif name == "Timer":
                    if len(node.args) > 1:
                        target, kind = node.args[1], "timer"
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "submit"
                    and node.args
                ):
                    target, kind = node.args[0], "executor"
                if target is None:
                    continue
                qn = self._resolve_target(ctx, mod, node, target)
                if qn is not None:
                    self._add_role(qn, kind or "thread")
        if entry_roots:
            self.roles[MAIN_ROLE] = Role(
                MAIN_ROLE, "entry", tuple(sorted(entry_roots))
            )

    def _add_role(self, root_qn: str, kind: str) -> None:
        name = root_qn
        prev = self.roles.get(name)
        if prev is None or prev.kind == "convention":
            self.roles[name] = Role(name, kind, (root_qn,))

    def _resolve_target(
        self, ctx: FileContext, mod: str, site: ast.AST, target: ast.AST
    ) -> Optional[str]:
        """Function qualname a Thread/Timer/submit callable argument
        names, resolved in the spawn site's scope."""
        encl_qn, encl_cls = self._enclosing(ctx, site)
        attr = _self_attr(target)
        if attr is not None and encl_cls is not None:
            cinfo = self.model.classes.get(f"{mod}:{encl_cls.name}")
            if cinfo is not None:
                return cinfo.methods.get(attr)
            return None
        if isinstance(target, ast.Name):
            if encl_qn is not None:
                nested = f"{encl_qn}.{target.id}"
                if nested in self.model.functions:
                    return nested
            direct = f"{mod}:{target.id}"
            if direct in self.model.functions:
                return direct
            imported = self.model.imports.get(mod, {}).get(target.id)
            if imported and imported in self.model.functions:
                return imported
        return None

    def _enclosing(
        self, ctx: FileContext, node: ast.AST
    ) -> Tuple[Optional[str], Optional[ast.ClassDef]]:
        fn_qn = None
        cls = None
        for anc in ctx.ancestors(node):
            if (
                fn_qn is None
                and isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef))
            ):
                fn_qn = self._fn_of_node.get(id(anc))
            if cls is None and isinstance(anc, ast.ClassDef):
                cls = anc
        return fn_qn, cls

    # -- per-function summary ------------------------------------------------

    def _summarize(self, qn: str, info: FunctionInfo) -> None:
        ctx = info.ctx
        mod = self.model.module_of[id(ctx)]
        cls = self._eff_cls.get(qn)
        local_prefix = qn + "."
        calls: List[Tuple[frozenset, str]] = []
        local_types = self._local_types(info, mod, cls)
        in_init = (
            info.cls is not None and info.node.name == "__init__"  # type: ignore[union-attr]
        )

        def field_site(
            cls_qn: str, fname: str, node: ast.AST, write: bool, rmw: bool,
            held: Tuple[str, ...],
        ) -> None:
            if (cls_qn, fname) not in self.fields:
                return
            own_init = in_init and info.cls is not None and (
                f"{mod}:{info.cls.name}" == cls_qn
            )
            self.accesses.append(
                Access(
                    cls_qn, fname, qn, ctx, node.lineno, node.col_offset,
                    write, rmw, frozenset(held), own_init,
                )
            )

        def receiver_class(base: ast.AST) -> Optional[str]:
            """Class of the object a field access / method call hangs
            off: ``self``, a typed local, ``self.<typed attr>``, a typed
            attr of a typed local (``be.breaker``), or an element of a
            typed container attr (``self._streams[k]``)."""
            if isinstance(base, ast.Subscript):
                attr = _self_attr(base.value)
                if attr is not None and cls is not None:
                    elem = self._elem_types.get(f"{mod}:{cls.name}", {})
                    return elem.get(attr)
                return None
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    return f"{mod}:{cls.name}"
                return local_types.get(base.id)
            if isinstance(base, ast.Attribute):
                owner = receiver_class(base.value)
                if owner is not None:
                    oinfo = self.model.classes.get(owner)
                    if oinfo is not None:
                        return oinfo.attr_types.get(base.attr)
            return None

        def resolve_any_call(node: ast.Call) -> Optional[str]:
            """The base resolver, extended with typed-receiver dispatch
            (``svc.stop()`` on a constructed local, ``be.breaker.record()``
            through attr chains) — what lets the main role's wiring code
            reach into the objects it drives."""
            target = self.model.resolve_call(node, mod, cls, local_prefix)
            if target is not None:
                return target
            fn = node.func
            if isinstance(fn, ast.Attribute):
                owner = receiver_class(fn.value)
                if owner is not None:
                    oinfo = self.model.classes.get(owner)
                    if oinfo is not None:
                        return oinfo.methods.get(fn.attr)
            if isinstance(fn, ast.Name):
                # SIBLING nested defs: a worker's helper closures call
                # each other by bare name (``finish`` → ``score_one`` in
                # the scorer loop); resolve up the enclosing FUNCTION
                # chain only — stopping at the class boundary keeps a
                # bare global/builtin call from aliasing a method name
                parts = qn.split(".")
                for i in range(len(parts) - 1, 0, -1):
                    prefix = ".".join(parts[:i])
                    if prefix not in self.model.functions:
                        break
                    cand = f"{prefix}.{fn.id}"
                    if cand in self.model.functions:
                        return cand
            return None

        def callback_targets(node: ast.Call) -> List[str]:
            """Project functions passed AS ARGUMENTS — a callback handed
            to a runner may be invoked by it (``self._consume(q, handle)``
            drives the nested ``handle``; ``on_batch=self._enqueue_window``
            re-enters the service from the merge thread). Conservative
            may-call edges — EXCEPT Thread/Timer/Process/submit targets,
            which run on the SPAWNED thread or process (role roots, not
            calls from the spawner's role — folding a spawn target into
            the spawner would drag a whole child process's code into a
            parent thread's lockset domain)."""
            _, name = _callee(node)
            if name in ("Thread", "Timer", "Process") or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "submit"
            ):
                return []
            out: List[str] = []
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                t = self._resolve_target(ctx, mod, node, arg)
                if t is not None:
                    out.append(t)
            return out

        def check_then_act(node: ast.AST, cls_qn: str, fname: str) -> bool:
            """An enclosing ``if``/``while`` test reads the same field
            with a membership/None test — the dict/list check-then-act
            compound (``if k not in self.cache: self.cache[k] = ...``)."""
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    break
                if not isinstance(anc, (ast.If, ast.While)):
                    continue
                for sub in ast.walk(anc.test):
                    if not isinstance(sub, ast.Attribute) or sub.attr != fname:
                        continue
                    if receiver_class(sub.value) == cls_qn:
                        return True
            return False

        def manual_ops(stmt: ast.AST) -> List[Tuple[int, int, str, str]]:
            """Source-ordered bare ``<lock>.acquire(...)`` /
            ``<lock>.release()`` calls inside ONE statement (shallow —
            nested defs carry their own summaries). Resolving the
            receiver through ``_lock_id_for`` keeps this to known locks:
            a semaphore-ish ``.acquire`` on an untyped object is not a
            lock region."""
            ops: List[Tuple[int, int, str, str]] = []
            stack: List[ast.AST] = [stmt]
            while stack:
                node = stack.pop()
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("acquire", "release")
                ):
                    lock = _lock_id_for(self.model, mod, cls, node.func.value)
                    if lock is not None:
                        ops.append(
                            (node.lineno, node.col_offset, node.func.attr, lock)
                        )
                stack.extend(ast.iter_child_nodes(node))
            ops.sort()
            return ops

        def walk_suite(stmts: Sequence[ast.stmt], held: Tuple[str, ...]) -> None:
            """Walk a statement list in SOURCE ORDER, tracking bare
            ``acquire()``/``release()`` regions: after a statement that
            acquires a known lock (``self._merge_lock.acquire()``, or the
            bounded ``if not lock.acquire(timeout=...): return`` shape),
            the following sibling statements count as holding it until a
            statement releases it — the close-wave merge region
            (acquire-before-try, mutate inside, release-in-finally) reads
            as locked instead of bare (the v1 "only ``with`` blocks
            count" precision bound, closed by ISSUE 19). An acquire
            buried under a non-exiting conditional still marks the tail
            of the suite held — same maybe-held over-approximation a
            conditional ``with`` would get if Python had one."""
            manual: Tuple[str, ...] = ()
            for stmt in stmts:
                walk(stmt, held + manual)
                for _, _, op, lock in manual_ops(stmt):
                    if op == "acquire":
                        if lock not in held and lock not in manual:
                            manual = manual + (lock,)
                    else:
                        manual = tuple(l for l in manual if l != lock)

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return  # nested defs carry their own summaries
            if isinstance(node, (ast.With, ast.AsyncWith)):
                newly: List[str] = []
                for item in node.items:
                    lock = _lock_id_for(self.model, mod, cls, item.context_expr)
                    walk(item.context_expr, held)
                    if lock is not None and lock not in held:
                        newly.append(lock)
                walk_suite(node.body, held + tuple(newly))
                return
            if isinstance(node, ast.Call):
                target = resolve_any_call(node)
                if target is not None and target != qn:
                    calls.append((frozenset(held), target))
                for cb in callback_targets(node):
                    if cb != qn:
                        calls.append((frozenset(held), cb))
                # mutating METHOD calls on a field (``self.d.update(x)``,
                # ``self._q.append(it)``): a structural container write,
                # recorded as a compound site like an aug-assign. Two
                # guards keep it precise: the call must NOT resolve to a
                # project method (``self.store.update()`` on a project
                # class is a call edge, not a dict mutation), and the
                # field must be DECLARED a container (``set()``/``{}``/
                # ``deque()`` init) — ``self._stop.clear()`` on a
                # threading.Event is a thread-safe primitive call that
                # shares these method names.
                fn = node.func
                if (
                    target is None
                    and isinstance(fn, ast.Attribute)
                    and fn.attr in _MUTATING_METHODS
                    and isinstance(fn.value, ast.Attribute)
                ):
                    cls_qn = receiver_class(fn.value.value)
                    decl = (
                        self.fields.get((cls_qn, fn.value.attr))
                        if cls_qn is not None
                        else None
                    )
                    if decl is not None and decl.value_kind == "container":
                        field_site(
                            cls_qn, fn.value.attr, node, True, True, held
                        )
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                rmw = isinstance(node, ast.AugAssign)
                for t in targets:
                    base = t
                    container = False
                    if isinstance(base, ast.Subscript):
                        base = base.value
                        container = True
                    if isinstance(base, ast.Attribute):
                        cls_qn = receiver_class(base.value)
                        if cls_qn is not None:
                            compound = rmw or (
                                container
                                and check_then_act(t, cls_qn, base.attr)
                            )
                            field_site(
                                cls_qn, base.attr, t, True, compound, held
                            )
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                cls_qn = receiver_class(node.value)
                if cls_qn is not None:
                    field_site(cls_qn, node.attr, node, False, False, held)
            # statement-list fields (try/if/for/while bodies, orelse,
            # finalbody, except-handler bodies) recurse through
            # walk_suite so manual acquire regions see suite order;
            # expression children recurse plainly
            for _fname, value in ast.iter_fields(node):
                if isinstance(value, list):
                    if value and isinstance(value[0], ast.stmt):
                        walk_suite(value, held)
                    else:
                        for v in value:
                            if isinstance(v, ast.AST):
                                walk(v, held)
                elif isinstance(value, ast.AST):
                    walk(value, held)

        body = info.node.body if isinstance(info.node.body, list) else [info.node.body]
        walk_suite(body, ())
        self.calls[qn] = calls

    def _local_types(
        self, info: FunctionInfo, mod: str, cls: Optional[ast.ClassDef]
    ) -> Dict[str, str]:
        """Locals with an evident project class: ``x = Cls(...)``,
        ``x = self.<attr>`` (typed attr), ``x = self.<container attr>[k]``
        (element type), ``for x in self.<container>.values()`` — and
        ANNOTATED PARAMETERS (``def _l7_worker(self, part:
        TenantPartition)``): worker entry points handed their state as a
        typed argument (the ISSUE 14 partition shape) must stay visible
        to the escape closure, or every field behind the parameter
        silently leaves the analysis."""
        out: Dict[str, str] = {}
        # closure inheritance: a nested def sees the enclosing
        # function's typed locals (the ``part`` a worker's ``handle``
        # closes over) exactly as ``_eff_cls`` lets it see ``self`` —
        # without this, the whole partition object vanishes from the
        # nested summary's escape closure. Own bindings override.
        encl = None
        for anc in info.ctx.ancestors(info.node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                encl = anc
                break
        if encl is not None:
            encl_qn = self._fn_of_node.get(id(encl))
            einfo = (
                self.model.functions.get(encl_qn) if encl_qn is not None else None
            )
            if einfo is not None:
                out.update(
                    self._local_types(einfo, mod, self._eff_cls.get(encl_qn))
                )
        fnode = info.node
        if isinstance(fnode, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = fnode.args
            for a in (
                list(getattr(args, "posonlyargs", []))
                + list(args.args)
                + list(args.kwonlyargs)
            ):
                ann = a.annotation
                if ann is None:
                    continue
                if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                    # quoted forward reference: parse the name expression
                    try:
                        ann = ast.parse(ann.value, mode="eval").body
                    except SyntaxError:
                        continue
                ann = _unwrap_optional(ann)
                if isinstance(ann, (ast.Name, ast.Attribute)):
                    ty = self.model.resolve_class(mod, ann)
                    if ty is not None:
                        out[a.arg] = ty
        cinfo = (
            self.model.classes.get(f"{mod}:{cls.name}") if cls is not None else None
        )
        elem = self._elem_types.get(cinfo.qualname, {}) if cinfo is not None else {}

        def attr_type(value: ast.AST) -> Optional[str]:
            attr = _self_attr(value)
            if attr is not None and cinfo is not None:
                return cinfo.attr_types.get(attr)
            if isinstance(value, ast.Subscript):
                attr = _self_attr(value.value)
                if attr is not None:
                    return elem.get(attr)
            if isinstance(value, ast.Call):
                f = value.func
                if isinstance(f, ast.Attribute) and f.attr in ("values", "get"):
                    attr = _self_attr(f.value)
                    if attr is not None:
                        return elem.get(attr)
                return self.model.resolve_class(mod, f)
            return None

        def iter_elem_type(it: ast.AST) -> Optional[str]:
            """Element class of an iterable expression:
            ``self._streams.values()``, ``list(...)`` wrappers, ``+``
            concatenation of same-typed iterables, and typed container
            attrs themselves."""
            if isinstance(it, ast.BinOp) and isinstance(it.op, ast.Add):
                left = iter_elem_type(it.left)
                right = iter_elem_type(it.right)
                return left if left == right else None
            if isinstance(it, ast.Call):
                f = it.func
                if getattr(f, "id", None) in ("list", "sorted", "tuple") and it.args:
                    return iter_elem_type(it.args[0])
                if isinstance(f, ast.Attribute) and f.attr == "values":
                    return iter_elem_type(f.value)
                return None
            attr = _self_attr(it)
            if attr is not None:
                return elem.get(attr)
            return None

        for node in walk_shallow(info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    ty = attr_type(node.value)
                    if ty is not None:
                        out[t.id] = ty
            if isinstance(node, (ast.For, ast.AsyncFor)):
                ty = iter_elem_type(node.iter)
                if ty is not None and isinstance(node.target, ast.Name):
                    out[node.target.id] = ty
        return out

    # -- closures ------------------------------------------------------------

    def _closure(self, roots: Sequence[str]) -> Set[str]:
        seen: Set[str] = set(roots)
        work = list(roots)
        while work:
            qn = work.pop()
            for _, target in self.calls.get(qn, ()):
                if target not in seen:
                    seen.add(target)
                    work.append(target)
        return seen

    def roles_of(self, qn: str) -> Set[str]:
        return self._roles_of.get(qn, set())

    def _entry_lock_fixpoint(self) -> Dict[str, frozenset]:
        """Locks ALWAYS held when a function is entered: intersection
        over every resolvable call site of (caller's entry set ∪ locks
        held at the site); role roots and never-called functions seed
        empty — they can be entered cold. Decreasing sets → terminates."""
        universe = frozenset(
            lock for calls in self.calls.values() for held, _ in calls for lock in held
        )
        callers: Dict[str, List[Tuple[str, frozenset]]] = {}
        for qn, calls in self.calls.items():
            for held, target in calls:
                callers.setdefault(target, []).append((qn, held))
        roots: Set[str] = set()
        for role in self.roles.values():
            roots.update(role.roots)
        entry: Dict[str, frozenset] = {}
        for qn in self.model.functions:
            if qn in roots or qn not in callers:
                entry[qn] = frozenset()
            else:
                entry[qn] = universe
        changed = True
        while changed:
            changed = False
            for qn, sites in callers.items():
                if qn in roots:
                    continue
                new = None
                for caller, held in sites:
                    s = entry.get(caller, frozenset()) | held
                    new = s if new is None else (new & s)
                if new is not None and new != entry[qn]:
                    entry[qn] = new
                    changed = True
        return entry

    def lockset(self, acc: Access) -> frozenset:
        return self.entry_locks.get(acc.fn_qn, frozenset()) | acc.held

    def classes_ctx(self, cls_qn: str) -> FileContext:
        return self.model.classes[cls_qn].ctx

    def lockless_sanction(
        self, decl: FieldDecl
    ) -> Optional[Tuple[Optional[str], int]]:
        """(why, line) when the field is sanctioned lockless — its own
        annotation or a class-level one; None otherwise."""
        if decl.lockless_line is not None:
            return decl.lockless_why, decl.lockless_line
        cls_level = self.class_lockless.get(decl.cls_qn)
        if cls_level is not None:
            return cls_level
        return None

    def role_private_sanction(
        self, cls_qn: str
    ) -> Optional[Tuple[Optional[str], int]]:
        """(why, line) when the class claims instance confinement."""
        return self.class_role_private.get(cls_qn)


def _value_kind(value: Optional[ast.AST]) -> str:
    """GIL-atomicity class of a field's declared initial value — what
    ALZ053 audits lockless-ok against."""
    if isinstance(
        value,
        (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp),
    ):
        return "container"
    if isinstance(value, ast.Call):
        _, name = _callee(value)
        if name in ("list", "dict", "set", "defaultdict", "OrderedDict", "deque"):
            return "container"
    if isinstance(value, ast.Constant):
        if isinstance(value.value, bool):
            return "int"
        if isinstance(value.value, int):
            return "int"
        if isinstance(value.value, float):
            return "float"
    if isinstance(value, ast.UnaryOp) and isinstance(value.operand, ast.Constant):
        return _value_kind(value.operand)
    return "other"


def _scan_marker(
    ctx: FileContext, marker_re: re.Pattern
) -> Dict[int, Optional[str]]:
    """line -> justification (None when missing) for every matching
    annotation comment. Token-stream scan like the core's
    disable/guarded-by maps — string literals can't false-positive."""
    out: Dict[int, Optional[str]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(ctx.source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = marker_re.search(tok.string)
            if m:
                out[tok.start[0]] = m.group("why")
    except tokenize.TokenError:
        pass
    return out
