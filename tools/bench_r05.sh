#!/bin/bash
# Round-5 TPU capture: everything VERDICT r4 asked for, runnable the
# moment the tunnel answers. SERIAL (two concurrent benches starve each
# other). Each line lands in BENCH_MODELS_r05.json; a fresh trace lands
# in traces/r05_graphsage.
#
#   bash tools/bench_r05.sh [out.json]
#
# Prereq: `python bench.py --direct --probe-only --watchdog-s 120`
# answers. Every invocation below carries its own watchdog so a
# mid-suite tunnel death costs one row, not the capture.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-BENCH_MODELS_r05.json}"
: > "$OUT"

run() { # run <label> <args...>
  local label="$1"; shift
  echo "== $label: python bench.py --direct --watchdog-s 420 $*" >&2
  local line rc
  # no pipe: $? after a `line=$(... | tail -1)` would be tail's rc
  python bench.py --direct --watchdog-s 420 "$@" \
    >/tmp/bench_r05_out.log 2>/tmp/bench_r05_err.log
  rc=$?
  line=$(tail -1 /tmp/bench_r05_out.log)
  if [ -n "$line" ]; then
    echo "$line" >> "$OUT"
  else
    echo "{\"metric\": \"$label\", \"value\": 0, \"error\": \"empty output rc=$rc\"}" >> "$OUT"
  fi
  tail -2 /tmp/bench_r05_err.log >&2 || true
  date -u +"%Y-%m-%dT%H:%M:%SZ $label done" >&2
}

# headline first — bank the flagship number before anything exploratory
run graphsage
# §3d conclusion 3: is the 9.3ms/step gap per-dispatch overhead (rises
# with K) or device idle (flat)? Default is now K=50; bracket it.
run iters20   --iters 20
run iters100  --iters 100
# §3d conclusion 2: pallas sorted-expand vs in-graph XLA gather at F=128
# (subshell: `VAR=x fn` would leak the var into later runs in bash)
( export ALAZ_EXPAND_DST=xla; run expand-xla )
# per-model rows (BASELINE configs 3/4 evidence)
run gat      --model gat
run experts  --model experts
run tgn      --model tgn
# full-pipeline ingest->score rows/s (VERDICT task 6 target >=1M):
# unbatched, then micro-batched (ARCHITECTURE §3e predicts batch4
# amortizes the ~190ms/dispatch relay overhead and crosses 1M)
run e2e        --e2e
run e2e-batch4 --e2e --e2e-batch 4
# locality study + the banded hybrid's first post-redesign TPU row
# (VERDICT task 4: beat the 27.1M XLA row on the same layout or delete)
run layout-community        --structure community --layout random
run layout-clustered        --structure community --layout clustered
run layout-clustered-banded --structure community --layout clustered --src-gather banded
# fresh traces: §3d confirmation + the GAT byte-gap apportionment (§3c)
mkdir -p traces
run profile     --profile traces/r05_graphsage --iters 5 --repeats 1
run profile-gat --model gat --profile traces/r05_gat --iters 5 --repeats 1

echo "--- $OUT ---"
cat "$OUT"
