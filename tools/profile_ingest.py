#!/usr/bin/env python
"""cProfile harness over the bench.py --ingest workload.

One command to diagnose host-path regressions: runs the exact synthetic
L7 trace the --ingest bench drives (bench.make_ingest_trace → process_l7
→ window close) under cProfile and prints the top-N functions by
cumulative time. No accelerator anywhere in the loop.

Usage: JAX_PLATFORMS=cpu python tools/profile_ingest.py [--rows N] [--top K]
"""

from __future__ import annotations

import argparse
import cProfile
import os
import pstats
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=1 << 18)
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--sort", default="cumulative",
                   choices=["cumulative", "tottime", "ncalls"])
    p.add_argument("--workers", type=int, default=0,
                   help="0 = serial path under cProfile (old behavior); "
                        "N >= 1 drives the sharded pipeline instead — the "
                        "submit/merge threads are summarized with wall, "
                        "merge-share and per-worker stats (cProfile is "
                        "single-thread, so worker internals are profiled "
                        "via the serial mode)")
    args = p.parse_args()

    import time

    from bench import make_ingest_trace
    from alaz_tpu.aggregator.cluster import ClusterInfo
    from alaz_tpu.aggregator.engine import Aggregator
    from alaz_tpu.events.intern import Interner
    from alaz_tpu.graph.builder import WindowedGraphStore

    n_rows = args.rows
    ev, msgs = make_ingest_trace(n_rows, windows=8)
    interner = Interner()
    closed = []
    cluster = ClusterInfo(interner)
    for m in msgs:
        cluster.handle_msg(m)
    chunk = 1 << 16

    if args.workers >= 1:
        from alaz_tpu.aggregator.sharded import ShardedIngest

        pipe = ShardedIngest(
            args.workers, interner=interner, cluster=cluster, window_s=1.0,
            on_batch=closed.append, queue_events=1 << 20,
        )
        t0 = time.perf_counter()
        for i in range(0, n_rows, chunk):
            pipe.process_l7(ev[i : i + chunk], now_ns=10_000_000_000)
        if not pipe.flush(timeout_s=120.0):
            raise RuntimeError("sharded flush timed out; profile invalid")
        dt = time.perf_counter() - t0
        print(
            f"# rows={n_rows} workers={args.workers} "
            f"windows_closed={len(closed)} "
            f"agg_edges={sum(b.n_edges for b in closed)} "
            f"rows_per_s={n_rows/dt:,.0f} wall={dt*1e3:.1f}ms "
            f"merge_share={pipe.merge_s/dt:.3f}"
        )
        for i, store in enumerate(pipe.stores):
            print(
                f"#   shard{i}: rows={store.request_count} "
                f"late_dropped={store.late_dropped}"
            )
        print(f"# engine stats: {pipe.stats.as_dict()}")
        pipe.stop()
        return

    store = WindowedGraphStore(interner, window_s=1.0, on_batch=closed.append)
    agg = Aggregator(store, interner=interner, cluster=cluster)

    def run() -> None:
        for i in range(0, n_rows, chunk):
            agg.process_l7(ev[i : i + chunk], now_ns=10_000_000_000)
        store.flush()

    prof = cProfile.Profile()
    prof.enable()
    run()
    prof.disable()
    print(
        f"# rows={n_rows} windows_closed={len(closed)} "
        f"agg_edges={sum(b.n_edges for b in closed)}"
    )
    pstats.Stats(prof).sort_stats(args.sort).print_stats(args.top)


if __name__ == "__main__":
    main()
