#!/usr/bin/env python
"""cProfile harness over the bench.py --ingest workload.

One command to diagnose host-path regressions: runs the exact synthetic
L7 trace the --ingest bench drives (bench.make_ingest_trace → process_l7
→ window close) under cProfile and prints the top-N functions by
cumulative time. No accelerator anywhere in the loop.

Usage: JAX_PLATFORMS=cpu python tools/profile_ingest.py [--rows N] [--top K]
"""

from __future__ import annotations

import argparse
import cProfile
import os
import pstats
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=1 << 18)
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--sort", default="cumulative",
                   choices=["cumulative", "tottime", "ncalls"])
    args = p.parse_args()

    from bench import make_ingest_trace
    from alaz_tpu.aggregator.cluster import ClusterInfo
    from alaz_tpu.aggregator.engine import Aggregator
    from alaz_tpu.events.intern import Interner
    from alaz_tpu.graph.builder import WindowedGraphStore

    n_rows = args.rows
    ev, msgs = make_ingest_trace(n_rows, windows=8)
    interner = Interner()
    closed = []
    store = WindowedGraphStore(interner, window_s=1.0, on_batch=closed.append)
    cluster = ClusterInfo(interner)
    for m in msgs:
        cluster.handle_msg(m)
    agg = Aggregator(store, interner=interner, cluster=cluster)
    chunk = 1 << 16

    def run() -> None:
        for i in range(0, n_rows, chunk):
            agg.process_l7(ev[i : i + chunk], now_ns=10_000_000_000)
        store.flush()

    prof = cProfile.Profile()
    prof.enable()
    run()
    prof.disable()
    print(
        f"# rows={n_rows} windows_closed={len(closed)} "
        f"agg_edges={sum(b.n_edges for b in closed)}"
    )
    pstats.Stats(prof).sort_stats(args.sort).print_stats(args.top)


if __name__ == "__main__":
    main()
