#!/usr/bin/env python
"""Host/device breakdown of the e2e ingest→score pipe (VERDICT r4 #6).

Same flow as bench.py bench_e2e (REQUEST rows → native windowed ingest →
graph assembly → jit'd scoring) but with per-stage host timers:

  push     alz_push into the SPSC ring + windowed accumulators (C++)
  poll     window close: counting-sort COO + feature export (C++) +
           GraphBatch wrap (python)
  h2d      jnp.asarray of the exported arrays (host→device transfer)
  dispatch jit dispatch of the score fn (async — returns immediately)
  drain    final block_until_ready (device catches up with the host)

On CPU the "device" shares the host, so drain ≈ device compute; on TPU
drain is whatever the device hadn't overlapped. The host stages are
TPU-independent — this is the CPU-side profile the round-4 verdict asked
for. Prints one JSON line.

Usage: JAX_PLATFORMS=cpu python tools/e2e_breakdown.py [--rows N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=1_048_576)
    p.add_argument("--pods", type=int, default=100_000)
    p.add_argument("--svcs", type=int, default=10_000)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--windows", type=int, default=4)
    p.add_argument("--chunk", type=int, default=1 << 16)
    args = p.parse_args()

    import numpy as np

    # honor JAX_PLATFORMS BEFORE any device query: the site plugin
    # force-registers the accelerator backend, and a dead tunnel hangs
    # the first device query of any process that doesn't pin cpu first
    from alaz_tpu.__main__ import _honor_jax_platforms

    _honor_jax_platforms()
    import jax
    import jax.numpy as jnp

    from bench import make_e2e_rows
    from alaz_tpu.config import ModelConfig
    from alaz_tpu.graph import native
    from alaz_tpu.models.registry import get_model

    if not native.available():
        print(json.dumps({"error": "libalaz_ingest.so unavailable"}))
        return

    cfg = ModelConfig(model="graphsage", hidden_dim=args.hidden, num_layers=2)
    init, apply = get_model(cfg.model)
    params = init(jax.random.PRNGKey(0), cfg)
    score = jax.jit(lambda p, g: apply(p, g, cfg)["edge_logits"])

    n_rows = args.rows
    rows = make_e2e_rows(n_rows, args.pods, args.svcs, args.windows)

    def run_once() -> dict:
        t = dict(push=0.0, poll=0.0, h2d=0.0, dispatch=0.0, drain=0.0)
        ni = native.NativeIngest(window_s=1.0, ring_capacity=1 << 21)
        last = None
        scored = 0
        t_all = time.perf_counter()
        for i in range(0, n_rows, args.chunk):
            t0 = time.perf_counter()
            ni.push(rows[i : i + args.chunk])
            t["push"] += time.perf_counter() - t0
            while True:
                t0 = time.perf_counter()
                b = ni.poll()
                t["poll"] += time.perf_counter() - t0
                if b is None:
                    break
                t0 = time.perf_counter()
                g = {k: jnp.asarray(v) for k, v in b.device_arrays().items()}
                t["h2d"] += time.perf_counter() - t0
                t0 = time.perf_counter()
                last = score(params, g)
                t["dispatch"] += time.perf_counter() - t0
                scored += int(last.shape[0])
        for b in ni.flush():
            t0 = time.perf_counter()
            g = {k: jnp.asarray(v) for k, v in b.device_arrays().items()}
            t["h2d"] += time.perf_counter() - t0
            t0 = time.perf_counter()
            last = score(params, g)
            t["dispatch"] += time.perf_counter() - t0
            scored += int(last.shape[0])
        if last is not None:
            t0 = time.perf_counter()
            jax.block_until_ready(last)
            t["drain"] += time.perf_counter() - t0
        ni.close()
        t["wall"] = time.perf_counter() - t_all
        t["scored"] = scored
        return t

    run_once()  # warm compiles for every bucket
    best = min((run_once() for _ in range(3)), key=lambda r: r["wall"])
    host = best["push"] + best["poll"] + best["h2d"] + best["dispatch"]
    out = {
        "metric": "e2e_breakdown_rows_per_sec",
        "value": round(n_rows / best["wall"]),
        "unit": "rows/s",
        "backend": jax.default_backend(),
        "wall_ms": round(best["wall"] * 1e3, 1),
        "host_ms": {
            k: round(best[k] * 1e3, 1)
            for k in ("push", "poll", "h2d", "dispatch")
        },
        "drain_ms": round(best["drain"] * 1e3, 1),
        "host_share": round(host / best["wall"], 3),
        "rows": n_rows,
        "scored": best["scored"],
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
