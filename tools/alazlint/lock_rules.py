"""Lock-discipline rules (ALZ010-ALZ013) for the threaded host pipeline.

The contract is annotation-driven: a field assigned with a trailing
``# guarded-by: self._lock`` comment may only be touched inside a
``with self._lock:`` block in methods of the declaring class
(``__init__`` is exempt — construction happens-before publication).
``threading.Condition(self._lock)`` aliases are resolved, so holding
``self._not_full`` counts as holding ``self._lock`` (the queues.py
pattern). Deferred bodies (nested ``def``/``lambda``) do NOT inherit
the enclosing ``with`` — a gauge lambda registered under a lock still
runs later without it.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Set

from tools.alazlint.core import FileContext, Finding, callee as _callee

_THREADING_CTORS = {
    "Lock": "lock",
    "RLock": "lock",
    "Condition": "condition",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
    "Event": "event",
}

# call shapes that block the calling thread on I/O or time
_BLOCKING_MODULE_CALLS = {
    ("time", "sleep"),
    ("time_module", "sleep"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("requests", "get"),
    ("requests", "post"),
    ("requests", "request"),
}
_BLOCKING_METHOD_NAMES = {
    "recv",
    "recv_into",
    "recvfrom",
    "accept",
    "connect",
    "sendall",
    "makefile",
}


def _self_attr(node: ast.AST) -> Optional[str]:
    """'_lock' for a ``self._lock`` expression, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassModel:
    """Locks, condition aliases and guarded fields of one class."""

    def __init__(self, ctx: FileContext, cls: ast.ClassDef):
        self.cls = cls
        self.kinds: Dict[str, str] = {}  # attr -> lock|condition|event
        self.base_of: Dict[str, str] = {}  # condition attr -> wrapped lock attr
        self.guarded: Dict[str, str] = {}  # field attr -> canonical lock attr
        guard_raw: Dict[str, str] = {}
        for node in ast.walk(cls):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                if isinstance(value, ast.Call):
                    _, name = _callee(value)
                    if name in _THREADING_CTORS:
                        self.kinds[attr] = _THREADING_CTORS[name]
                        if name == "Condition" and value.args:
                            wrapped = _self_attr(value.args[0])
                            if wrapped is not None:
                                self.base_of[attr] = wrapped
                # the guarded-by comment may sit on ANY line of a
                # wrapped (black-style multi-line) assignment — scan the
                # statement's whole span, not just its first line
                end = getattr(node, "end_lineno", None) or node.lineno
                for ln in range(node.lineno, end + 1):
                    lock = ctx.guarded_lines.get(ln)
                    if lock is not None:
                        guard_raw[attr] = lock
                        break
        for field, lock in guard_raw.items():
            self.guarded[field] = self.canon(lock)

    def canon(self, attr: str) -> str:
        return self.base_of.get(attr, attr)

    def is_lockish(self, attr: str) -> bool:
        return self.kinds.get(attr) in ("lock", "condition")


def _blocking_hit(call: ast.Call) -> Optional[str]:
    mod, name = _callee(call)
    if (mod, name) in _BLOCKING_MODULE_CALLS:
        return f"{mod}.{name}()"
    if mod is None and name == "open":
        return "open()"
    if mod is None and name == "sleep":
        return "sleep()"
    if name in _BLOCKING_METHOD_NAMES and isinstance(call.func, ast.Attribute):
        return f".{name}()"
    return None


def _iter_classes(ctx: FileContext) -> Iterable[ast.ClassDef]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            yield node


def _manual_ops(model: _ClassModel, stmt: ast.AST) -> List[tuple]:
    """Source-ordered bare ``self.<lock>.acquire(...)`` / ``.release()``
    calls inside ONE statement (shallow — deferred bodies run later).
    Feeds the suite walk so the bounded-acquire region (acquire before
    ``try``, release in ``finally``) counts as holding the lock."""
    ops: List[tuple] = []
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("acquire", "release")
        ):
            attr = _self_attr(node.func.value)
            if attr is not None and model.is_lockish(attr):
                ops.append(
                    (node.lineno, node.col_offset, node.func.attr, model.canon(attr))
                )
        stack.extend(ast.iter_child_nodes(node))
    ops.sort()
    return ops


def _walk_suite(
    ctx: FileContext,
    model: _ClassModel,
    stmts: Iterable[ast.stmt],
    held: FrozenSet[str],
    in_while: bool,
    findings: List[Finding],
) -> None:
    """Walk a statement list in SOURCE ORDER, tracking manual lock
    regions: after a statement that bare-acquires a known lock (incl.
    the bounded ``if not self._lock.acquire(timeout=...): return``
    shape), subsequent sibling statements count as holding it until a
    statement releases it — the close-wave merge region reads as locked
    instead of tripping ALZ010 on every guarded touch (the `with`-only
    precision bound, closed by ISSUE 19). ALZ012 still flags the bare
    acquire itself; the pairing discipline stays reviewable there."""
    manual: FrozenSet[str] = frozenset()
    for stmt in stmts:
        _walk_method(ctx, model, stmt, held | manual, in_while, findings)
        for _, _, op, lock in _manual_ops(model, stmt):
            if op == "acquire":
                manual = manual | {lock}
            else:
                manual = manual - {lock}


def _walk_method(
    ctx: FileContext,
    model: _ClassModel,
    node: ast.AST,
    held: FrozenSet[str],
    in_while: bool,
    findings: List[Finding],
) -> None:
    if isinstance(node, (ast.With, ast.AsyncWith)):
        newly: Set[str] = set()
        for item in node.items:
            expr = item.context_expr
            attr = _self_attr(expr)
            if attr is not None and model.is_lockish(attr):
                newly.add(model.canon(attr))
            _walk_method(ctx, model, expr, held, in_while, findings)
        _walk_suite(
            ctx, model, node.body, held | frozenset(newly), in_while, findings
        )
        return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        # deferred body: the enclosing `with` will NOT be held at run time
        body = node.body if isinstance(node.body, list) else [node.body]
        _walk_suite(ctx, model, body, frozenset(), False, findings)
        return
    if isinstance(node, ast.While):
        _walk_method(ctx, model, node.test, held, True, findings)
        _walk_suite(ctx, model, node.body + node.orelse, held, True, findings)
        return

    attr = _self_attr(node)
    if attr is not None and attr in model.guarded:
        lock = model.guarded[attr]
        if lock not in held:
            findings.append(
                Finding(
                    "ALZ010",
                    f"`self.{attr}` is declared `# guarded-by: "
                    f"self.{lock}` but is touched without holding it — "
                    f"wrap the access in `with self.{lock}:` (or add a "
                    "justified disable for an intentionally racy read)",
                    ctx.path,
                    node.lineno,
                    node.col_offset,
                )
            )

    if isinstance(node, ast.Call):
        if held:
            hit = _blocking_hit(node)
            if hit:
                findings.append(
                    Finding(
                        "ALZ011",
                        f"blocking call {hit} while holding "
                        f"{'/'.join(sorted(held))} — I/O under a lock "
                        "stalls every thread contending for it; move the "
                        "I/O outside the critical section",
                        ctx.path,
                        node.lineno,
                        node.col_offset,
                    )
                )
        if isinstance(node.func, ast.Attribute):
            obj_attr = _self_attr(node.func.value)
            if node.func.attr == "acquire" and obj_attr is not None and (
                model.is_lockish(obj_attr)
            ):
                findings.append(
                    Finding(
                        "ALZ012",
                        f"bare `self.{obj_attr}.acquire()` — an exception "
                        "before release() deadlocks every waiter; use "
                        f"`with self.{obj_attr}:`",
                        ctx.path,
                        node.lineno,
                        node.col_offset,
                    )
                )
            if (
                node.func.attr == "wait"
                and obj_attr is not None
                and model.kinds.get(obj_attr) == "condition"
                and not in_while
            ):
                findings.append(
                    Finding(
                        "ALZ013",
                        f"`self.{obj_attr}.wait()` outside a `while` "
                        "predicate loop — condition waits can wake "
                        "spuriously (and the predicate can be re-falsified "
                        "before the woken thread runs); re-check in a loop",
                        ctx.path,
                        node.lineno,
                        node.col_offset,
                    )
                )

    # statement-list fields (try/if/for bodies, orelse, finalbody,
    # except-handler bodies) recurse through the suite walk so manual
    # acquire regions see source order; expression children recurse
    # plainly
    for _fname, value in ast.iter_fields(node):
        if isinstance(value, list):
            if value and isinstance(value[0], ast.stmt):
                _walk_suite(ctx, model, value, held, in_while, findings)
            else:
                for v in value:
                    if isinstance(v, ast.AST):
                        _walk_method(ctx, model, v, held, in_while, findings)
        elif isinstance(value, ast.AST):
            _walk_method(ctx, model, value, held, in_while, findings)


def check_lock_discipline(ctx: FileContext) -> Iterable[Finding]:
    """ALZ010-ALZ013, one pass per class."""
    findings: List[Finding] = []
    for cls in _iter_classes(ctx):
        model = _ClassModel(ctx, cls)
        if not model.kinds and not model.guarded:
            continue
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue
            _walk_suite(ctx, model, item.body, frozenset(), False, findings)
    return findings
