"""Worker-thread discipline rules (ALZ030).

The self-healing host plane (ISSUE 6) only works if failures REACH the
supervisor: a worker/merger/consumer loop that swallows an exception
with a bare ``except:`` or an empty broad handler turns a dying shard
into a silently wedged one — exactly the failure class the chaos suite
exists to kill. The rule scopes to functions that NAME themselves
worker loops (``*_loop`` / ``*_worker`` / ``*_main`` / ``_consume``),
where a swallowed exception is a supervision hole rather than a local
style choice.

Legal patterns stay legal: narrow catches with ``pass``/``continue``
(``except socket.timeout: continue`` idle polls, ``except QueueClosed:
pass`` shutdown races) and broad handlers that DO something (log,
count, notify, re-raise) — routing is what the supervisor needs.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tools.alazlint.core import FileContext, Finding

# the thread-body naming convention this repo's worker loops follow
# (service._consume, sharded._worker_loop/_merger_loop/_worker_main,
# ingest_server._accept_loop, ...)
_WORKER_NAME_RE = re.compile(r"(_loop|_worker|_main)$|^_consume$")

_BROAD = {"Exception", "BaseException"}


def _is_worker_fn(name: str) -> bool:
    return bool(_WORKER_NAME_RE.search(name))


def _exc_names(node: ast.AST) -> Iterable[str]:
    """Exception type names a handler catches (tuple-aware)."""
    if node is None:
        return
    targets = node.elts if isinstance(node, ast.Tuple) else [node]
    for t in targets:
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, ast.Attribute):
            yield t.attr


def _swallows(body) -> bool:
    """True when the handler body routes NOTHING: only pass/continue/
    break/constant expressions — no call, raise, assignment, return or
    control construct that could inform a supervisor."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # a bare docstring/ellipsis
        return False
    return True


def check_alz030(ctx: FileContext) -> Iterable[Finding]:
    """ALZ030: bare/broad except swallowed inside a worker-loop body."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.FunctionDef) or not _is_worker_fn(node.name):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.ExceptHandler):
                continue
            if sub.type is None:
                yield Finding(
                    "ALZ030",
                    f"bare `except:` in worker loop `{node.name}` — it "
                    "absorbs even injected crashes; catch something "
                    "specific or route the failure to the supervisor",
                    ctx.path,
                    sub.lineno,
                    sub.col_offset,
                )
                continue
            caught = set(_exc_names(sub.type))
            if caught & _BROAD and _swallows(sub.body):
                broad = "/".join(sorted(caught & _BROAD))
                yield Finding(
                    "ALZ030",
                    f"`except {broad}` swallowed in worker loop "
                    f"`{node.name}` — a dying iteration vanishes; log, "
                    "count, notify or re-raise so the supervisor sees it",
                    ctx.path,
                    sub.lineno,
                    sub.col_offset,
                )
