"""Rule registry: stable code -> (summary, checker).

Codes are append-only — a retired rule's code is never reused, so
``# alazlint: disable=`` comments and CI grep lines stay meaningful
across versions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Iterable

from tools.alazlint import jax_rules, lock_rules, program, thread_rules
from tools.alazlint.core import FileContext, Finding


def _alz024(ctx: FileContext) -> Iterable[Finding]:
    # Lazy on purpose: axisrules imports tools.alazlint.core, whose
    # package __init__ imports THIS module — a module-level reference
    # would crash any consumer that imports axisrules first (the
    # still-initializing module has no check_alz024 attribute yet).
    from tools.alazspec.axisrules import check_alz024

    return check_alz024(ctx)


@dataclass(frozen=True)
class Rule:
    code: str
    summary: str
    check: Callable[[FileContext], Iterable[Finding]]


_ALL = [
    Rule(
        "ALZ001",
        "host-device sync (.item()/float()/np.asarray()) on a traced value "
        "inside a jit/vmap scope",
        jax_rules.check_alz001,
    ),
    Rule(
        "ALZ002",
        "Python if/while branching on a traced value inside a jit/vmap scope",
        jax_rules.check_alz002,
    ),
    Rule(
        "ALZ003",
        "non-literal / unhashable static_argnums-static_argnames spec",
        jax_rules.check_alz003,
    ),
    Rule(
        "ALZ004",
        "un-dtyped f32-defaulting jnp constructor next to a polymorphic "
        "compute dtype (silent bf16 promotion)",
        jax_rules.check_alz004,
    ),
    Rule(
        "ALZ005",
        "blocking device sync inside a stage_* function (async staging "
        "contract)",
        jax_rules.check_alz005,
    ),
    Rule(
        "ALZ010",
        "# guarded-by field touched outside `with <lock>:`",
        lock_rules.check_lock_discipline,
    ),
    Rule(
        "ALZ011",
        "blocking I/O while holding a lock",
        lambda ctx: (),  # emitted by the ALZ010 walk; registered for --list-rules
    ),
    Rule(
        "ALZ012",
        "bare lock.acquire() instead of `with`",
        lambda ctx: (),
    ),
    Rule(
        "ALZ013",
        "condition .wait() not re-checked in a while loop",
        lambda ctx: (),
    ),
    Rule(
        "ALZ000",
        "alazlint disable comment without a justification",
        lambda ctx: (),  # emitted by the core suppression pass
    ),
    Rule(
        "ALZ900",
        "file does not parse",
        lambda ctx: (),  # emitted by the core driver
    ),
    # -- alazspec family (tools/alazspec): cross-layer ABI/schema/contract
    # drift. ALZ020-ALZ023 are emitted by the alazspec driver (`python -m
    # tools.alazspec`, `make abi-check`) because they read C sources,
    # golden JSON, and live numpy dtypes — not a single Python AST; they
    # are registered here so codes stay append-only, `--list-rules` shows
    # the whole catalog, and disable comments parse uniformly. ALZ024 is
    # a real per-file AST rule and runs in this driver.
    Rule(
        "ALZ020",
        "AlzRecord C struct drifted from NATIVE_RECORD_DTYPE "
        "(offsets/sizes/constants) or libalaz_ingest.so is stale",
        lambda ctx: (),  # emitted by tools.alazspec.abirules
    ),
    Rule(
        "ALZ021",
        "wire frame/event-schema layout drifted from the golden table "
        "(resources/specs/wire_layouts.json)",
        lambda ctx: (),  # emitted by tools.alazspec.abirules
    ),
    Rule(
        "ALZ022",
        "protocol/method enum parity broken (C enum vs Python enums, "
        "method strings, uint8 range, model edge-type axis)",
        lambda ctx: (),  # emitted by tools.alazspec.abirules
    ),
    Rule(
        "ALZ023",
        "model shape/dtype/sharding contract drifted from its golden "
        "specfile (resources/specs/, `make specs`)",
        lambda ctx: (),  # emitted by tools.alazspec.specfiles
    ),
    Rule(
        "ALZ024",
        "spec hygiene: PartitionSpec/collective axis name outside the "
        "project mesh, or float64 requested inside a traced scope",
        _alz024,
    ),
    Rule(
        "ALZ030",
        "bare/broad except swallowed inside a worker-loop body "
        "(failures must route to the supervisor, not pass)",
        thread_rules.check_alz030,
    ),
    # -- alazflow family (tools/alazflow): whole-program row-conservation
    # + blocking-discipline dataflow. Emitted by the alazflow driver
    # (`python -m tools.alazflow`, `make flow`) — the passes need the
    # full project model plus golden JSON artifacts, not a single file —
    # and registered here so codes stay append-only, `--list-rules`
    # shows the whole catalog, and disable comments parse uniformly.
    Rule(
        "ALZ040",
        "row-bearing data discarded (mask filter / truncating slice) "
        "with no call-graph path to DropLedger.add",
        lambda ctx: (),  # emitted by tools.alazflow.droprules
    ),
    Rule(
        "ALZ041",
        "drop-cause vocabulary broken: off-CAUSES literal, or CAUSES "
        "drifted from the wire table / metric registry",
        lambda ctx: (),  # emitted by tools.alazflow.vocabrules
    ),
    Rule(
        "ALZ042",
        "unbounded blocking (queue put/get, join, acquire, condition "
        "wait without timeout) reachable from the ingest/flush/close "
        "entry surface",
        lambda ctx: (),  # emitted by tools.alazflow.blockrules
    ),
    Rule(
        "ALZ043",
        "exception edge abandons in-flight rows (handler neither "
        "ledgers, re-raises, nor returns them)",
        lambda ctx: (),  # emitted by tools.alazflow.droprules
    ),
    Rule(
        "ALZ044",
        "metric name outside the golden registry "
        "(resources/specs/metrics.json; --write-metrics regenerates)",
        lambda ctx: (),  # emitted by tools.alazflow.vocabrules
    ),
    # -- alazrace family (tools/alazrace): whole-program thread-escape +
    # lockset race detection. Emitted by the alazrace driver (`python -m
    # tools.alazrace`, `make race`) — the passes need thread-role
    # discovery and call-graph lockset fixpoints over the full project
    # model, plus the golden concurrency map — and registered here so
    # codes stay append-only, `--list-rules` shows the whole catalog,
    # and disable comments parse uniformly.
    Rule(
        "ALZ050",
        "unsynchronized shared write: a multi-role-reachable field "
        "written with no lock common to its access sites",
        lambda ctx: (),  # emitted by tools.alazrace.racerules
    ),
    Rule(
        "ALZ051",
        "compound read-modify-write (aug-assign / check-then-act) on a "
        "multi-role field outside any common lock",
        lambda ctx: (),  # emitted by tools.alazrace.racerules
    ),
    Rule(
        "ALZ052",
        "shared field consistently guarded by one lock but missing its "
        "# guarded-by annotation (ALZ010 coverage closure)",
        lambda ctx: (),  # emitted by tools.alazrace.racerules
    ),
    Rule(
        "ALZ053",
        "# lockless-ok / # role-private audit: missing justification, "
        "or a sanction covering a non-GIL-atomic access shape",
        lambda ctx: (),  # emitted by tools.alazrace.racerules
    ),
    Rule(
        "ALZ054",
        "thread topology drifted from the golden concurrency map "
        "(resources/specs/threads.json; --write-threads regenerates)",
        lambda ctx: (),  # emitted by tools.alazrace.goldenmap
    ),
    # -- alaznat family (tools/alaznat): native-layer safety — the sixth
    # head. The static half lints alaz_tpu/native/*.cc (offset/magic
    # provenance, GIL discipline, golden offset map); the dynamic half
    # replays the fuzz corpus under ASan/UBSan builds (`make
    # sanitize-native`). C++ sources carry the same disable comment as
    # Python (`// alazlint: disable=CODE -- why`); registered here so
    # codes stay append-only and the catalog stays whole.
    Rule(
        "ALZ060",
        "native magic number not derivable from a pinned layout, a "
        "struct drifted from its wire-table layout, or a pinned "
        "constant drifted from its Python provenance",
        lambda ctx: (),  # emitted by tools.alaznat.natrules/natgolden
    ),
    Rule(
        "ALZ061",
        "CPython API reachable in GIL-dropped native code (ctypes "
        "releases the GIL around every export)",
        lambda ctx: (),  # emitted by tools.alaznat.natrules
    ),
    Rule(
        "ALZ062",
        "native offset map drifted from the golden "
        "(resources/specs/nat_offsets.json; --write-offsets regenerates)",
        lambda ctx: (),  # emitted by tools.alaznat.natgolden
    ),
    Rule(
        "ALZ063",
        "sanitizer fuzz finding: ASan/UBSan report or native-vs-python "
        "parity divergence on a corpus case (make sanitize-native)",
        lambda ctx: (),  # emitted by tools.alaznat.fuzz
    ),
    # -- alazjit family (tools/alazjit): device-plane static analysis —
    # the seventh head. Discovers the whole jit surface (every jit /
    # vmap / pmap / shard_map construction reachable from the entry
    # surface), pins it to resources/specs/jit_surface.json, and lints
    # retrace / host-sync / dtype hazards interprocedurally over the
    # traced closure — the whole-program complement of the per-file
    # ALZ002/004/005/006/024 checks. Registered here so codes stay
    # append-only and disable comments parse uniformly.
    Rule(
        "ALZ070",
        "whole-program retrace hazard: uncached jit construction in a "
        "method body, an uncached maker re-invoked per loop iteration "
        "(syntactic or via the reachable call graph), or a shape-valued "
        "scalar flowing into a static jit argument",
        lambda ctx: (),  # emitted by tools.alazjit.jitrules
    ),
    Rule(
        "ALZ071",
        "Python control flow on a device value inside a helper reached "
        "from a traced fn (interprocedural ConcretizationTypeError)",
        lambda ctx: (),  # emitted by tools.alazjit.jitrules
    ),
    Rule(
        "ALZ072",
        "host-sync discipline: hard sync in a helper reachable from "
        "staging, or a readback / implicit __bool__ between dispatch "
        "and finish in a dispatch-loop driver (§3n)",
        lambda ctx: (),  # emitted by tools.alazjit.jitrules
    ),
    Rule(
        "ALZ073",
        "dtype discipline in the traced closure: numpy float64-default "
        "constructor, or an f64 spelling (incl. bare `float`) a "
        "per-file rule cannot see",
        lambda ctx: (),  # emitted by tools.alazjit.jitrules
    ),
    Rule(
        "ALZ074",
        "jit surface drifted from the golden spec, or a retrace-budget "
        "key no longer names a discovered traced fn "
        "(resources/specs/jit_surface.json; --write-surface regenerates)",
        lambda ctx: (),  # emitted by tools.alazjit.jitgolden
    ),
]

RULES: Dict[str, Rule] = {r.code: r for r in _ALL}

# whole-program rules: checked over EVERY file of a lint invocation at
# once (``check`` takes the full FileContext list) — the interprocedural
# half of the gate (tools/alazlint/program.py)
_PROGRAM = [
    Rule(
        "ALZ006",
        "retrace risk: jit built in a loop / on a fresh lambda per call / "
        "called with type-varying Python literals",
        program.check_alz006,
    ),
    Rule(
        "ALZ014",
        "lock-order cycle reachable through the call graph "
        "(interprocedural deadlock)",
        program.check_alz014,
    ),
]

PROGRAM_RULES: Dict[str, Rule] = {r.code: r for r in _PROGRAM}
