"""alazlint core: file model, findings, disable comments, driver.

The engine is deliberately small: rules are plain functions
``rule(ctx) -> Iterable[Finding]`` registered in ``rules.RULES``; the
core owns parsing, comment handling (``# guarded-by`` declarations and
``# alazlint: disable=`` suppressions are both comments, invisible to
``ast``), suppression filtering, and output.
"""

from __future__ import annotations

import ast
import io
import json
import re
import sys
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

# ``# alazlint: disable=ALZ010 -- why this is safe``
_DISABLE_RE = re.compile(
    r"#\s*alazlint:\s*disable=(?P<codes>ALZ\d{3}(?:\s*,\s*ALZ\d{3})*)"
    r"(?:\s*--\s*(?P<why>\S.*))?"
)
# ``# guarded-by: self._lock``
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*self\.(?P<lock>\w+)")


@dataclass(frozen=True)
class Finding:
    code: str
    message: str
    path: str
    line: int
    col: int

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

    def as_json(self) -> dict:
        return {
            "code": self.code,
            "message": self.message,
            "path": self.path,
            "line": self.line,
            "col": self.col,
        }


@dataclass
class FileContext:
    """Everything a rule needs about one file."""

    path: str
    source: str
    tree: ast.AST
    # line -> set of suppressed codes
    disables: Dict[int, set] = field(default_factory=dict)
    # line -> lock name from a ``# guarded-by: self.<lock>`` comment
    guarded_lines: Dict[int, str] = field(default_factory=dict)
    # lines of bare disables (missing the required justification)
    bare_disables: List[Tuple[int, int]] = field(default_factory=list)

    def __post_init__(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._alz_parent = node  # type: ignore[attr-defined]

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_alz_parent", None)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)


def _scan_comments(ctx: FileContext) -> None:
    """Populate disables / guarded-by maps from the token stream."""
    try:
        tokens = tokenize.generate_tokens(io.StringIO(ctx.source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            m = _DISABLE_RE.search(tok.string)
            if m:
                codes = {c.strip() for c in m.group("codes").split(",")}
                ctx.disables.setdefault(line, set()).update(codes)
                if not m.group("why"):
                    ctx.bare_disables.append((line, tok.start[1]))
            g = _GUARDED_RE.search(tok.string)
            if g:
                ctx.guarded_lines[line] = g.group("lock")
    except tokenize.TokenError:
        pass  # the parse-error finding covers truly broken files


def _expand_disables_over_statements(ctx: FileContext) -> None:
    """A disable comment anywhere on a wrapped (multi-line) SIMPLE
    statement suppresses findings on every line of that statement — the
    comment can only physically sit on one line, usually the last, while
    findings anchor at inner node linenos. Compound statements (``with``,
    ``if``, ``def`` — anything with a body) are deliberately NOT
    expanded: their span covers the whole suite and a trailing disable
    would silently blanket-suppress the block."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.stmt) or hasattr(node, "body"):
            continue
        end = getattr(node, "end_lineno", None) or node.lineno
        if end == node.lineno:
            continue
        span = range(node.lineno, end + 1)
        codes: set = set()
        for ln in span:
            codes |= ctx.disables.get(ln, set())
        if codes:
            for ln in span:
                ctx.disables.setdefault(ln, set()).update(codes)


def parse_context(path: str, source: str) -> "FileContext | Finding":
    """Parse one file into a FileContext (comments scanned, disables
    expanded), or the ALZ900 Finding when it doesn't parse."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return Finding(
            "ALZ900",
            f"file does not parse: {exc.msg}",
            path,
            exc.lineno or 1,
            (exc.offset or 1) - 1,
        )
    ctx = FileContext(path=path, source=source, tree=tree)
    _scan_comments(ctx)
    _expand_disables_over_statements(ctx)
    return ctx


def _file_findings(ctx: FileContext) -> List[Finding]:
    """Per-file rules + suppression filtering + ALZ000 for one context."""
    from tools.alazlint.rules import RULES

    raw: List[Finding] = []
    for rule in RULES.values():
        raw.extend(rule.check(ctx))

    out: List[Finding] = []
    for f in raw:
        suppressed = f.code in ctx.disables.get(f.line, set())
        if not suppressed:
            out.append(f)
    for line, col in ctx.bare_disables:
        out.append(
            Finding(
                "ALZ000",
                "disable comment is missing its justification "
                "(write `# alazlint: disable=ALZxxx -- <why this is safe>`)",
                ctx.path,
                line,
                col,
            )
        )
    return out


def _program_findings(ctxs: List[FileContext]) -> List[Finding]:
    """Whole-program rules over every parsed file of the invocation,
    with each file's disable comments still honored."""
    from tools.alazlint.rules import PROGRAM_RULES

    by_path = {ctx.path: ctx for ctx in ctxs}
    out: List[Finding] = []
    for rule in PROGRAM_RULES.values():
        for f in rule.check(ctxs):
            ctx = by_path.get(f.path)
            if ctx is not None and f.code in ctx.disables.get(f.line, set()):
                continue
            out.append(f)
    return out


def lint_source(path: str, source: str) -> List[Finding]:
    """Lint one file's source; returns surviving findings (suppressions
    applied, bare suppressions reported as ALZ000). Whole-program rules
    run too, scoped to this single file."""
    ctx = parse_context(path, source)
    if isinstance(ctx, Finding):
        return [ctx]
    out = _file_findings(ctx) + _program_findings([ctx])
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def callee(call: ast.Call) -> "Tuple[Optional[str], Optional[str]]":
    """(module-ish prefix, attr/name) for a call: ``np.asarray`` →
    ("np", "asarray"), ``float`` → (None, "float"), ``x.y.item`` →
    ("<expr>", "item"). Shared by both rule families."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return None, fn.id
    if isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Name):
            return fn.value.id, fn.attr
        return "<expr>", fn.attr
    return None, None


def parse_files(
    paths: Iterable[str],
) -> "Tuple[List[FileContext], List[Finding]]":
    """Parse every .py under ``paths`` into FileContexts; unreadable or
    unparsable files become ALZ900 findings instead of aborting the run.
    Shared by the whole-program driver heads (alazflow, alazrace)."""
    ctxs: List[FileContext] = []
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        try:
            source = f.read_text()
        except (UnicodeDecodeError, OSError) as exc:
            findings.append(
                Finding("ALZ900", f"file is not readable: {exc}", str(f), 1, 0)
            )
            continue
        ctx = parse_context(str(f), source)
        if isinstance(ctx, Finding):
            findings.append(ctx)
            continue
        ctxs.append(ctx)
    return ctxs, findings


def filter_disables(
    findings: Iterable[Finding], ctxs: Iterable[FileContext]
) -> List[Finding]:
    """Drop findings a ``# alazlint: disable=`` comment suppresses and
    return the survivors in the canonical (path, line, col, code) order
    — the shared epilogue of every whole-program driver head."""
    by_path = {ctx.path: ctx for ctx in ctxs}
    out: List[Finding] = []
    for f in findings:
        ctx = by_path.get(f.path)
        if ctx is not None and f.code in ctx.disables.get(f.line, set()):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def iter_py_files(paths: Iterable[str]) -> Iterable[Path]:
    for p in paths:
        path = Path(p)
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            yield path


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    ctxs: List[FileContext] = []
    for f in iter_py_files(paths):
        try:
            source = f.read_text()
        except (UnicodeDecodeError, OSError) as exc:
            # one undecodable file must not abort the whole run — report
            # it through the same channel as a parse failure
            findings.append(
                Finding("ALZ900", f"file is not readable: {exc}", str(f), 1, 0)
            )
            continue
        ctx = parse_context(str(f), source)
        if isinstance(ctx, Finding):
            findings.append(ctx)
            continue
        ctxs.append(ctx)
        findings.extend(_file_findings(ctx))
    # the whole-program pass sees every file of the invocation at once —
    # this is what lets ALZ014 chase a lock order across modules
    findings.extend(_program_findings(ctxs))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    from tools.alazlint.rules import RULES

    from tools.alazlint.rules import PROGRAM_RULES

    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if "--list-rules" in argv:
        for code, rule in sorted({**RULES, **PROGRAM_RULES}.items()):
            print(f"{code}  {rule.summary}")
        return 0
    if not argv:
        print("usage: python -m tools.alazlint <paths...> [--json] [--list-rules]")
        return 2
    findings = lint_paths(argv)
    if as_json:
        print(
            json.dumps(
                {
                    "findings": [f.as_json() for f in findings],
                    "count": len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        print(f"alazlint: {len(findings)} finding(s)")
    return 1 if findings else 0
