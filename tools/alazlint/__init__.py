"""alazlint — project-specific static analysis for the alaz_tpu codebase.

Two rule families, both tuned to the failure modes this repo actually
has (stdlib ``ast`` only, no third-party deps):

**JAX hygiene** (ALZ001-ALZ005) — host-device sync and tracer misuse
inside jit/vmap/shard_map-traced functions, non-hashable static
arguments, silent f32 promotion next to a bf16 compute dtype, and
blocking sync calls inside the async staging path.

**Lock discipline** (ALZ010-ALZ013) — the ``# guarded-by: self._lock``
annotation contract for the threaded host pipeline, blocking I/O while
holding a lock, bare ``acquire()`` outside try/finally, and condition
waits not re-checked in a loop.

Run as ``python -m tools.alazlint <paths> [--json]``; exit code 1 when
findings exist. Suppress a single finding with an inline comment::

    x = self._items  # alazlint: disable=ALZ010 -- racy gauge read is fine

The justification text after ``--`` is REQUIRED: a bare disable is
itself reported (ALZ000).
"""

from tools.alazlint.core import Finding, lint_paths, lint_source  # noqa: F401
from tools.alazlint.rules import RULES  # noqa: F401
