import sys

from tools.alazlint.core import main

if __name__ == "__main__":
    sys.exit(main())
