"""Whole-program rules (ALZ006, ALZ014) — the interprocedural half of
alazsan.

Unlike the per-file rules, these run over *every* FileContext of a lint
invocation at once, on top of a light project model:

- a **function index** keyed by qualified name (``module:func``,
  ``module:Class.method``, ``module:func.<nested>``),
- an **import map** per module (``import alaz_tpu.utils.queues as q`` /
  ``from alaz_tpu.utils.queues import BatchQueue``),
- **attribute-type inference** from ``self.x = ClassName(...)``
  assignments, so ``self.window_queue.put(...)`` resolves to
  ``BatchQueue.put`` across modules.

ALZ014 builds per-function lock summaries (locks acquired directly, and
calls made while holding locks), closes them over the call graph to a
fixpoint, and then looks for cycles in the resulting lock-order graph:
function A taking lock₁ then reaching (through any call chain) an
acquisition of lock₂, while function B orders them the other way, is a
deadlock that no single function's body reveals — exactly what PR 2's
intra-function ALZ010 family cannot see.

ALZ006 is the static half of the retrace budget: ``jax.jit`` applied
inside a loop or to a fresh lambda per call builds a new trace cache per
iteration/call, and a jit'd entry point whose call sites pass different
Python literal *types* at one position compiles once per type. All three
shapes are invisible at runtime until the compile log fills up.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import PurePath
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.alazlint.core import FileContext, Finding, callee as _callee
from tools.alazlint.jax_rules import _call_transform_name

_LOCKISH_CTORS = {"Lock": "lock", "RLock": "lock", "Condition": "condition"}
# enclosing decorators that make a per-call jit construction legal: the
# maker runs once per distinct key, not once per call
_CACHING_DECORATORS = {"lru_cache", "cache", "cached_property"}


def module_name(path: str) -> str:
    """Dotted module name for cross-file resolution. Rooted at the
    project packages when present (``.../alaz_tpu/utils/queues.py`` →
    ``alaz_tpu.utils.queues``); bare stem otherwise (fixtures)."""
    parts = list(PurePath(path).parts)
    stem_parts = parts[:-1] + [PurePath(path).stem]
    for root in ("alaz_tpu", "tools"):
        if root in stem_parts[:-1] or stem_parts[-1] == root:
            idx = stem_parts.index(root)
            mod = stem_parts[idx:]
            if mod[-1] == "__init__":
                mod = mod[:-1]
            return ".".join(mod)
    return stem_parts[-1]


# ---------------------------------------------------------------------------
# Project model
# ---------------------------------------------------------------------------


@dataclass
class FunctionInfo:
    qualname: str  # module:Class.method / module:func / module:func.<n>
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    ctx: FileContext
    cls: Optional[ast.ClassDef] = None


@dataclass
class ClassInfo:
    qualname: str  # module:Class
    node: ast.ClassDef
    ctx: FileContext
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> kind
    cond_base: Dict[str, str] = field(default_factory=dict)  # cond attr -> lock attr
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> class qualname
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fn qualname
    # __init__ positional params (after self), and the subset stored
    # verbatim into self attrs (``self._lk = lk``) — the hooks the
    # constructor-arg lock inference (ISSUE 4 satellite) resolves through
    ctor_params: List[str] = field(default_factory=list)
    ctor_param_attrs: Dict[str, str] = field(default_factory=dict)  # param -> attr


class ProgramModel:
    """Indexes + import maps over one lint invocation's files."""

    def __init__(self, ctxs: Sequence[FileContext]):
        self.ctxs = list(ctxs)
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        # per module: local name -> fully dotted target ("module" or
        # "module:Class" or "module:func")
        self.imports: Dict[str, Dict[str, str]] = {}
        self.module_of: Dict[int, str] = {}
        for ctx in self.ctxs:
            self._index_file(ctx)
        # attr types need the class index complete first
        for info in self.classes.values():
            self._infer_attr_types(info)
        # ...and ctor-arg lock inference needs attr types + every call
        # site, so it runs last (ISSUE 4 satellite: `self._lk = lk` where
        # the constructor is called with a lock)
        self._infer_ctor_locks()

    # -- indexing -----------------------------------------------------------

    def _index_file(self, ctx: FileContext) -> None:
        mod = module_name(ctx.path)
        self.module_of[id(ctx)] = mod
        imports: Dict[str, str] = {}
        self.imports[mod] = imports
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name if alias.asname else alias.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for alias in node.names:
                    imports[alias.asname or alias.name] = (
                        f"{node.module}:{alias.name}"
                    )

        def walk_scope(body, prefix: str, cls: Optional[ast.ClassDef]):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}{stmt.name}"
                    self.functions[qn] = FunctionInfo(qn, stmt, ctx, cls)
                    walk_scope(stmt.body, qn + ".", None)
                elif isinstance(stmt, ast.ClassDef) and cls is None:
                    cqn = f"{prefix}{stmt.name}"
                    cinfo = ClassInfo(cqn, stmt, ctx)
                    self.classes[cqn] = cinfo
                    for item in stmt.body:
                        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            mqn = f"{cqn}.{item.name}"
                            cinfo.methods[item.name] = mqn
                            self.functions[mqn] = FunctionInfo(mqn, item, ctx, stmt)
                            walk_scope(item.body, mqn + ".", None)
                    self._collect_locks(cinfo)

        walk_scope(ctx.tree.body, f"{mod}:", None)

    def _collect_locks(self, cinfo: ClassInfo) -> None:
        for item in cinfo.node.body:
            if (
                isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                and item.name == "__init__"
            ):
                args = item.args
                cinfo.ctor_params = [
                    a.arg for a in (args.posonlyargs + args.args)[1:]
                ]
                for sub in ast.walk(item):
                    if not isinstance(sub, ast.Assign):
                        continue
                    if not (
                        isinstance(sub.value, ast.Name)
                        and sub.value.id in cinfo.ctor_params
                    ):
                        continue
                    for t in sub.targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            cinfo.ctor_param_attrs[sub.value.id] = attr
        for node in ast.walk(cinfo.node):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if not isinstance(value, ast.Call):
                continue
            _, name = _callee(value)
            if name not in _LOCKISH_CTORS:
                continue
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                cinfo.lock_attrs[attr] = _LOCKISH_CTORS[name]
                if name == "Condition" and value.args:
                    wrapped = _self_attr(value.args[0])
                    if wrapped is not None:
                        cinfo.cond_base[attr] = wrapped

    def _infer_attr_types(self, cinfo: ClassInfo) -> None:
        mod = self.module_of[id(cinfo.ctx)]
        for node in ast.walk(cinfo.node):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            target_cls = self.resolve_class(mod, node.value.func)
            if target_cls is None:
                continue
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    cinfo.attr_types[attr] = target_cls

    def _infer_ctor_locks(self) -> None:
        """Resolve ``self.<attr> = <param>`` constructor-stored params
        through their construction sites (ROADMAP follow-up — before
        this pass only ``self.x = Cls(...)`` literals resolved, so
        anything injected through a constructor was invisible to the
        ALZ014 cycle search):

        - the attr becomes a LOCK when any resolvable site passes a
          fresh ``threading.Lock()``/``RLock()``/``Condition()``, the
          calling class's own lock attr, or a module-global lock;
        - the attr gets a TYPE when a site passes ``self`` (the calling
          class) or a constructor call of a project class, so method
          calls through the stored object keep resolving."""
        for ctx in self.ctxs:
            mod = self.module_of[id(ctx)]
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                target_cls = self.resolve_class(mod, node.func)
                if target_cls is None:
                    continue
                cinfo = self.classes[target_cls]
                if not cinfo.ctor_param_attrs:
                    continue
                bound: List[Tuple[str, ast.AST]] = list(
                    zip(cinfo.ctor_params, node.args)
                )
                bound += [
                    (kw.arg, kw.value) for kw in node.keywords if kw.arg
                ]
                for pname, arg in bound:
                    attr = cinfo.ctor_param_attrs.get(pname)
                    if attr is None:
                        continue
                    if attr not in cinfo.lock_attrs and self._is_lock_expr(
                        ctx, mod, node, arg
                    ):
                        cinfo.lock_attrs[attr] = "lock"
                    if attr not in cinfo.attr_types:
                        t = self._ctor_arg_type(ctx, mod, node, arg)
                        if t is not None:
                            cinfo.attr_types[attr] = t

    def _ctor_arg_type(
        self, ctx: FileContext, mod: str, site: ast.AST, arg: ast.AST
    ) -> Optional[str]:
        """Class qualname a constructor argument evidently carries."""
        if isinstance(arg, ast.Name) and arg.id == "self":
            for anc in ctx.ancestors(site):
                if isinstance(anc, ast.ClassDef):
                    qn = f"{mod}:{anc.name}"
                    return qn if qn in self.classes else None
            return None
        if isinstance(arg, ast.Call):
            return self.resolve_class(mod, arg.func)
        return None

    def _is_lock_expr(
        self, ctx: FileContext, mod: str, site: ast.AST, arg: ast.AST
    ) -> bool:
        """Does this constructor argument evidently carry a lock?"""
        if isinstance(arg, ast.Call):
            _, name = _callee(arg)
            return name in _LOCKISH_CTORS
        attr = _self_attr(arg)
        if attr is not None:
            for anc in ctx.ancestors(site):
                if isinstance(anc, ast.ClassDef):
                    cinfo = self.classes.get(f"{mod}:{anc.name}")
                    return cinfo is not None and attr in cinfo.lock_attrs
            return False
        if isinstance(arg, ast.Name):
            return _module_global_lock(self, mod, arg.id) is not None
        return False

    # -- resolution ---------------------------------------------------------

    def resolve_class(self, mod: str, func: ast.AST) -> Optional[str]:
        """Class qualname a constructor expression refers to, if it names
        a project class (directly, via from-import, or module attr)."""
        if isinstance(func, ast.Name):
            local = f"{mod}:{func.id}"
            if local in self.classes:
                return local
            target = self.imports.get(mod, {}).get(func.id)
            if target and target in self.classes:
                return target
        elif isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            target_mod = self.imports.get(mod, {}).get(func.value.id)
            if target_mod and ":" not in target_mod:
                qn = f"{target_mod}:{func.attr}"
                if qn in self.classes:
                    return qn
        return None

    def resolve_call(
        self, call: ast.Call, mod: str, cls: Optional[ast.ClassDef], local_prefix: str
    ) -> Optional[str]:
        """Function qualname a call resolves to within the project, or
        None for unresolvable targets (stdlib, dynamic dispatch)."""
        fn = call.func
        if isinstance(fn, ast.Name):
            # innermost nested def first, then module function, then import
            nested = f"{local_prefix}{fn.id}"
            if nested in self.functions:
                return nested
            direct = f"{mod}:{fn.id}"
            if direct in self.functions:
                return direct
            target = self.imports.get(mod, {}).get(fn.id)
            if target and target in self.functions:
                return target
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        base = fn.value
        if isinstance(base, ast.Name) and base.id == "self" and cls is not None:
            cinfo = self.classes.get(f"{mod}:{cls.name}")
            if cinfo is not None:
                return cinfo.methods.get(fn.attr)
            return None
        if isinstance(base, ast.Attribute) and _self_attr(base) and cls is not None:
            # self.<field>.method(): attribute-type inference
            cinfo = self.classes.get(f"{mod}:{cls.name}")
            if cinfo is not None:
                target_cls = cinfo.attr_types.get(base.attr)
                if target_cls is not None:
                    tinfo = self.classes.get(target_cls)
                    if tinfo is not None:
                        return tinfo.methods.get(fn.attr)
            return None
        if isinstance(base, ast.Name):
            target_mod = self.imports.get(mod, {}).get(base.id)
            if target_mod and ":" not in target_mod:
                qn = f"{target_mod}:{fn.attr}"
                if qn in self.functions:
                    return qn
        return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


# ---------------------------------------------------------------------------
# ALZ014 — interprocedural lock-order cycles
# ---------------------------------------------------------------------------


@dataclass
class _FnSummary:
    # locks acquired directly in this function (any context)
    acquires: Set[str] = field(default_factory=set)
    # (held-lock, acquired-lock, site-ctx, line, col) direct order edges
    edges: List[Tuple[str, str, FileContext, int, int]] = field(default_factory=list)
    # (frozenset(held), callee-qualname, site line/col) calls under locks —
    # plus calls with nothing held (held=∅) which only matter for the
    # transitive `acquires` closure
    calls: List[Tuple[frozenset, str, FileContext, int, int]] = field(
        default_factory=list
    )


def _lock_id_for(
    model: ProgramModel, mod: str, cls: Optional[ast.ClassDef], expr: ast.AST
) -> Optional[str]:
    """Canonical lock node for a ``with`` context expression: a class
    lock field (``module:Class.attr``, condition aliases collapsed onto
    their wrapped lock) or a module-global lock."""
    attr = _self_attr(expr)
    if attr is not None and cls is not None:
        cinfo = model.classes.get(f"{mod}:{cls.name}")
        if cinfo is not None and attr in cinfo.lock_attrs:
            return f"{mod}:{cls.name}.{cinfo.cond_base.get(attr, attr)}"
        return None
    if isinstance(expr, ast.Name):
        return _module_global_lock(model, mod, expr.id)
    return None


def _module_global_lock(
    model: ProgramModel, mod: str, name: str
) -> Optional[str]:
    """Lock node id when ``name`` is assigned threading.Lock()/RLock()
    at module scope in ``mod``; None otherwise."""
    for ctx in model.ctxs:
        if model.module_of[id(ctx)] != mod:
            continue
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call
            ):
                _, ctor = _callee(stmt.value)
                if ctor in _LOCKISH_CTORS:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == name:
                            return f"{mod}.{name}"
    return None


def _summarize_fn(model: ProgramModel, info: FunctionInfo) -> _FnSummary:
    out = _FnSummary()
    mod = model.module_of[id(info.ctx)]
    local_prefix = info.qualname + "."

    def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # nested defs run later, without the enclosing `with` held;
            # they carry their own qualname summary
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly: List[str] = []
            for item in node.items:
                lock = _lock_id_for(model, mod, info.cls, item.context_expr)
                walk(item.context_expr, held)
                if lock is not None and lock not in held:
                    out.acquires.add(lock)
                    for h in held:
                        out.edges.append(
                            (h, lock, info.ctx, item.context_expr.lineno,
                             item.context_expr.col_offset)
                        )
                    newly.append(lock)
            inner = held + tuple(newly)
            for stmt in node.body:
                walk(stmt, inner)
            return
        if isinstance(node, ast.Call):
            target = model.resolve_call(node, mod, info.cls, local_prefix)
            if target is not None and target != info.qualname:
                out.calls.append(
                    (frozenset(held), target, info.ctx, node.lineno, node.col_offset)
                )
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    body = info.node.body if isinstance(info.node.body, list) else [info.node.body]
    for stmt in body:
        walk(stmt, ())
    return out


def check_alz014(ctxs: Sequence[FileContext]) -> Iterable[Finding]:
    model = ProgramModel(ctxs)
    summaries = {qn: _summarize_fn(model, info) for qn, info in model.functions.items()}

    # transitive lock footprint per function, to a fixpoint over the call
    # graph (cycles in the CALL graph just converge — the union is monotone)
    footprint: Dict[str, Set[str]] = {qn: set(s.acquires) for qn, s in summaries.items()}
    changed = True
    while changed:
        changed = False
        for qn, s in summaries.items():
            for _, callee_qn, _, _, _ in s.calls:
                extra = footprint.get(callee_qn, set()) - footprint[qn]
                if extra:
                    footprint[qn] |= extra
                    changed = True

    # lock-order graph: direct with-nesting edges + held-across-call edges
    edges: Dict[Tuple[str, str], Tuple[FileContext, int, int]] = {}
    for s in summaries.values():
        for a, b, ctx, line, col in s.edges:
            edges.setdefault((a, b), (ctx, line, col))
        for held, callee_qn, ctx, line, col in s.calls:
            if not held:
                continue
            for a in held:
                for b in footprint.get(callee_qn, ()):
                    if a != b:
                        edges.setdefault((a, b), (ctx, line, col))

    # strongly connected components of the lock graph; any SCC with an
    # internal edge is a reachable order inversion
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    scc_of = _tarjan(adj)
    for (a, b), (ctx, line, col) in sorted(
        edges.items(), key=lambda kv: (kv[1][0].path, kv[1][1], kv[1][2])
    ):
        if scc_of.get(a) is not None and scc_of.get(a) == scc_of.get(b):
            yield Finding(
                "ALZ014",
                f"lock-order cycle: `{_short(a)}` is held while "
                f"`{_short(b)}` is (transitively) acquired here, but "
                "another call path orders them the other way — two "
                "threads taking the two paths concurrently deadlock; "
                "pick one global order for these locks",
                ctx.path,
                line,
                col,
            )


def _short(lock_id: str) -> str:
    return lock_id.split(":", 1)[-1]


def _tarjan(adj: Dict[str, Set[str]]) -> Dict[str, int]:
    """Node -> SCC id, only for nodes in SCCs of size ≥ 2 (or with a
    self-edge); singletons map to None-ish absence semantics via id -1."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: Dict[str, Optional[int]] = {}
    counter = [0]
    scc_id = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan: explicit frame stack, no recursion limit risk
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                keep = len(comp) > 1 or node in adj.get(node, ())
                for w in comp:
                    out[w] = scc_id[0] if keep else None
                scc_id[0] += 1

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return {k: v for k, v in out.items() if v is not None}


# ---------------------------------------------------------------------------
# ALZ006 — retrace risk
# ---------------------------------------------------------------------------

_JIT_NAMES = ("jit", "pmap")


def _is_jit_call(call: ast.Call) -> bool:
    return _call_transform_name(call) in _JIT_NAMES


def _jit_target(call: ast.Call) -> Optional[ast.AST]:
    """The function expression a jit/pmap call wraps — through partial
    AND through nested transforms (``jit(vmap(lambda ...))`` is still a
    fresh lambda per call)."""
    fn_name = getattr(call.func, "attr", getattr(call.func, "id", None))
    args = call.args
    target = (args[1] if len(args) > 1 else None) if fn_name == "partial" else (
        args[0] if args else None
    )
    while isinstance(target, ast.Call) and _call_transform_name(target) is not None:
        target = _jit_target(target)
    return target


def _has_caching_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        node = dec.func if isinstance(dec, ast.Call) else dec
        name = getattr(node, "attr", getattr(node, "id", None))
        if name in _CACHING_DECORATORS:
            return True
    return False


def _literal_type(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant):
        if node.value is None:
            return None  # None is a singleton — never a type-variance risk
        return type(node.value).__name__
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub, ast.UAdd)):
        return _literal_type(node.operand)
    return None


def check_alz006(ctxs: Sequence[FileContext]) -> Iterable[Finding]:
    model = ProgramModel(ctxs)
    seen_sites: Set[Tuple[str, int, int]] = set()

    def emit(ctx: FileContext, node: ast.AST, msg: str) -> Optional[Finding]:
        site = (ctx.path, node.lineno, node.col_offset)
        if site in seen_sites:
            return None
        seen_sites.add(site)
        return Finding("ALZ006", msg, ctx.path, node.lineno, node.col_offset)

    # (a) jit construction inside a loop, (b) jit of a fresh lambda per
    # call — both per-file walks with ancestor checks
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not _is_jit_call(node):
                continue
            in_loop = False
            enclosing_fns: List[ast.AST] = []
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                    in_loop = True
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    enclosing_fns.append(anc)
            if in_loop:
                f = emit(
                    ctx,
                    node,
                    "jit constructed inside a loop — every iteration builds "
                    "a fresh traced callable with an empty compile cache "
                    "(one retrace per iteration); hoist the jit out of the "
                    "loop",
                )
                if f:
                    yield f
                continue
            target = _jit_target(node)
            if (
                isinstance(target, ast.Lambda)
                and enclosing_fns
                and not any(_has_caching_decorator(fn) for fn in enclosing_fns)
            ):
                f = emit(
                    ctx,
                    node,
                    "jit applied to a fresh lambda inside a function — each "
                    "call builds a new trace cache, so repeated construction "
                    "re-traces (and re-compiles) from scratch; hoist the jit "
                    "to module scope or cache the maker (functools.lru_cache "
                    "keyed on the config)",
                )
                if f:
                    yield f

    # (c) call sites of a jit'd entry point whose positional literals
    # change Python type — one compile-cache entry per distinct type
    jit_entry_points: Dict[str, Tuple[FileContext, int]] = {}
    for ctx in ctxs:
        mod = model.module_of[id(ctx)]
        for stmt in ctx.tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Call)
                and _is_jit_call(stmt.value)
            ):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        jit_entry_points[f"{mod}:{t.id}"] = (ctx, stmt.lineno)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in stmt.decorator_list:
                    if isinstance(dec, ast.Call) and _is_jit_call(dec):
                        jit_entry_points[f"{mod}:{stmt.name}"] = (ctx, stmt.lineno)
                    node_name = getattr(dec, "attr", getattr(dec, "id", None))
                    if node_name in _JIT_NAMES:
                        jit_entry_points[f"{mod}:{stmt.name}"] = (ctx, stmt.lineno)
    if not jit_entry_points:
        return
    # arg-position -> first-seen literal type, then flag divergent sites
    seen_types: Dict[Tuple[str, int], Tuple[str, str, int]] = {}
    sites: List[Tuple[str, int, str, FileContext, ast.Call]] = []
    for ctx in ctxs:
        mod = model.module_of[id(ctx)]
        imports = model.imports.get(mod, {})
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Name
            ):
                continue
            qn = None
            if f"{mod}:{node.func.id}" in jit_entry_points:
                qn = f"{mod}:{node.func.id}"
            else:
                target = imports.get(node.func.id)
                if target in jit_entry_points:
                    qn = target
            if qn is None:
                continue
            for i, arg in enumerate(node.args):
                lt = _literal_type(arg)
                if lt is not None:
                    sites.append((qn, i, lt, ctx, node))
    sites.sort(key=lambda s: (s[3].path, s[4].lineno, s[4].col_offset, s[1]))
    for qn, i, lt, ctx, node in sites:
        first = seen_types.get((qn, i))
        if first is None:
            seen_types[(qn, i)] = (lt, ctx.path, node.lineno)
            continue
        if first[0] != lt:
            f = emit(
                ctx,
                node,
                f"jit'd `{_short(qn)}` gets a Python {lt} for positional "
                f"arg {i} here but a {first[0]} at {first[1]}:{first[2]} — "
                "each distinct Python scalar type is a separate trace-cache "
                "entry (weak-type retrace); pick one type at every call "
                "site",
            )
            if f:
                yield f
