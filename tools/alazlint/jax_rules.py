"""JAX-hygiene rules (ALZ001-ALZ005).

Scope: functions that are *directly* traced — decorated with
``jax.jit`` / ``jax.vmap`` / ``jax.checkpoint`` / ``shard_map`` (bare or
through ``functools.partial``), or passed by name/lambda into one of
those transforms in the same module. Helpers reached only through a
traced caller are out of scope by design: flow-through-call-graph would
need whole-program analysis, and the hot entry points are exactly the
directly-transformed functions.

Within a traced function, a light taint pass marks the non-static
parameters (the values that become tracers) and propagates through
assignments; the tracer-misuse rules fire on tainted expressions only,
so branching on closed-over config stays legal.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Set, Tuple

from tools.alazlint.core import FileContext, Finding, callee as _callee

_TRACING_TRANSFORMS = {"jit", "vmap", "pmap", "checkpoint", "remat", "shard_map"}
# jnp constructors whose default dtype is strong f32 — the silent
# promotion hazard next to a bf16 compute dtype (ALZ004). ``*_like`` and
# ``jnp.asarray`` inherit their input's dtype and are exempt.
_F32_CONSTRUCTORS = {"zeros", "ones", "full", "empty", "arange", "linspace", "eye"}
_NUMPY_MODULES = {"np", "numpy", "onp"}
_HOST_SYNC_BUILTINS = {"float", "int", "bool", "complex"}


def _call_transform_name(call: ast.Call) -> Optional[str]:
    """'jit' for jax.jit(...) / jit(...); handles functools.partial(jax.jit, ...)."""
    fn = call.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    if name == "partial" and call.args:
        inner = call.args[0]
        if isinstance(inner, ast.Attribute):
            return inner.attr if inner.attr in _TRACING_TRANSFORMS else None
        if isinstance(inner, ast.Name):
            return inner.id if inner.id in _TRACING_TRANSFORMS else None
        return None
    return name if name in _TRACING_TRANSFORMS else None


def _static_names_from_call(
    call: ast.Call, fn: ast.FunctionDef | ast.Lambda
) -> Set[str]:
    """Parameter names made static by static_argnums/static_argnames."""
    args = fn.args
    pos = [a.arg for a in args.posonlyargs + args.args]
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for idx in _int_literals(kw.value):
                if 0 <= idx < len(pos):
                    out.add(pos[idx])
        elif kw.arg == "static_argnames":
            out.update(_str_literals(kw.value))
    return out


def _int_literals(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for el in node.elts:
            out.extend(_int_literals(el))
        return out
    return []


def _str_literals(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in node.elts:
            out.extend(_str_literals(el))
        return out
    return []


def _enclosing_fn(ctx: FileContext, node: ast.AST) -> Optional[ast.AST]:
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return anc
    return None


def traced_functions(
    ctx: FileContext,
) -> Iterator[Tuple[ast.FunctionDef | ast.Lambda, ast.Call | None]]:
    """Yield (function node, transform call | None for decorators)."""
    defs_by_name: dict = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(n.name, []).append(n)

    def resolve(name: str, call: ast.Call):
        """Pick the def a by-name transform call refers to. Same-named
        defs are common here (every sharded-model maker nests a `run`):
        prefer the candidate sharing the call's enclosing function, so
        `jax.jit(run)` inside maker A analyzes A's `run`, not the last
        `run` in the file. Fall back to ALL candidates rather than miss
        a traced function (a stray extra analysis only risks an FP that
        a disable comment can silence; a miss silently drops the gate)."""
        candidates = defs_by_name.get(name, [])
        if len(candidates) <= 1:
            return candidates
        home = _enclosing_fn(ctx, call)
        local = [d for d in candidates if _enclosing_fn(ctx, d) is home]
        return local or candidates

    seen: Set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                tname = None
                call = None
                if isinstance(dec, ast.Call):
                    tname = _call_transform_name(dec)
                    call = dec
                elif isinstance(dec, (ast.Attribute, ast.Name)):
                    nm = dec.attr if isinstance(dec, ast.Attribute) else dec.id
                    tname = nm if nm in _TRACING_TRANSFORMS else None
                if tname and id(node) not in seen:
                    seen.add(id(node))
                    yield node, call
        elif isinstance(node, ast.Call):
            if _call_transform_name(node) is None:
                continue
            # first positional arg (after partial's transform) is the fn
            args = node.args
            fn_nodes: list = []
            if isinstance(node.func, (ast.Attribute, ast.Name)) and args:
                head = args[0]
                if (
                    getattr(node.func, "attr", getattr(node.func, "id", None))
                    == "partial"
                ):
                    head = args[1] if len(args) > 1 else None
                if isinstance(head, ast.Lambda):
                    fn_nodes = [head]
                elif isinstance(head, ast.Name):
                    fn_nodes = resolve(head.id, node)
            for fn_node in fn_nodes:
                if id(fn_node) not in seen:
                    seen.add(id(fn_node))
                    yield fn_node, node


def _param_names(fn: ast.FunctionDef | ast.Lambda) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _taint(fn: ast.FunctionDef | ast.Lambda, static: Set[str]) -> Set[str]:
    """Names that (may) hold tracers inside ``fn``: the non-static
    params, propagated through assignments / loop targets to fixpoint."""
    tainted = {p for p in _param_names(fn) if p not in static}
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for _ in range(10):  # fixpoint over simple def-use chains
        before = len(tainted)
        for stmt in body:
            for node in ast.walk(stmt) if isinstance(stmt, ast.AST) else []:
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.For):
                    targets, value = [node.target], node.iter
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                if value is not None and (_names_in(value) & tainted):
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
        if len(tainted) == before:
            break
    return tainted


def _is_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    return bool(_names_in(node) & tainted)


def check_alz001(ctx: FileContext) -> Iterable[Finding]:
    """ALZ001: host-device sync on a traced value inside a traced fn."""
    for fn, call in traced_functions(ctx):
        static = _static_names_from_call(call, fn) if call is not None else set()
        tainted = _taint(fn, static)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                mod, name = _callee(node)
                hit = None
                if name == "item" and isinstance(node.func, ast.Attribute):
                    if _is_tainted(node.func.value, tainted):
                        hit = ".item()"
                elif mod is None and name in _HOST_SYNC_BUILTINS and node.args:
                    if _is_tainted(node.args[0], tainted):
                        hit = f"{name}()"
                elif mod in _NUMPY_MODULES and name in ("asarray", "array") and node.args:
                    if _is_tainted(node.args[0], tainted):
                        hit = f"{mod}.{name}()"
                if hit:
                    yield Finding(
                        "ALZ001",
                        f"{hit} on a traced value forces a host-device sync "
                        "inside a jit/vmap scope (TracerConversionError at "
                        "best, a silent recompile+readback at worst); keep "
                        "it in jnp or move the readback outside the "
                        "transform",
                        ctx.path,
                        node.lineno,
                        node.col_offset,
                    )


def check_alz002(ctx: FileContext) -> Iterable[Finding]:
    """ALZ002: Python control flow branching on a traced value."""
    for fn, call in traced_functions(ctx):
        static = _static_names_from_call(call, fn) if call is not None else set()
        tainted = _taint(fn, static)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.If, ast.While)) and _is_tainted(
                    node.test, tainted
                ):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield Finding(
                        "ALZ002",
                        f"Python `{kind}` branches on a traced value inside "
                        "a jit/vmap scope (ConcretizationTypeError); use "
                        "jnp.where / lax.cond / lax.while_loop, or mark the "
                        "argument static",
                        ctx.path,
                        node.lineno,
                        node.col_offset,
                    )


def _is_hashable_static_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, str, bool)) or node.value is None
    if isinstance(node, ast.Tuple):
        return all(_is_hashable_static_literal(e) for e in node.elts)
    return False


def check_alz003(ctx: FileContext) -> Iterable[Finding]:
    """ALZ003: static_argnums/static_argnames that are non-literal
    (per-call-varying) or unhashable containers; static params with
    mutable defaults."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_transform_name(node) not in ("jit", "pmap"):
            continue
        for kw in node.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            if isinstance(kw.value, (ast.List, ast.Set, ast.Dict)):
                yield Finding(
                    "ALZ003",
                    f"{kw.arg} given a mutable container literal; jit "
                    "hashes static arguments per call — pass a tuple/int "
                    "so the cache key is stable and hashable",
                    ctx.path,
                    kw.value.lineno,
                    kw.value.col_offset,
                )
            elif not _is_hashable_static_literal(kw.value):
                yield Finding(
                    "ALZ003",
                    f"{kw.arg} is not a literal — a per-call-varying "
                    "static spec retraces on every call (one compile "
                    "cache entry per distinct value)",
                    ctx.path,
                    kw.value.lineno,
                    kw.value.col_offset,
                )
    # static params whose *default value* is an unhashable literal
    for fn, call in traced_functions(ctx):
        if call is None or isinstance(fn, ast.Lambda):
            continue
        static = _static_names_from_call(call, fn)
        if not static:
            continue
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults = [None] * (len(pos) - len(args.defaults)) + list(args.defaults)
        for p, d in zip(pos, defaults):
            if p.arg in static and isinstance(d, (ast.List, ast.Set, ast.Dict)):
                yield Finding(
                    "ALZ003",
                    f"static argument `{p.arg}` defaults to an unhashable "
                    "container — jit will raise on the default call path",
                    ctx.path,
                    d.lineno,
                    d.col_offset,
                )


def _establishes_compute_dtype(fn: ast.FunctionDef) -> bool:
    """True when the function works against a polymorphic compute dtype:
    assigns ``dtype = compute_dtype(...)``, takes a ``dtype`` param, or
    casts with ``.astype(dtype)``."""
    if "dtype" in _param_names(fn):
        return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            _, name = _callee(node.value)
            if name == "compute_dtype":
                return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "astype" and node.args:
                a = node.args[0]
                if isinstance(a, ast.Name) and a.id == "dtype":
                    return True
    return False


def check_alz004(ctx: FileContext) -> Iterable[Finding]:
    """ALZ004: un-dtyped f32-defaulting jnp constructor next to a bf16
    compute dtype — the silent bf16→f32 promotion."""
    funcs = [
        n
        for n in ast.walk(ctx.tree)
        if isinstance(n, ast.FunctionDef) and _establishes_compute_dtype(n)
    ]
    seen: set = set()
    for fn in funcs:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            mod, name = _callee(node)
            if mod != "jnp" or name not in _F32_CONSTRUCTORS:
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            if name == "arange" and not any(
                isinstance(a, ast.Constant) and isinstance(a.value, float)
                for a in node.args
            ):
                # integer arange defaults to int32 — an index vector, not
                # a promotion hazard; only float bounds produce f32
                continue
            # dtype passed positionally: zeros/ones/empty(shape, dtype),
            # full(shape, fill, dtype)
            if name in ("zeros", "ones", "empty") and len(node.args) >= 2:
                continue
            if name == "full" and len(node.args) >= 3:
                continue
            seen.add(id(node))
            yield Finding(
                "ALZ004",
                f"jnp.{name}() without an explicit dtype defaults to "
                "strong f32 and silently promotes bf16 operands — pass "
                "dtype= (the function handles a polymorphic compute "
                "dtype elsewhere)",
                ctx.path,
                node.lineno,
                node.col_offset,
            )


def check_alz005(ctx: FileContext) -> Iterable[Finding]:
    """ALZ005: blocking device sync inside a ``stage_*`` function — the
    async-dispatch staging contract (runtime/service.py: stage, then
    finish AFTER the next work is staged)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.FunctionDef) or not node.name.startswith(
            "stage_"
        ):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            mod, name = _callee(sub)
            hit = None
            if name == "block_until_ready":
                hit = ".block_until_ready()"
            elif mod == "jax" and name == "device_get":
                hit = "jax.device_get()"
            elif mod in _NUMPY_MODULES and name in ("asarray", "array"):
                hit = f"{mod}.{name}() (device→host readback)"
            if hit:
                yield Finding(
                    "ALZ005",
                    f"{hit} blocks inside staging function "
                    f"`{node.name}` — staging must dispatch async and let "
                    "the finisher block, or host work stops overlapping "
                    "device compute",
                    ctx.path,
                    sub.lineno,
                    sub.col_offset,
                )
