"""alazflow — whole-program row-conservation + blocking-discipline
static analyzer (ISSUE 8), the fourth tier-1-enforced analysis head.

The chaos and scenario suites (ISSUES 6-7) prove the host plane's
load-bearing invariant — exact row conservation through the drop ledger
(``pushed == emitted + ledger.total``) — *dynamically*, on the seeds
they happen to run. alazflow proves the same contract *statically*, so
the refactors the ROADMAP names (process-mode ShardedIngest, native
batch process_l7) cannot silently move a drop path out from under the
ledger between chaos runs:

- **ALZ040** unledgered row discard: a host-plane function that filters
  or truncates row-bearing data with no path (closed over the call
  graph) to ``DropLedger.add``.
- **ALZ041** closed cause vocabulary: every ledgered cause literal must
  be in ``DropLedger.CAUSES``, and CAUSES must triangulate with the
  alazspec wire-table vocabulary and the golden metric registry.
- **ALZ042** unbounded blocking: queue put/get, thread join, lock
  acquire, condition wait without a timeout/deadline on a path
  reachable from an ingest/flush/close-wave entry point.
- **ALZ043** exception-safe handoff: an exception edge in a
  row-handling function that abandons live rows (neither ledgers,
  re-raises, nor returns them).
- **ALZ044** closed metric registry: gauge/counter names must be
  literals (or prefix-stable f-strings) drawn from the golden
  ``resources/specs/metrics.json``.

Codes live in the shared alazlint registry (append-only); disable
comments (``# alazlint: disable=ALZ04x -- why``) parse uniformly.
Driver: ``python -m tools.alazflow`` / ``make flow``.
"""

from tools.alazflow.driver import flow_paths, flow_source, main  # noqa: F401
