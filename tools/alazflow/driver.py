"""alazflow driver: parse → whole-program flow rules → suppression →
report. Mirrors the alazlint core contract (same Finding type, same
``# alazlint: disable=ALZ04x -- why`` escape hatch, same exit codes)
so `make flow` and tier-1 read one uniform finding stream.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from tools.alazlint.core import (
    FileContext,
    Finding,
    filter_disables,
    parse_context,
    parse_files,
)
from tools.alazflow import blockrules, droprules, vocabrules
from tools.alazflow.flowmodel import FlowModel

REPO = Path(__file__).resolve().parent.parent.parent

# what `make flow` / bench's flow_findings sweep: the host plane plus
# the analyzer itself (self-enforcement, the alazlint precedent)
DEFAULT_PATHS = (
    str(REPO / "alaz_tpu"),
    str(REPO / "tools" / "alazflow"),
)

_parse = parse_files  # the shared driver front end (tools.alazlint.core)


def _run_rules(
    ctxs: List[FileContext], tree_mode: bool
) -> List[Finding]:
    """The five passes. ``tree_mode`` arms the cross-artifact checks
    (cause triangulation, registry completeness) that only make sense
    over the full tree — fixture/single-file runs skip them so a
    fixture pair proves exactly its own rule."""
    # one whole-program model shared by the three dataflow rules — the
    # call-graph/ledger fixpoints are the expensive part of a run
    model = FlowModel(ctxs)
    raw: List[Finding] = []
    raw.extend(droprules.check_alz040(ctxs, model=model))
    raw.extend(vocabrules.check_alz041(ctxs, triangulate=tree_mode))
    raw.extend(blockrules.check_alz042(ctxs, model=model))
    raw.extend(droprules.check_alz043(ctxs, model=model))
    raw.extend(vocabrules.check_alz044(ctxs, completeness=tree_mode))
    return filter_disables(raw, ctxs)


def flow_paths(paths: Sequence[str], tree_mode: bool = False) -> List[Finding]:
    ctxs, findings = _parse(paths)
    findings.extend(_run_rules(ctxs, tree_mode))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def flow_source(path: str, source: str) -> List[Finding]:
    """Analyze one file's source (fixture tests); whole-program rules run
    scoped to this single file, artifact triangulation off."""
    ctx = parse_context(path, source)
    if isinstance(ctx, Finding):
        return [ctx]
    return _run_rules([ctx], tree_mode=False)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if "--write-metrics" in argv:
        argv = [a for a in argv if a != "--write-metrics"]
        ctxs, _ = _parse(argv or [str(REPO / "alaz_tpu")])
        path = vocabrules.write_metrics_golden(ctxs)
        print(f"wrote {path}")
        return 0
    # the cross-artifact checks (vocabulary triangulation, registry
    # completeness) are statements about the WHOLE tree — they run on
    # the default invocation (`make flow`); explicit paths get the
    # per-file rules only, so scanning a fixture doesn't re-litigate
    # tree-global goldens
    paths = argv or list(DEFAULT_PATHS)
    findings = flow_paths(paths, tree_mode=not argv)
    if as_json:
        print(
            json.dumps(
                {
                    "findings": [f.as_json() for f in findings],
                    "count": len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        print(f"alazflow: {len(findings)} finding(s)")
    return 1 if findings else 0
