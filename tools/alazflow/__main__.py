"""``python -m tools.alazflow [paths...] [--json] [--write-metrics]``"""

import sys

from tools.alazflow.driver import main

if __name__ == "__main__":
    sys.exit(main())
