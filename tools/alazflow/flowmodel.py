"""The flow model — alazsan's project model extended for dataflow.

Reuses ``tools.alazlint.program.ProgramModel`` (function index, import
maps, ``self.x = Cls(...)`` attr-type inference, ctor-arg resolution)
and layers on what the conservation/blocking rules need:

- **element types**: ``self.qs = [BatchQueue(...) for ...]`` records the
  element class, so ``self.qs[i].put(...)`` resolves like a typed attr;
- **local variable types** per function (``q = self._queues[i]``,
  ``store = ShardPartialStore(...)``, annotated params);
- **queue/lock/condition typing** for the blocking primitives the rules
  reason about (``BatchQueue`` by project class OR constructor name —
  fixtures parse standalone; stdlib ``queue.Queue`` only when bounded);
- **reachability** from the ingest/flush/close-wave entry surface,
  closed over the call graph;
- **ledger closure**: which functions (transitively) reach
  ``DropLedger.add`` — the "a helper may ledger on the caller's behalf"
  half of ALZ040/ALZ043.

Scope: the drop rules (ALZ040/ALZ043) run only over the ROW PLANE —
the modules rows traverse between a source edge and window emission.
The export leg (datastore/) accounts loss in ``stream.failed`` by
design and the replay/chaos harnesses *deliberately* rewrite rows, so
both stay out of row-plane scope; the blocking rule (ALZ042) covers all
of ``alaz_tpu``. Bare-stem modules (fixtures, tmp-path tests) are
always in scope — they exist to exercise the rules.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.alazlint.core import FileContext, callee as _callee
from tools.alazlint.program import ProgramModel, _self_attr, module_name

# modules whose functions handle conservation-relevant rows (ALZ040/043)
ROW_PLANE_PREFIXES = (
    "alaz_tpu.aggregator",
    "alaz_tpu.sources.ingest_server",
    "alaz_tpu.utils.queues",
    "alaz_tpu.utils.ledger",
    "alaz_tpu.graph.builder",
    "alaz_tpu.runtime.service",
    # the tenancy plane (ISSUE 14) wires per-tenant queues/stores —
    # row-holding construction, in scope like the service it partitions
    "alaz_tpu.runtime.tenancy",
    # the export leg joined the ledger in ISSUE 12 (breaker sheds
    # attribute as the closed `shed` cause), so its drops are in scope
    # for ALZ040/043 like every other row holder's
    "alaz_tpu.datastore.backend",
    # the process-mode ingest plane (ISSUE 15): rings carry row-bearing
    # records across the spawn boundary, the pool sheds/attributes at
    # the scatter and kill seams — in scope for ALZ040/042/043 like the
    # thread backend it mirrors
    "alaz_tpu.shm",
)

# names that mark a value as row-bearing when they appear as parameters
# or assignment targets in a row-plane function (the repo's own naming
# convention for REQUEST/L7 row arrays; see engine.process_l7 and the
# ShardedIngest scatter plane)
ROW_NAMES = frozenset({"events", "batch", "batches", "rows", "chunk", "chunks"})

# the ingest / flush / close-wave entry surface: reachability roots for
# ALZ042 (names, matched against the unqualified function name)
ENTRY_NAME_RE = re.compile(
    r"^(submit_|process_|flush|drain$|close|stop$|serve$|main$|cmd_|_run_close_wave$)"
)

_QUEUE_CTORS = {"BatchQueue"}
_QUEUE_MODULE = "alaz_tpu.utils.queues"


def walk_shallow(fn_node: ast.AST):
    """Walk a function body WITHOUT descending into nested
    def/lambda bodies — those are indexed (and analyzed) under their own
    qualnames, so attributing their facts to the enclosing function
    would smear row/handler analysis across scopes."""
    todo = list(ast.iter_child_nodes(fn_node))
    while todo:
        n = todo.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        todo.extend(ast.iter_child_nodes(n))


def in_row_plane(mod: str) -> bool:
    if "." not in mod:
        return True  # fixture / tmp-path module
    return any(mod == p or mod.startswith(p + ".") for p in ROW_PLANE_PREFIXES)


def is_ledger_add(call: ast.Call) -> bool:
    """``<something ledger-ish>.add(...)`` — the attribution sink. Name
    keyed (``ledger`` / ``_ledger`` / ``self.ledger`` / ``store.ledger``)
    so fixtures and duck-typed sinks resolve without the class index."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "add"):
        return False
    base = fn.value
    name = None
    if isinstance(base, ast.Name):
        name = base.id
    elif isinstance(base, ast.Attribute):
        name = base.attr
    return name is not None and "ledger" in name.lower()


def boolmask_expr(node: ast.AST, bool_names: Set[str]) -> bool:
    """Is this subscript index evidently a boolean row mask? Comparisons,
    boolean algebra over them (&, |, ~), and names assigned from such
    (including ``np.ones/zeros(..., dtype=bool)`` keep-masks). Index
    arrays (argsort/flatnonzero products) deliberately do NOT match —
    permutations and gathers move rows, masks drop them."""
    if isinstance(node, ast.Compare):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.Invert, ast.Not)):
        return boolmask_expr(node.operand, bool_names)
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.BitAnd, ast.BitOr)):
        return boolmask_expr(node.left, bool_names) or boolmask_expr(
            node.right, bool_names
        )
    if isinstance(node, ast.BoolOp):
        return any(boolmask_expr(v, bool_names) for v in node.values)
    if isinstance(node, ast.Name):
        return node.id in bool_names
    if isinstance(node, ast.Call):
        _, name = _callee(node)
        if name in ("ones", "zeros", "full"):
            for kw in node.keywords:
                if kw.arg == "dtype" and getattr(kw.value, "id", None) == "bool":
                    return True
    return False


@dataclass
class FnFlow:
    """Per-function flow facts the rules consume."""

    qualname: str
    node: ast.AST
    ctx: FileContext
    mod: str
    cls: Optional[ast.ClassDef]
    row_vars: Set[str] = field(default_factory=set)  # row-bearing locals
    bool_vars: Set[str] = field(default_factory=set)  # boolean-mask locals
    calls: List[Tuple[str, int, int]] = field(default_factory=list)  # resolved callees
    ledgers_directly: bool = False
    dequeues_rows: bool = False  # pops row batches off a project queue


class FlowModel:
    def __init__(self, ctxs: Sequence[FileContext]):
        self.model = ProgramModel(ctxs)
        self.ctxs = list(ctxs)
        self._mark_queue_attrs()
        self.flows: Dict[str, FnFlow] = {}
        for qn, info in self.model.functions.items():
            self.flows[qn] = self._analyze_fn(qn, info)
        self._reaches_ledger = self._close_ledger()
        self.reachable = self._close_reachable()

    # -- typing helpers ------------------------------------------------------

    def _attr_is_queue(self, mod: str, cls: Optional[ast.ClassDef], attr: str) -> bool:
        if cls is None:
            return False
        cinfo = self.model.classes.get(f"{mod}:{cls.name}")
        if cinfo is None:
            return False
        t = cinfo.attr_types.get(attr)
        if t is not None and t.endswith(":BatchQueue"):
            return True
        return attr in getattr(cinfo, "_alz_queue_attrs", ())

    def _mark_queue_attrs(self) -> None:
        """Record attrs assigned a BatchQueue — directly, or as the
        element type of a list (``self.qs = [BatchQueue(..) for ..]``),
        which the base model's Call-only inference cannot see."""
        for cinfo in self.model.classes.values():
            queue_attrs: Set[str] = set()
            for node in ast.walk(cinfo.node):
                if not isinstance(node, ast.Assign):
                    continue
                value = node.value
                elems: List[ast.AST] = []
                if isinstance(value, ast.ListComp):
                    elems = [value.elt]
                elif isinstance(value, ast.List):
                    elems = value.elts
                elif isinstance(value, ast.Call):
                    elems = [value]
                for e in elems:
                    if isinstance(e, ast.Call):
                        _, name = _callee(e)
                        if name in _QUEUE_CTORS or (
                            name == "Queue" and _bounded_queue_ctor(e)
                        ):
                            for t in node.targets:
                                attr = _self_attr(t)
                                if attr is not None:
                                    queue_attrs.add(attr)
            cinfo._alz_queue_attrs = queue_attrs  # type: ignore[attr-defined]

    def receiver_kind(
        self, fn: FnFlow, base: ast.AST, local_queueish: Set[str]
    ) -> Optional[str]:
        """'queue' / 'lock' / 'condition' for a method-call receiver,
        None when untyped. Resolves self attrs (incl. subscripts of
        queue-list attrs), annotated params, and locals assigned from
        either."""
        mod, cls = fn.mod, fn.cls
        # self.<attr> / self.<attr>[i]
        sub_base = base.value if isinstance(base, ast.Subscript) else base
        attr = _self_attr(sub_base)
        if attr is not None:
            if self._attr_is_queue(mod, cls, attr):
                return "queue"
            if cls is not None:
                cinfo = self.model.classes.get(f"{mod}:{cls.name}")
                if cinfo is not None and attr in cinfo.lock_attrs:
                    return cinfo.lock_attrs[attr]  # 'lock' | 'condition'
            return None
        if isinstance(base, ast.Name) and base.id in local_queueish:
            return "queue"
        return None

    # -- per-function analysis ----------------------------------------------

    def _analyze_fn(self, qn: str, info) -> FnFlow:
        mod = self.model.module_of[id(info.ctx)]
        fn = FnFlow(qn, info.node, info.ctx, mod, info.cls)
        args = getattr(info.node, "args", None)
        if args is not None:
            for a in args.posonlyargs + args.args + args.kwonlyargs:
                if a.arg in ROW_NAMES:
                    fn.row_vars.add(a.arg)
        local_prefix = qn + "."
        local_queueish = self.local_queue_vars(fn)
        for node in walk_shallow(info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    if boolmask_expr(node.value, fn.bool_vars):
                        fn.bool_vars.add(t.id)
                    if self._is_row_source(fn, node.value, local_queueish):
                        fn.row_vars.add(t.id)
                        if isinstance(node.value, ast.Call):
                            fn.dequeues_rows = True
            if isinstance(node, ast.Call):
                if is_ledger_add(node):
                    fn.ledgers_directly = True
                target = self.model.resolve_call(node, mod, info.cls, local_prefix)
                if target is None and isinstance(node.func, ast.Attribute):
                    # typed-receiver fallback the base resolver can't do:
                    # a method call on a queue-typed receiver (incl.
                    # subscripted lists and loop vars) reaches the
                    # BatchQueue method body — what makes the blocking
                    # branches INSIDE put/get entry-reachable
                    if self.receiver_kind(
                        fn, node.func.value, local_queueish
                    ) == "queue":
                        qmeth = f"{_QUEUE_MODULE}:BatchQueue.{node.func.attr}"
                        if qmeth in self.model.functions:
                            target = qmeth
                if target is not None and target != qn:
                    fn.calls.append((target, node.lineno, node.col_offset))
        return fn

    def local_queue_vars(self, fn: FnFlow) -> Set[str]:
        """Locals that evidently hold a project queue: annotated params
        (``queue: BatchQueue``), ``q = BatchQueue(...)``, and
        ``q = self.<queue attr>[i]`` / ``q = self.<queue attr>``."""
        out: Set[str] = set()
        args = getattr(fn.node, "args", None)
        if args is not None:
            for a in args.posonlyargs + args.args + args.kwonlyargs:
                ann = a.annotation
                ann_name = getattr(ann, "id", getattr(ann, "attr", None))
                if ann_name in _QUEUE_CTORS:
                    out.add(a.arg)
        for node in walk_shallow(fn.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not isinstance(t, ast.Name):
                continue
            v = node.value
            if isinstance(v, ast.Call):
                _, name = _callee(v)
                if name in _QUEUE_CTORS or (
                    name == "Queue" and _bounded_queue_ctor(v)
                ):
                    out.add(t.id)
            sub = v.value if isinstance(v, ast.Subscript) else v
            attr = _self_attr(sub)
            if attr is not None and self._attr_is_queue(fn.mod, fn.cls, attr):
                out.add(t.id)
        # ``for q in self._queues`` / ``for i, q in enumerate(self._queues)``
        for node in walk_shallow(fn.node):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            it = node.iter
            if (
                isinstance(it, ast.Call)
                and getattr(it.func, "id", None) == "enumerate"
                and it.args
            ):
                it = it.args[0]
            attr = _self_attr(it)
            if attr is None or not self._attr_is_queue(fn.mod, fn.cls, attr):
                continue
            targets = (
                node.target.elts
                if isinstance(node.target, ast.Tuple)
                else [node.target]
            )
            last = targets[-1]
            if isinstance(last, ast.Name):
                out.add(last.id)
        return out

    def _is_row_source(
        self, fn: FnFlow, value: ast.AST, local_queueish: Set[str]
    ) -> bool:
        """Does this assignment value evidently carry rows? ``x[...]`` /
        ``x.copy()`` of a row var, concatenation of row vars, or a
        ``.get(...)``/``.drain()`` pop off a project queue."""
        if isinstance(value, ast.Subscript):
            base = value.value
            return isinstance(base, ast.Name) and base.id in fn.row_vars
        if isinstance(value, ast.Call):
            f = value.func
            if isinstance(f, ast.Attribute):
                if f.attr in ("get", "drain") and self.receiver_kind(
                    fn, f.value, local_queueish
                ) == "queue":
                    return True
                if f.attr == "copy" and isinstance(f.value, ast.Name):
                    return f.value.id in fn.row_vars
                if f.attr == "concatenate":
                    for a in value.args:
                        if isinstance(a, (ast.List, ast.Tuple)):
                            if any(
                                isinstance(e, ast.Name) and e.id in fn.row_vars
                                for e in a.elts
                            ):
                                return True
                        if isinstance(a, ast.Name) and a.id in fn.row_vars:
                            return True
        if isinstance(value, ast.IfExp):
            return self._is_row_source(fn, value.body, local_queueish) or (
                self._is_row_source(fn, value.orelse, local_queueish)
            )
        return False

    # -- closures ------------------------------------------------------------

    def _close_ledger(self) -> Set[str]:
        reaches = {qn for qn, f in self.flows.items() if f.ledgers_directly}
        changed = True
        while changed:
            changed = False
            for qn, f in self.flows.items():
                if qn in reaches:
                    continue
                if any(c in reaches for c, _, _ in f.calls):
                    reaches.add(qn)
                    changed = True
        return reaches

    def reaches_ledger(self, qn: str) -> bool:
        return qn in self._reaches_ledger

    def statement_reaches_ledger(self, fn: FnFlow, body: List[ast.stmt]) -> bool:
        """Does any statement in this suite ledger — directly or through
        a resolvable helper call? (Handler-granular half of the closure:
        the exception EDGE must attribute, not merely the function.)"""
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                if is_ledger_add(node):
                    return True
                target = self.model.resolve_call(
                    node, fn.mod, fn.cls, fn.qualname + "."
                )
                if target is not None and target in self._reaches_ledger:
                    return True
        return False

    def _close_reachable(self) -> Set[str]:
        roots = {
            qn
            for qn, f in self.flows.items()
            if ENTRY_NAME_RE.search(qn.split(":", 1)[-1].rsplit(".", 1)[-1])
        }
        seen = set(roots)
        work = list(roots)
        while work:
            qn = work.pop()
            f = self.flows.get(qn)
            if f is None:
                continue
            for c, _, _ in f.calls:
                if c not in seen:
                    seen.add(c)
                    work.append(c)
        return seen


def _bounded_queue_ctor(call: ast.Call) -> bool:
    """stdlib ``queue.Queue(maxsize)``: blocking only when bounded — a
    default-unbounded Queue's put never blocks and never drops."""
    for a in call.args[:1]:
        if isinstance(a, ast.Constant) and isinstance(a.value, int) and a.value > 0:
            return True
    for kw in call.keywords:
        if kw.arg == "maxsize" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False
