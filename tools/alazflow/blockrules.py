"""ALZ042 — unbounded blocking on the ingest/flush/close-wave surface.

The PR 6 war story this rule is the static twin of: ``BatchQueue.put``
took a ``timeout`` that was a per-wakeup budget, not a deadline — under
producer contention the shed bound was no bound at all, and a stalled
shard wedged its producer forever. The dynamic fix landed; this rule
pins the *discipline*: on any path reachable from an ingest / flush /
close-wave entry point, every blocking primitive must carry a
timeout or deadline —

- ``BatchQueue.put(...)`` / ``.get(...)`` without a timeout argument
  (the defaults block indefinitely); bounded stdlib ``queue.Queue`` too;
- zero-argument ``.join()`` (a thread join that can outwait the world;
  ``str.join``/``os.path.join`` always take an argument, so the
  zero-arg shape IS the thread shape);
- ``<lock>.acquire()`` without ``timeout=`` on a known lock attribute;
- ``<condition>.wait()`` with no timeout on a known condition attribute.

Reachability is closed over the call graph from the entry-name surface
(``submit_*`` / ``process_*`` / ``flush*`` / ``drain`` / ``close*`` /
``stop`` / ``serve`` / ``main`` / ``cmd_*``), so a blocking call buried
three helpers under ``flush()`` is still caught, while an offline tool
that blocks on purpose is not.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence

from tools.alazlint.core import FileContext, Finding
from tools.alazflow.flowmodel import FlowModel, FnFlow, walk_shallow


def _has_timeoutish(call: ast.Call, extra_pos: int) -> bool:
    """A timeout/deadline rides the call: positional at ``extra_pos``
    (0-indexed past the payload args) or a timeout-ish keyword."""
    if len(call.args) > extra_pos:
        return True
    for kw in call.keywords:
        if kw.arg in ("timeout", "timeout_s", "deadline", "deadline_s"):
            return True
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant):
            if kw.value.value is False:
                return True  # acquire(blocking=False) never blocks
    return False


def check_alz042(
    ctxs: Sequence[FileContext], model: FlowModel | None = None
) -> Iterable[Finding]:
    model = model if model is not None else FlowModel(ctxs)
    out: List[Finding] = []
    for qn, fn in model.flows.items():
        if not fn.mod.startswith("alaz_tpu") and "." in fn.mod:
            continue  # tools/tests: blocking there is not a serving hazard
        if qn not in model.reachable:
            continue
        local_queueish = model.local_queue_vars(fn)
        for node in walk_shallow(fn.node):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            meth = node.func.attr
            recv = node.func.value
            msg = _site_message(model, fn, node, meth, recv, local_queueish)
            if msg is not None:
                out.append(
                    Finding(
                        "ALZ042",
                        msg + " — reachable from the ingest/flush/close "
                        "entry surface, so a stall here wedges the "
                        "pipeline instead of degrading it; pass a "
                        "timeout/deadline (drop-not-block, PR 6's "
                        "put-deadline lesson)",
                        fn.ctx.path,
                        node.lineno,
                        node.col_offset,
                    )
                )
    return out


def _site_message(
    model: FlowModel,
    fn: FnFlow,
    call: ast.Call,
    meth: str,
    recv: ast.AST,
    local_queueish,
) -> Optional[str]:
    if meth == "join" and not call.args and not call.keywords:
        return "unbounded `.join()` (no timeout)"
    kind = model.receiver_kind(fn, recv, local_queueish)
    if meth == "put" and kind == "queue" and not _has_timeoutish(call, 1):
        return "bounded-queue `.put(...)` with no timeout blocks forever on a full queue"
    if meth == "get" and kind == "queue" and not _has_timeoutish(call, 0):
        return "queue `.get()` with no timeout blocks forever on an empty queue"
    if meth == "acquire" and kind == "lock" and not _has_timeoutish(call, 0):
        return "lock `.acquire()` with no timeout"
    if meth == "wait" and kind == "condition" and not _has_timeoutish(call, 0):
        return "condition `.wait()` with no timeout sleeps through a lost notify"
    return None
