"""Closed-vocabulary rules: ALZ041 (ledger causes) and ALZ044 (metric
registry).

ALZ041 — the drop ledger's cause vocabulary is closed-world on purpose
(utils/ledger.py): the conservation gates sum EXACTLY the causes they
know. Three artifacts carry that vocabulary and all three must agree:

1. every ``ledger.add(cause, ...)`` / ``drop_cause=`` literal in the
   tree must be a member of ``DropLedger.CAUSES`` (a typo'd cause would
   raise at runtime — on the drop path, under an incident);
2. ``DropLedger.CAUSES`` must equal the alazspec wire-table vocabulary
   (``resources/specs/wire_layouts.json`` → sampling.ledger_causes) —
   a cause grown in code without ``make specs`` is drift;
3. every cause must be covered by the golden metric registry
   (``ledger.<cause>`` in resources/specs/metrics.json, wildcards
   allowed) — a cause with no gauge is invisible in degraded mode.

ALZ044 — metric names are a wire contract too: dashboards, the health
payload and the Prometheus scrape all key on them. Every
``metrics.gauge/counter/info/histogram`` name must be a literal (or an f-string
whose constant skeleton matches a registered wildcard) drawn from the
golden registry; golden names nothing registers anymore are flagged the
other way. ``python -m tools.alazflow --write-metrics`` regenerates the
golden from the tree — review and commit the diff, exactly the
``make specs`` workflow.
"""

from __future__ import annotations

import ast
import fnmatch
import json
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tools.alazlint.core import FileContext, Finding, parse_context

REPO = Path(__file__).resolve().parent.parent.parent
LEDGER_PY = REPO / "alaz_tpu" / "utils" / "ledger.py"
WIRE_TABLE = REPO / "resources" / "specs" / "wire_layouts.json"
METRICS_GOLDEN = REPO / "resources" / "specs" / "metrics.json"

_METRIC_METHODS = ("gauge", "counter", "info", "histogram")


# ---------------------------------------------------------------------------
# vocabulary extraction
# ---------------------------------------------------------------------------


def _causes_from_ctx(ctx: FileContext) -> Optional[Tuple[List[str], int]]:
    """(CAUSES literal, line) from a DropLedger class body, if present."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.ClassDef) and node.name == "DropLedger"):
            continue
        for stmt in node.body:
            if not isinstance(stmt, ast.Assign):
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id == "CAUSES":
                    v = stmt.value
                    if isinstance(v, (ast.Tuple, ast.List)) and all(
                        isinstance(e, ast.Constant) for e in v.elts
                    ):
                        return [e.value for e in v.elts], stmt.lineno
    return None


def ledger_causes(
    ctxs: Sequence[FileContext], ledger_py: Path = LEDGER_PY
) -> Tuple[List[str], str, int]:
    """(causes, anchor path, anchor line) — from a scanned ctx when the
    ledger module is in the invocation, else from disk."""
    for ctx in ctxs:
        got = _causes_from_ctx(ctx)
        if got is not None:
            return got[0], ctx.path, got[1]
    ctx = parse_context(str(ledger_py), ledger_py.read_text())
    if isinstance(ctx, Finding):  # pragma: no cover - ledger.py must parse
        return [], str(ledger_py), 1
    got = _causes_from_ctx(ctx)
    if got is None:  # pragma: no cover - CAUSES is load-bearing
        return [], str(ledger_py), 1
    return got[0], str(ledger_py), got[1]


def _cause_literal_sites(ctxs: Sequence[FileContext]):
    """(ctx, node, literal) for every cause literal: first positional /
    ``cause=`` of a ledger ``.add``, and ``drop_cause=`` anywhere (the
    BatchQueue mouth-drop routing)."""
    from tools.alazflow.flowmodel import is_ledger_add

    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if is_ledger_add(node):
                lit = None
                if node.args and isinstance(node.args[0], ast.Constant):
                    lit = node.args[0]
                for kw in node.keywords:
                    if kw.arg == "cause" and isinstance(kw.value, ast.Constant):
                        lit = kw.value
                if lit is not None and isinstance(lit.value, str):
                    yield ctx, lit, lit.value
            for kw in node.keywords:
                if kw.arg == "drop_cause" and isinstance(kw.value, ast.Constant):
                    if isinstance(kw.value.value, str):
                        yield ctx, kw.value, kw.value.value


def check_alz041(
    ctxs: Sequence[FileContext],
    triangulate: bool = False,
    ledger_py: Path = LEDGER_PY,
    wire_table: Path = WIRE_TABLE,
    metrics_golden: Path = METRICS_GOLDEN,
) -> Iterable[Finding]:
    causes, anchor_path, anchor_line = ledger_causes(ctxs, ledger_py)
    known = set(causes)
    out: List[Finding] = []
    for ctx, node, lit in _cause_literal_sites(ctxs):
        if lit not in known:
            out.append(
                Finding(
                    "ALZ041",
                    f"drop cause {lit!r} is not in DropLedger.CAUSES "
                    f"{tuple(causes)} — an off-vocabulary cause raises at "
                    "runtime ON THE DROP PATH and the conservation gates "
                    "would never sum it; pick a closed cause or grow the "
                    "vocabulary (ledger.py + `make specs` + the metric "
                    "registry) in one move",
                    ctx.path,
                    node.lineno,
                    node.col_offset,
                )
            )
    if not triangulate:
        return out
    # cross-artifact triangulation (driver/tree mode): code ↔ wire table
    # ↔ metric registry must carry ONE vocabulary
    try:
        wire = json.loads(wire_table.read_text())
        wire_causes = list(wire.get("sampling", {}).get("ledger_causes", []))
    except (OSError, json.JSONDecodeError):
        wire_causes = None
    if wire_causes is None:
        out.append(
            Finding(
                "ALZ041",
                f"wire table {wire_table.name} unreadable — the golden "
                "cause vocabulary cannot be triangulated (run `make specs`)",
                str(wire_table),
                1,
                0,
            )
        )
    elif wire_causes != causes:
        out.append(
            Finding(
                "ALZ041",
                f"DropLedger.CAUSES {tuple(causes)} != wire-table "
                f"ledger_causes {tuple(wire_causes)} — the vocabulary "
                "moved on one side only; `make specs` regenerates the "
                "table from code (then review the conservation gates)",
                anchor_path,
                anchor_line,
                0,
            )
        )
    names = _golden_metric_names(metrics_golden)
    if names is not None:
        for cause in causes:
            gauge = f"ledger.{cause}"
            if not _name_registered(gauge, names):
                out.append(
                    Finding(
                        "ALZ041",
                        f"cause {cause!r} has no `{gauge}` entry in the "
                        f"golden metric registry ({metrics_golden.name}) — "
                        "a loss cause without a gauge is invisible in "
                        "degraded mode; regenerate with --write-metrics",
                        str(metrics_golden),
                        1,
                        0,
                    )
                )
    return out


# ---------------------------------------------------------------------------
# ALZ044 — metric registry
# ---------------------------------------------------------------------------


def _is_metrics_recv(base: ast.AST) -> bool:
    if isinstance(base, ast.Name):
        return base.id == "metrics"
    if isinstance(base, ast.Attribute):
        return base.attr == "metrics"
    return False


def _fstring_skeleton(node: ast.JoinedStr) -> str:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append("*")
    return "".join(parts)


def metric_sites(ctxs: Sequence[FileContext]):
    """(ctx, node, name-or-skeleton, is_literal) for every
    ``metrics.gauge/counter/info`` registration in the invocation.
    ``None`` name = dynamic (non-literal, non-f-string) — always a
    finding: the registry cannot close over it."""
    for ctx in ctxs:
        # self-registrations inside the Metrics class itself count too:
        # the registry must not depend on a local being NAMED `metrics`
        # (naming-convention camouflage a rename would silently defeat)
        self_spans = [
            (n.lineno, n.end_lineno or n.lineno)
            for n in ast.walk(ctx.tree)
            if isinstance(n, ast.ClassDef) and n.name == "Metrics"
        ]
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in _METRIC_METHODS
                and (
                    _is_metrics_recv(fn.value)
                    or (
                        isinstance(fn.value, ast.Name)
                        and fn.value.id == "self"
                        and any(
                            lo <= node.lineno <= hi for lo, hi in self_spans
                        )
                    )
                )
            ):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                yield ctx, node, arg.value, True
            elif isinstance(arg, ast.JoinedStr):
                yield ctx, node, _fstring_skeleton(arg), False
            else:
                yield ctx, node, None, False


def _golden_metric_names(path: Path = METRICS_GOLDEN) -> Optional[List[str]]:
    try:
        return list(json.loads(path.read_text())["names"])
    except (OSError, json.JSONDecodeError, KeyError):
        return None


def _name_registered(name: str, golden: Sequence[str]) -> bool:
    if name in golden:
        return True
    return any("*" in g and fnmatch.fnmatchcase(name, g) for g in golden)


def check_alz044(
    ctxs: Sequence[FileContext],
    completeness: bool = False,
    metrics_golden: Path = METRICS_GOLDEN,
) -> Iterable[Finding]:
    golden = _golden_metric_names(metrics_golden)
    out: List[Finding] = []
    if golden is None:
        out.append(
            Finding(
                "ALZ044",
                f"golden metric registry {metrics_golden} missing or "
                "unreadable — regenerate with "
                "`python -m tools.alazflow --write-metrics` and commit",
                str(metrics_golden),
                1,
                0,
            )
        )
        return out
    seen: Dict[str, int] = {}
    for ctx, node, name, is_literal in metric_sites(ctxs):
        if name is None:
            out.append(
                Finding(
                    "ALZ044",
                    "metric registered under a computed name — the closed "
                    "registry (and every dashboard keyed on it) cannot "
                    "see it; use a literal or a constant-skeleton "
                    "f-string matching a registered wildcard",
                    ctx.path,
                    node.lineno,
                    node.col_offset,
                )
            )
            continue
        seen[name] = seen.get(name, 0) + 1
        if not _name_registered(name, golden):
            kind = "name" if is_literal else "f-string pattern"
            out.append(
                Finding(
                    "ALZ044",
                    f"metric {kind} {name!r} is not in the golden "
                    f"registry ({metrics_golden.name}) — health payloads "
                    "and dashboards key on a closed name set; if the "
                    "metric is intentional, regenerate the registry "
                    "(--write-metrics) and review the diff",
                    ctx.path,
                    node.lineno,
                    node.col_offset,
                )
            )
    if completeness:
        for g in golden:
            if g not in seen:
                out.append(
                    Finding(
                        "ALZ044",
                        f"golden metric {g!r} is registered by nothing in "
                        "the tree — a dashboard keyed on it reads a hole; "
                        "remove it from the registry (--write-metrics) or "
                        "restore the gauge",
                        str(metrics_golden),
                        1,
                        0,
                    )
                )
    return out


def write_metrics_golden(
    ctxs: Sequence[FileContext], path: Path = METRICS_GOLDEN
) -> Path:
    """Regenerate the golden registry from the tree (sorted, stable —
    the `make specs` fixpoint discipline)."""
    names = sorted(
        {name for _, _, name, _ in metric_sites(ctxs) if name is not None}
    )
    path.write_text(json.dumps({"names": names}, indent=2) + "\n")
    return path
