"""Row-conservation rules: ALZ040 (unledgered discard) and ALZ043
(exception-safe handoff).

The conservation contract (utils/ledger.py): every row the pipeline
loses is attributed to EXACTLY ONE ledger cause, so
``pushed == emitted + ledger.total`` is checkable. These rules prove the
attribution side statically:

- **ALZ040** finds the places rows *leave* a row-plane function —
  boolean-mask filters (``events = events[keep]``) and truncating
  slices (``rows = rows[:cap]``) — in functions with no path to
  ``DropLedger.add``, closed over the call graph: a helper that ledgers
  on the caller's behalf keeps the caller clean, cross-module included.
  Gathers and permutations (``rows[order]``, ``rows[np.flatnonzero(..)]``)
  move rows without losing any and never match. The exemption is
  deliberately FUNCTION-granular (one attribution exempts every discard
  site in the function) — per-site dominance would need real dataflow;
  a new unattributed filter inside an already-ledgering function is the
  dynamic gates' job. See ARCHITECTURE §3l for the precision bound.

- **ALZ043** checks the exception EDGES of row-handling code: a handler
  that swallows (or merely logs) while row-bearing data is live loses
  those rows with no attribution — the shard stays alive, conservation
  silently breaks. A handler is safe when it re-raises, returns the
  rows onward, or (transitively) reaches ``DropLedger.add`` itself —
  handler-granular, because the FUNCTION ledgering on its happy path
  says nothing about the exception path.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Sequence

from tools.alazlint.core import FileContext, Finding
from tools.alazflow.flowmodel import (
    FlowModel,
    FnFlow,
    boolmask_expr,
    in_row_plane,
    walk_shallow,
)


def _discard_sites(fn: FnFlow) -> Iterable[ast.AST]:
    """Subscript expressions in ``fn`` that shrink a row-bearing value:
    boolean-mask indexing and upper-bounded slices of a row var."""
    for node in walk_shallow(fn.node):
        if not isinstance(node, ast.Subscript):
            continue
        base = node.value
        if not (isinstance(base, ast.Name) and base.id in fn.row_vars):
            continue
        idx = node.slice
        if isinstance(idx, ast.Slice):
            # rows[:k] truncates; rows[k:] drops a prefix. rows[:] is a
            # copy and full-range views with step keep every row.
            if idx.upper is not None or idx.lower is not None:
                yield node
            continue
        if boolmask_expr(idx, fn.bool_vars):
            yield node


def check_alz040(
    ctxs: Sequence[FileContext], model: FlowModel | None = None
) -> Iterable[Finding]:
    model = model if model is not None else FlowModel(ctxs)
    out: List[Finding] = []
    for qn, fn in model.flows.items():
        if not in_row_plane(fn.mod) or not fn.row_vars:
            continue
        if model.reaches_ledger(qn):
            continue  # this function (or a helper it calls) attributes
        for site in _discard_sites(fn):
            out.append(
                Finding(
                    "ALZ040",
                    f"`{qn.split(':')[-1]}` discards row-bearing "
                    f"`{site.value.id}` here with no path to "
                    "DropLedger.add — the cut rows vanish from the "
                    "conservation equation (pushed == emitted + ledger); "
                    "attribute them to a ledger cause, route them through "
                    "a helper that does, or ledger-justify the filter",
                    fn.ctx.path,
                    site.lineno,
                    site.col_offset,
                )
            )
    return out


def _handler_exits(handler: ast.ExceptHandler) -> bool:
    """Does the handler re-raise or return a value (routing the failure
    AND the rows to the caller)? A bare ``return`` abandons them."""
    for stmt in ast.walk(handler):
        if isinstance(stmt, ast.Raise):
            return True
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            return True
    return False


def check_alz043(
    ctxs: Sequence[FileContext], model: FlowModel | None = None
) -> Iterable[Finding]:
    model = model if model is not None else FlowModel(ctxs)
    out: List[Finding] = []
    for qn, fn in model.flows.items():
        if not in_row_plane(fn.mod) or not fn.row_vars:
            continue
        for node in walk_shallow(fn.node):
            if not isinstance(node, ast.Try):
                continue
            # only tries whose body handles rows in flight: the body
            # references a row var, or the function is a dequeue loop
            # (item popped before the try, processed inside it)
            touches = fn.dequeues_rows or any(
                isinstance(sub, ast.Name) and sub.id in fn.row_vars
                for stmt in node.body
                for sub in ast.walk(stmt)
            )
            if not touches:
                continue
            for handler in node.handlers:
                if _handler_exits(handler):
                    continue
                if model.statement_reaches_ledger(fn, handler.body):
                    continue
                caught = _caught_names(handler)
                out.append(
                    Finding(
                        "ALZ043",
                        f"exception edge in `{qn.split(':')[-1]}` "
                        f"(except {caught}) abandons in-flight rows: the "
                        "handler neither ledgers them, re-raises, nor "
                        "returns them — a failed batch vanishes while the "
                        "worker lives on, silently breaking "
                        "pushed == emitted + ledger; attribute the rows "
                        "(ledger.add) before swallowing the failure",
                        fn.ctx.path,
                        handler.lineno,
                        handler.col_offset,
                    )
                )
    return out


def _caught_names(handler: ast.ExceptHandler) -> str:
    if handler.type is None:
        return "<bare>"
    t = handler.type
    names = []
    for n in t.elts if isinstance(t, ast.Tuple) else [t]:
        names.append(getattr(n, "attr", getattr(n, "id", "?")))
    return "/".join(names)
