"""``python -m tools.alazjit`` — the `make jit` entry point."""

import sys

from tools.alazjit.driver import main

if __name__ == "__main__":
    sys.exit(main())
