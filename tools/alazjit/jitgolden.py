"""The golden jit-surface spec: ``resources/specs/jit_surface.json``
and ALZ074 (surface drift + retrace-budget coverage).

The spec pins what discovery FOUND — site key → (wrapped fn, transform
chain, static args, maker caching, entry-surface reachability, in-dtype
policy, cache-key family, retrace budget) — the same way threads.json
pins the thread topology: regenerated deterministically (``make specs``
/ ``python -m tools.alazjit --write-surface``), committed, byte-fixpoint
under regen. A new jit entry point, a static-arg set change, or a maker
losing its cache shows up as a one-line JSON diff in the PR that caused
it — not as a silent growth of the compile cache discovered in
BENCH_HISTORY three PRs later.

ALZ074 also closes the loop on ``sanitize/retrace.py``'s
``STEADY_STATE_BUDGETS``: every budgeted fn name must match a
discovered site's wrapped fn, which retires that hand-maintained dict
as a drift risk — renaming a traced fn without updating the budget (or
the budget outliving the fn) is now a finding, not a silently-ignored
watch entry.

Site keys are position-free (module:enclosing_fn/wrapped_fn), so the
committed golden does not churn when unrelated edits move lines.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, List

from tools.alazlint.core import Finding
from tools.alazjit.jitmodel import JitModel

REPO = Path(__file__).resolve().parent.parent.parent
SURFACE_GOLDEN = REPO / "resources" / "specs" / "jit_surface.json"

_REGEN = "`python -m tools.alazjit --write-surface` (or `make specs`)"


def compute_surface(jm: JitModel) -> dict:
    sites = {}
    for s in jm.sites:
        sites[s.key] = {
            "fn": s.fn_name,
            "transforms": list(s.transforms),
            "static_args": list(s.static_args),
            "cached_maker": s.cached_maker,
            "reachable": s.reachable,
            "in_dtypes": s.in_dtypes(),
            "cache_key": s.cache_key_family(),
            "budget": jm.budgets.get(s.fn_name),
        }
    return {"sites": dict(sorted(sites.items()))}


def render(surface: dict) -> str:
    return json.dumps(surface, indent=2, sort_keys=True) + "\n"


def write_surface_golden(jm: JitModel, path: Path = SURFACE_GOLDEN) -> Path:
    path.write_text(render(compute_surface(jm)))
    return path


def check_budget_coverage(jm: JitModel) -> Iterable[Finding]:
    """Every STEADY_STATE_BUDGETS key must name a discovered wrapped fn
    — the static coverage that retires the dict as a drift risk."""
    if not jm.budgets:
        return
    fn_names = jm.site_fn_names()
    ctx = jm.budget_ctx
    for bkey in sorted(jm.budgets):
        if bkey not in fn_names:
            yield Finding(
                "ALZ074",
                f"STEADY_STATE_BUDGETS names `{bkey}` but jit-surface "
                "discovery found no site wrapping a fn of that name — "
                "the budget dict is stale (fn renamed/retired) or "
                "discovery regressed; fix the dict or the traced fn "
                "name (CompileWatcher attributes compiles by name)",
                ctx.path if ctx is not None else "<budgets>",
                jm.budget_line or 1,
                0,
            )


def check_alz074(
    jm: JitModel,
    golden_path: Path = SURFACE_GOLDEN,
) -> Iterable[Finding]:
    out: List[Finding] = []
    out.extend(check_budget_coverage(jm))
    live = compute_surface(jm)["sites"]
    try:
        golden = json.loads(golden_path.read_text()).get("sites", {})
    except (OSError, json.JSONDecodeError):
        out.append(
            Finding(
                "ALZ074",
                f"golden jit-surface spec {golden_path.name} missing or "
                f"unreadable — regenerate with {_REGEN} and commit",
                str(golden_path),
                1,
                0,
            )
        )
        return out
    for key in sorted(set(live) - set(golden)):
        site = jm.by_key[key]
        out.append(
            Finding(
                "ALZ074",
                f"jit site `{key}` is not in the golden surface spec "
                f"({golden_path.name}) — the jit surface grew; "
                f"regenerate with {_REGEN} and REVIEW the diff (a new "
                "entry point is a compile-cache design event, not a "
                "drive-by)",
                site.ctx.path,
                site.line,
                site.col,
            )
        )
    for key in sorted(set(golden) - set(live)):
        out.append(
            Finding(
                "ALZ074",
                f"golden jit site `{key}` no longer exists in the tree "
                f"— the committed surface is stale; regenerate with "
                f"{_REGEN} and review what retired it",
                str(golden_path),
                1,
                0,
            )
        )
    for key in sorted(set(golden) & set(live)):
        if golden[key] != live[key]:
            site = jm.by_key[key]
            drifted = sorted(
                f
                for f in set(golden[key]) | set(live[key])
                if golden[key].get(f) != live[key].get(f)
            )
            out.append(
                Finding(
                    "ALZ074",
                    f"surface entry for `{key}` drifted in "
                    f"{', '.join(drifted)}: golden "
                    f"{ {f: golden[key].get(f) for f in drifted} } vs live "
                    f"{ {f: live[key].get(f) for f in drifted} } — a "
                    "static-arg set, transform chain, or caching change "
                    f"moves the compile-cache key; regenerate with {_REGEN} "
                    "and review",
                    site.ctx.path,
                    site.line,
                    site.col,
                )
            )
    return out
