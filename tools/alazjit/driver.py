"""alazjit driver: parse → jit-surface discovery → device-plane rules →
suppression → report. Mirrors the alazrace/alaznat driver contract
(same Finding type, same ``# alazlint: disable=ALZ07x -- why`` escape
hatch, same exit codes) so `make jit` and tier-1 read one uniform
finding stream.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from tools.alazlint.core import (
    FileContext,
    Finding,
    filter_disables,
    parse_context,
    parse_files,
)
from tools.alazjit import jitgolden, jitrules
from tools.alazjit.jitmodel import JitModel

REPO = Path(__file__).resolve().parent.parent.parent

# what `make jit` / bench's jit_findings sweep: the device plane plus
# the analyzer itself (self-enforcement, the alazlint precedent)
DEFAULT_PATHS = (
    str(REPO / "alaz_tpu"),
    str(REPO / "tools" / "alazjit"),
)

_parse = parse_files  # the shared driver front end (tools.alazlint.core)


def _run_rules(ctxs: List[FileContext], tree_mode: bool) -> List[Finding]:
    """The four rule passes over ONE shared jit model (discovery + the
    reachability closure are the expensive part of a run). ``tree_mode``
    arms the golden-surface drift check (ALZ074), which only makes sense
    over the full tree — fixture/single-file runs skip it so a fixture
    pair proves exactly its own rule."""
    jm = JitModel(ctxs)
    raw: List[Finding] = []
    raw.extend(jitrules.check_alz070(jm))
    raw.extend(jitrules.check_alz071(jm))
    raw.extend(jitrules.check_alz072(jm))
    raw.extend(jitrules.check_alz073(jm))
    if tree_mode:
        raw.extend(jitgolden.check_alz074(jm))
    return filter_disables(raw, ctxs)


def jit_paths(paths: Sequence[str], tree_mode: bool = False) -> List[Finding]:
    ctxs, findings = _parse(paths)
    findings.extend(_run_rules(ctxs, tree_mode))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def jit_source(path: str, source: str) -> List[Finding]:
    """Analyze one file's source (fixture tests); the whole-program
    rules run scoped to this single file, golden-surface drift off."""
    ctx = parse_context(path, source)
    if isinstance(ctx, Finding):
        return [ctx]
    return _run_rules([ctx], tree_mode=False)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if "--write-surface" in argv:
        argv = [a for a in argv if a != "--write-surface"]
        # regen MUST parse the same tree the drift check scans, or an
        # ALZ074 finding in the analyzer's own package could prescribe
        # a regen command that cannot clear it
        ctxs, _ = _parse(argv or list(DEFAULT_PATHS))
        path = jitgolden.write_surface_golden(JitModel(ctxs))
        print(f"wrote {path}")
        return 0
    # the golden-surface drift check is a statement about the WHOLE
    # tree — it runs on the default invocation (`make jit`); explicit
    # paths get the hazard rules only, so scanning a fixture doesn't
    # re-litigate the tree-global golden
    paths = argv or list(DEFAULT_PATHS)
    findings = jit_paths(paths, tree_mode=not argv)
    if as_json:
        print(
            json.dumps(
                {
                    "findings": [f.as_json() for f in findings],
                    "count": len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        print(f"alazjit: {len(findings)} finding(s)")
    return 1 if findings else 0
