"""ALZ070-ALZ073: the retrace / host-sync / dtype hazard rules over the
discovered jit surface.

Scope split against the existing per-file heads (no double findings):

- ALZ070 is the *whole-program* fresh-wrapper/caller-side complement of
  ALZ006 (which already flags jit-in-loop, jit-of-fresh-lambda, and
  literal type variance per invocation): uncached constructions inside
  method bodies, uncached makers invoked from loops, and shape-valued
  Python scalars flowing into *static* positions of a maker-produced
  jit callable (one compile-cache entry per distinct value).
- ALZ071 is interprocedural ALZ002: data-dependent Python control flow
  on device values inside *helpers* reached from a traced fn — the
  wrapped fn itself stays ALZ002's (per-file) territory. The taint is
  shape-aware: ``x.shape[0]``, ``len(x)``, ``x.ndim`` and
  ``x is None`` checks never carry device taint.
- ALZ072 is interprocedural ALZ005 plus the §3n dispatch-loop
  contract: unambiguous syncs (``block_until_ready`` /
  ``jax.device_get`` / ``.item()``) in helpers transitively reachable
  from a ``stage_*`` function; device readbacks in the *shallow* body
  of a dispatch-loop driver (a fn that both stages and finishes —
  sync belongs in the ``finish*`` scopes, never between dispatch and
  finish); and implicit ``__bool__`` on a jit-call result in a driver.
- ALZ073 is the interprocedural dtype-discipline complement of ALZ004
  (jnp f32 ctors near a compute dtype, per-file) and ALZ024 (explicit
  float64 in *directly* traced fns, per-file): numpy f64-defaulting
  constructors anywhere in the traced closure, and f64 spellings —
  including bare ``float``, which IS float64 — in helpers the per-file
  rules cannot see.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.alazlint.core import FileContext, Finding, callee as _callee
from tools.alazlint.jax_rules import _NUMPY_MODULES, _param_names
from tools.alazjit.jitmodel import (
    JitModel,
    JitSite,
    _LOOP_NODES,
    _walk_shallow,
    device_names,
    local_device_taint,
)

# numpy constructors whose default dtype is float64 — each one inside a
# traced closure bakes an f64 constant into the trace (promotion, or a
# silent downcast under disabled x64 — either way not what bf16/int8
# arms want to inherit)
_NP_F64_CONSTRUCTORS = {"zeros", "ones", "full", "empty", "linspace", "eye"}
_F64_SPELLINGS = {"float64", "f64", "double"}
# syncs that are unambiguous on any value (no host-side-numpy false
# positive possible, unlike np.asarray in a helper)
_HARD_SYNCS = ("block_until_ready", "device_get", "item")


def _final_name(qualname: str) -> str:
    return qualname.split(":", 1)[-1].split(".")[-1]


def _callee_params(jm: JitModel, target: str, call: ast.Call) -> List[str]:
    info = jm.model.functions[target]
    params = _param_names(info.node)
    if (
        params
        and params[0] in ("self", "cls")
        and isinstance(call.func, ast.Attribute)
    ):
        params = params[1:]  # bound call: positionals start after self
    return params


def _tainted_callee_params(
    jm: JitModel, target: str, call: ast.Call, tainted: Set[str]
) -> "frozenset[str]":
    params = _callee_params(jm, target, call)
    out: Set[str] = set()
    for i, arg in enumerate(call.args):
        if i < len(params) and (device_names(arg) & tainted):
            out.add(params[i])
    for kw in call.keywords:
        if kw.arg and kw.arg in params and (device_names(kw.value) & tainted):
            out.add(kw.arg)
    return frozenset(out)


# ---------------------------------------------------------------------------
# ALZ070 — whole-program fresh-wrapper / caller-side cache-key hazards
# ---------------------------------------------------------------------------


def _shape_valued(expr: ast.AST) -> Optional[str]:
    """A spelling when ``expr`` is evidently a per-shape Python scalar:
    ``len(x)``, ``x.shape[i]``, ``x.shape`` / ``x.size`` / ``x.ndim``."""
    if isinstance(expr, ast.Call):
        fn = expr.func
        if isinstance(fn, ast.Name) and fn.id == "len":
            return "len(...)"
    node = expr
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in ("shape", "size", "ndim"):
        return f".{node.attr}"
    return None


def check_alz070(jm: JitModel) -> Iterable[Finding]:
    model = jm.model

    # (a) uncached construction inside a method body: a fresh compile
    # cache per method call (ALZ006 only sees loops and lambdas)
    for site in jm.sites:
        if not site.is_entry or site.cached_maker:
            continue
        if site.encl_qualname is None:
            continue
        info = model.functions.get(site.encl_qualname)
        if info is None or info.cls is None:
            continue
        if _final_name(site.encl_qualname) == "__init__":
            continue  # once per instance: a legal construction point
        yield Finding(
            "ALZ070",
            f"jit surface `{site.key}` is constructed inside method "
            f"`{_final_name(site.encl_qualname)}` without a cache — every "
            "call builds a fresh traced callable with an empty compile "
            "cache (one retrace per call); construct it in __init__ or "
            "cache the maker (functools.lru_cache keyed on the config)",
            site.ctx.path,
            site.line,
            site.col,
        )

    # (b) uncached maker invoked per loop iteration: same pathology one
    # or more calls further out, where the per-file ALZ006 loop check
    # cannot see it. Two shapes: the call sits in a loop syntactically,
    # or the calling function is loop-tainted — transitively called
    # from a loop in the reachable entry surface (`main` sweeping
    # scenarios re-invokes the whole detection leg per iteration, and
    # an uncached maker three frames down re-traces every time).
    uncached_makers = {
        qn: s
        for qn, s in jm.maker_functions().items()
        if not s.cached_maker
    }
    if uncached_makers:
        for qn, info in model.functions.items():
            mod = model.module_of[id(info.ctx)]
            local_prefix = qn + "."
            for node in _walk_shallow(info.node):
                if not isinstance(node, ast.Call):
                    continue
                target = jm.resolve_call_ext(node, mod, info.cls, local_prefix)
                site = uncached_makers.get(target or "")
                if site is None:
                    continue
                in_loop = any(
                    isinstance(anc, _LOOP_NODES)
                    for anc in info.ctx.ancestors(node)
                )
                if in_loop:
                    yield Finding(
                        "ALZ070",
                        f"uncached jit maker `{_final_name(target)}` called "
                        "inside a loop — each iteration re-builds "
                        f"`{site.key}` and re-traces from an empty cache; "
                        "hoist the maker call out of the loop or "
                        "lru_cache the maker",
                        info.ctx.path,
                        node.lineno,
                        node.col_offset,
                    )
                elif qn in jm.loop_tainted:
                    yield Finding(
                        "ALZ070",
                        f"uncached jit maker `{_final_name(target)}` is "
                        f"re-invoked per loop iteration: `{_final_name(qn)}` "
                        "is loop-called from the entry surface, so every "
                        f"iteration re-builds `{site.key}` and re-traces "
                        "from an empty compile cache; lru_cache the maker "
                        "(keyed on the config) so same-config iterations "
                        "share one trace cache",
                        info.ctx.path,
                        node.lineno,
                        node.col_offset,
                    )

    # (c) shape-valued Python scalars into a STATIC position of a
    # maker-produced jit callable: one compile-cache entry per distinct
    # runtime value — unbounded unless routed through the bucket table
    makers = jm.maker_functions()
    # binding -> site, per module: `step = make_step_fn(cfg)` and
    # `self._fn = make_score_fn(cfg)` both index the returned callable
    bindings: Dict[Tuple[str, str], JitSite] = {}
    for qn, info in model.functions.items():
        mod = model.module_of[id(info.ctx)]
        local_prefix = qn + "."
        for node in _walk_shallow(info.node):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            target = jm.resolve_call_ext(node.value, mod, info.cls, local_prefix)
            site = makers.get(target or "")
            if site is None or not site.static_args:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    bindings[(mod, t.id)] = site
                elif (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    bindings[(mod, f"self.{t.attr}")] = site
    if bindings:
        for qn, info in model.functions.items():
            mod = model.module_of[id(info.ctx)]
            for node in _walk_shallow(info.node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = None
                if isinstance(fn, ast.Name):
                    name = fn.id
                elif (
                    isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "self"
                ):
                    name = f"self.{fn.attr}"
                site = bindings.get((mod, name or ""))
                if site is None or site.fn_node is None:
                    continue
                params = _param_names(site.fn_node)
                for i, arg in enumerate(node.args):
                    spelled = _shape_valued(arg)
                    if spelled is None or i >= len(params):
                        continue
                    if params[i] in site.static_args:
                        yield Finding(
                            "ALZ070",
                            f"shape-valued scalar ({spelled}) flows into "
                            f"static arg `{params[i]}` of jit surface "
                            f"`{site.key}` — every distinct value is a "
                            "separate compile-cache entry; quantize it "
                            "through the bucket table before the call",
                            info.ctx.path,
                            arg.lineno,
                            arg.col_offset,
                        )


# ---------------------------------------------------------------------------
# ALZ071 — interprocedural data-dependent control flow on device values
# ---------------------------------------------------------------------------


def check_alz071(jm: JitModel) -> Iterable[Finding]:
    model = jm.model
    node_to_qn = {id(info.node): qn for qn, info in model.functions.items()}
    memo: Set[Tuple[str, frozenset]] = set()
    out: List[Finding] = []

    def analyze(qn: str, seed: "frozenset[str]", report_here: bool) -> None:
        key = (qn, seed)
        if key in memo or len(memo) > 4000:
            return
        memo.add(key)
        info = model.functions[qn]
        mod = model.module_of[id(info.ctx)]
        local_prefix = qn + "."
        tainted = local_device_taint(info.node, set(seed))
        for node in _walk_shallow(info.node):
            if (
                report_here
                and isinstance(node, (ast.If, ast.While))
                and (device_names(node.test) & tainted)
            ):
                kind = "if" if isinstance(node, ast.If) else "while"
                out.append(
                    Finding(
                        "ALZ071",
                        f"Python `{kind}` in helper `{_final_name(qn)}` "
                        "branches on a device value that rides in from a "
                        "traced caller (ConcretizationTypeError once "
                        "jitted); use jnp.where / lax.cond, branch on "
                        "shapes only, or hoist the decision to the caller",
                        info.ctx.path,
                        node.lineno,
                        node.col_offset,
                    )
                )
            if isinstance(node, ast.Call):
                target = jm.resolve_call_ext(node, mod, info.cls, local_prefix)
                if target is None or target not in model.functions:
                    continue
                tp = _tainted_callee_params(jm, target, node, tainted)
                if tp:
                    analyze(target, tp, report_here=True)

    for site in jm.sites:
        fn = site.fn_node
        if fn is None or isinstance(fn, ast.Lambda):
            continue
        qn = node_to_qn.get(id(fn))
        if qn is None:
            continue
        seed = frozenset(
            p for p in _param_names(fn) if p not in site.static_args
        )
        # the wrapped fn itself is ALZ002's territory (per-file); only
        # its helpers report here
        analyze(qn, seed, report_here=False)
    return out


# ---------------------------------------------------------------------------
# ALZ072 — host-sync discipline on the scorer dispatch paths (§3n)
# ---------------------------------------------------------------------------


def _closure_from(
    jm: JitModel, roots: Sequence[str]
) -> Dict[str, str]:
    """fn qualname -> root qualname for everything transitively called
    from ``roots`` (resolved calls only, shallow walk per fn so a
    nested finisher def doesn't leak its scope into the closure)."""
    model = jm.model
    owner: Dict[str, str] = {}
    work: List[Tuple[str, str]] = [(r, r) for r in roots]
    while work:
        qn, root = work.pop()
        if qn in owner or qn not in model.functions:
            continue
        owner[qn] = root
        info = model.functions[qn]
        mod = model.module_of[id(info.ctx)]
        local_prefix = qn + "."
        for node in _walk_shallow(info.node):
            if isinstance(node, ast.Call):
                target = jm.resolve_call_ext(node, mod, info.cls, local_prefix)
                if target is not None and target not in owner:
                    work.append((target, root))
    return owner


def _hard_sync(node: ast.Call) -> Optional[str]:
    mod, name = _callee(node)
    if name == "block_until_ready":
        return ".block_until_ready()"
    if mod == "jax" and name == "device_get":
        return "jax.device_get()"
    if name == "item" and isinstance(node.func, ast.Attribute):
        return ".item()"
    return None


def _readback(node: ast.Call) -> Optional[str]:
    hit = _hard_sync(node)
    if hit is not None:
        return hit
    mod, name = _callee(node)
    if mod in _NUMPY_MODULES and name in ("asarray", "array"):
        return f"{mod}.{name}()"
    return None


def check_alz072(jm: JitModel) -> Iterable[Finding]:
    model = jm.model

    # (1) interprocedural ALZ005: a helper transitively reachable from a
    # stage_* function must not hard-sync (the stage fn's own body is
    # per-file ALZ005 territory)
    stage_roots = [
        qn for qn in model.functions if _final_name(qn).startswith("stage_")
    ]
    owner = _closure_from(jm, stage_roots)
    for qn, root in sorted(owner.items()):
        if qn in stage_roots:
            continue
        info = model.functions[qn]
        for node in _walk_shallow(info.node):
            if not isinstance(node, ast.Call):
                continue
            hit = _hard_sync(node)
            if hit is not None:
                yield Finding(
                    "ALZ072",
                    f"{hit} blocks inside `{_final_name(qn)}`, which is "
                    f"reachable from staging function "
                    f"`{_final_name(root)}` — staging must dispatch async "
                    "and let the finisher block, or host work stops "
                    "overlapping device compute",
                    info.ctx.path,
                    node.lineno,
                    node.col_offset,
                )

    # (2)+(3) dispatch-loop drivers: a fn that both stages and finishes
    # is the §3n loop — its shallow body may sync at staging and finish
    # scopes ONLY, and must not read back (or truth-test) device values
    # between dispatch and finish
    for qn, info in model.functions.items():
        stages = False
        finishes = False
        for node in _walk_shallow(info.node):
            if isinstance(node, ast.Call):
                _, name = _callee(node)
                if name and name.startswith("stage"):
                    stages = True
                if name and name.startswith("finish"):
                    finishes = True
        if not (stages and finishes):
            continue
        # pass 1: names bound from jitted calls (the shallow walk is
        # not in source order, so collect before checking truth-tests)
        jit_results: Set[str] = set()
        for node in _walk_shallow(info.node):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                fn = node.value.func
                name = None
                if isinstance(fn, ast.Attribute):
                    name = fn.attr
                elif isinstance(fn, ast.Name):
                    name = fn.id
                if name in jm.site_fn_names() or (
                    name is not None
                    and isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "self"
                    and ("score" in name or "jit" in name or "step" in name)
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jit_results.add(t.id)
        for node in _walk_shallow(info.node):
            if isinstance(node, ast.Call):
                hit = _readback(node)
                if hit is not None:
                    yield Finding(
                        "ALZ072",
                        f"{hit} in the dispatch loop of "
                        f"`{_final_name(qn)}` — the §3n staging contract "
                        "allows sync at staging and finish only, never "
                        "between dispatch and finish; move the readback "
                        "into the finish scope",
                        info.ctx.path,
                        node.lineno,
                        node.col_offset,
                    )
            if (
                isinstance(node, (ast.If, ast.While))
                and isinstance(node.test, ast.Name)
                and node.test.id in jit_results
            ):
                yield Finding(
                    "ALZ072",
                    f"truth-test on `{node.test.id}` — the result of a "
                    "jitted call — in the dispatch loop of "
                    f"`{_final_name(qn)}`: implicit __bool__ on a device "
                    "value is a hidden host sync between dispatch and "
                    "finish; test `is not None` or move it to the finish "
                    "scope",
                    info.ctx.path,
                    node.lineno,
                    node.col_offset,
                )


# ---------------------------------------------------------------------------
# ALZ073 — dtype discipline in the traced closure
# ---------------------------------------------------------------------------


def _f64_spelling(node: ast.AST) -> Optional[str]:
    """'float64'-meaning spelling of a dtype expression, or None."""
    if isinstance(node, ast.Attribute) and node.attr in _F64_SPELLINGS:
        return f".{node.attr}"
    if isinstance(node, ast.Name):
        if node.id in _F64_SPELLINGS:
            return node.id
        if node.id == "float":
            return "float (Python float IS float64)"
    if isinstance(node, ast.Constant) and node.value in ("float64", "f64", "double"):
        return repr(node.value)
    return None


def check_alz073(jm: JitModel) -> Iterable[Finding]:
    model = jm.model
    node_to_qn = {id(info.node): qn for qn, info in model.functions.items()}

    # the traced closure: wrapped fns + transitively resolved callees
    wrapped: List[str] = []
    for site in jm.sites:
        if site.fn_node is None:
            continue
        qn = node_to_qn.get(id(site.fn_node))
        if qn is not None:
            wrapped.append(qn)
    owner = _closure_from(jm, wrapped)
    wrapped_set = set(wrapped)

    seen: Set[Tuple[str, int, int]] = set()
    for qn in sorted(owner):
        info = model.functions[qn]
        in_wrapped = qn in wrapped_set
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            anchor = (info.ctx.path, node.lineno, node.col_offset)
            if anchor in seen:
                continue
            mod, name = _callee(node)
            # numpy f64-defaulting constructor inside the traced closure
            if (
                mod in _NUMPY_MODULES
                and name in _NP_F64_CONSTRUCTORS
                and not any(kw.arg == "dtype" for kw in node.keywords)
            ):
                if name in ("zeros", "ones", "empty") and len(node.args) >= 2:
                    continue  # dtype passed positionally
                if name == "full" and len(node.args) >= 3:
                    continue
                seen.add(anchor)
                yield Finding(
                    "ALZ073",
                    f"{mod}.{name}() without a dtype inside the traced "
                    "closure defaults to float64 — the constant enters "
                    "the jit body as f64 (promotion, or a silent cast "
                    "under disabled x64); pass dtype= or build it with "
                    "jnp",
                    info.ctx.path,
                    node.lineno,
                    node.col_offset,
                )
                continue
            # f64 spellings: helpers only for float64/f64 (ALZ024 owns
            # the directly-traced fn), but bare `float` everywhere (no
            # other rule sees it)
            hits: List[str] = []
            for kw in node.keywords:
                if kw.arg == "dtype":
                    sp = _f64_spelling(kw.value)
                    if sp is not None:
                        hits.append(f"dtype={sp}")
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and node.args
            ):
                sp = _f64_spelling(node.args[0])
                if sp is not None:
                    hits.append(f".astype({sp})")
            for hit in hits:
                if in_wrapped and "Python float" not in hit:
                    continue  # ALZ024's per-file territory
                seen.add(anchor)
                yield Finding(
                    "ALZ073",
                    f"{hit} requests float64 inside the traced closure "
                    f"(helper `{_final_name(qn)}`) — f64 never belongs "
                    "on the scorer device plane; use the compute dtype "
                    "or an explicit f32",
                    info.ctx.path,
                    node.lineno,
                    node.col_offset,
                )
