"""The jit-surface model: discovery of every transform construction
site, entry-surface reachability, and the retrace-budget link.

Discovery is *total*: every ``jax.jit`` / ``jax.vmap`` / ``jax.pmap`` /
``shard_map`` construction in the scanned tree becomes one ``JitSite``,
whether or not the entry surface reaches it — the golden surface spec
(jitgolden) then records reachability as a per-site fact instead of
silently narrowing the scan. Nested transform chains collapse onto one
site (``jax.jit(jax.vmap(f))`` is a single site with transforms
``["jit", "vmap"]``), and a by-name wrap whose resolved def carries its
own transform decorators extends the chain (``jax.jit(run)`` where
``run`` is ``@partial(shard_map, ...)``-decorated is
``["jit", "shard_map"]``).

Site keys are **position-free** (``module:enclosing_fn/wrapped_fn``,
with a ``#N`` ordinal only on collision) so the committed golden does
not churn when unrelated edits move line numbers.

Reachability mirrors alazflow: a worklist closure from the entry
surface — ``cmd_*`` / ``main`` functions, every method of a
``*Service`` class, and the ``train*`` / ``bench*`` families — through
resolved calls, callback references (``target=self._worker``), project
constructor calls, and nested defs. The closure is deliberately
conservative (a reachable function's nested defs are all reachable).

The budget link: ``sanitize/retrace.py``'s ``STEADY_STATE_BUDGETS``
keys are traced-fn *names* (CompileWatcher attributes compile events by
name). ``parse_budgets`` lifts that dict out of the scanned AST so the
ALZ074 coverage check can retire it as a hand-maintained drift risk:
every budgeted name must match a discovered site's wrapped fn.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.alazlint.core import FileContext
from tools.alazlint.jax_rules import (
    _call_transform_name,
    _establishes_compute_dtype,
    _static_names_from_call,
    _str_literals,
)
from tools.alazlint.program import (
    FunctionInfo,
    ProgramModel,
    _has_caching_decorator,
)

# the jit *surface*: transforms that stage a callable for the device.
# checkpoint/remat rewrite an already-traced region and never form a
# standalone entry, so they stay out of the surface (jax_rules still
# treats them as tracing scopes for the per-file rules).
SURFACE_TRANSFORMS = ("jit", "vmap", "pmap", "shard_map")

# jit/pmap are the compile-cache owners: a fresh construction of one of
# these is a fresh empty cache (ALZ070); a bare vmap/shard_map only
# costs a retrace of itself
_CACHE_OWNERS = ("jit", "pmap")

# loop contexts for the per-iteration taint: comprehensions included —
# `[run(n) for n in names]` re-invokes exactly like a for body
_LOOP_NODES = (
    ast.For,
    ast.While,
    ast.AsyncFor,
    ast.ListComp,
    ast.SetComp,
    ast.DictComp,
    ast.GeneratorExp,
)


def _walk_shallow(fn: ast.AST) -> Iterable[ast.AST]:
    """Body nodes of ``fn`` without descending into nested def/lambda
    bodies (the alazflow walk convention)."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue  # a nested def's body runs in its own scope
        stack.extend(ast.iter_child_nodes(node))


def _surface_name(call: ast.Call) -> Optional[str]:
    name = _call_transform_name(call)
    return name if name in SURFACE_TRANSFORMS else None


def _wrapped_expr(call: ast.Call) -> Optional[ast.AST]:
    """The fn-expression a transform call wraps (one step, no
    flattening): first positional arg, or the second for
    ``functools.partial(transform, fn)``."""
    fn_name = getattr(call.func, "attr", getattr(call.func, "id", None))
    args = call.args
    if fn_name == "partial":
        return args[1] if len(args) > 1 else None
    return args[0] if args else None


def _decorator_transforms(fn: ast.AST) -> List[Tuple[str, Optional[ast.Call]]]:
    """(transform name, decorator call | None) per surface-transform
    decorator on ``fn``, in source order."""
    out: List[Tuple[str, Optional[ast.Call]]] = []
    for dec in getattr(fn, "decorator_list", []):
        if isinstance(dec, ast.Call):
            name = _surface_name(dec)
            if name is not None:
                out.append((name, dec))
        elif isinstance(dec, (ast.Attribute, ast.Name)):
            nm = dec.attr if isinstance(dec, ast.Attribute) else dec.id
            if nm in SURFACE_TRANSFORMS:
                out.append((nm, None))
    return out


@dataclass
class JitSite:
    """One transform construction site: the unit the golden pins."""

    key: str  # "<module>:<enclosing fn>/<wrapped fn>" (+"#N" on collision)
    mod: str
    fn_name: str  # wrapped fn name ("<lambda>" for lambdas)
    transforms: List[str]  # outermost-first, e.g. ["jit", "vmap"]
    ctx: FileContext = field(repr=False)
    line: int = 0
    col: int = 0
    call: Optional[ast.Call] = field(default=None, repr=False)
    fn_node: Optional[ast.AST] = field(default=None, repr=False)  # resolved def
    static_args: List[str] = field(default_factory=list)
    cached_maker: bool = False
    reachable: bool = False
    encl_qualname: Optional[str] = None  # None for module-level sites

    @property
    def is_entry(self) -> bool:
        """Does this site own a compile cache (jit/pmap in the chain)?"""
        return any(t in _CACHE_OWNERS for t in self.transforms)

    def in_dtypes(self) -> str:
        """Dtype policy of the wrapped fn: 'polymorphic' when it works
        against a compute dtype (dtype param / compute_dtype() /
        .astype(dtype)), 'inherited' otherwise (dtypes ride in on the
        arguments), 'opaque' when the wrapped fn did not resolve."""
        node = self.fn_node
        if node is None:
            return "opaque"
        if isinstance(node, ast.FunctionDef) and _establishes_compute_dtype(node):
            return "polymorphic"
        return "inherited"

    def cache_key_family(self) -> str:
        """The compile-cache key family the site implies: 'cfg×shape'
        when a cached maker closes config into the trace (one cache per
        distinct config), plain 'shape' otherwise; static argnames ride
        the key too and are listed in their own golden field."""
        return "cfg×shape" if self.cached_maker else "shape"


def parse_budgets(
    ctxs: Sequence[FileContext],
) -> Tuple[Dict[str, int], Optional[FileContext], int]:
    """Lift STEADY_STATE_BUDGETS out of the scanned sanitize/retrace.py
    AST: {traced fn name -> budget}, plus the declaring ctx and line for
    finding anchors. Empty when the module isn't in the scan (fixtures)."""
    for ctx in ctxs:
        if not ctx.path.endswith("retrace.py"):
            continue
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                names = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                names = [stmt.target.id]
            else:
                continue
            if "STEADY_STATE_BUDGETS" not in names:
                continue
            if not isinstance(stmt.value, ast.Dict):
                continue
            out: Dict[str, int] = {}
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, int)
                ):
                    out[k.value] = v.value
            return out, ctx, stmt.lineno
    return {}, None, 0


class JitModel:
    """Sites + reachability + budgets over one invocation's files."""

    def __init__(self, ctxs: Sequence[FileContext]):
        self.ctxs = list(ctxs)
        self.model = ProgramModel(ctxs)
        self.budgets, self.budget_ctx, self.budget_line = parse_budgets(ctxs)
        self.reachable: Set[str] = self._close_reachable()
        self.loop_tainted: Set[str] = self._close_loop_taint()
        self.sites: List[JitSite] = self._discover()
        self.by_key: Dict[str, JitSite] = {s.key: s for s in self.sites}

    # -- reachability -------------------------------------------------------

    def _is_root(self, qn: str, info: FunctionInfo) -> bool:
        final = qn.split(":", 1)[-1].split(".")[-1]
        if final.startswith("cmd_") or final == "main":
            return True
        if final.startswith("train") or final.startswith("bench"):
            return True
        return info.cls is not None and info.cls.name.endswith("Service")

    def _resolve_ref(
        self,
        ref: ast.AST,
        mod: str,
        info: FunctionInfo,
        local_prefix: str,
    ) -> Optional[str]:
        """Function qualname a bare callback reference resolves to
        (``target=self._worker`` / ``submit(stage_fn, ...)``)."""
        if isinstance(ref, ast.Name):
            for cand in (f"{local_prefix}{ref.id}", f"{mod}:{ref.id}"):
                if cand in self.model.functions:
                    return cand
            target = self.model.imports.get(mod, {}).get(ref.id)
            if target and target in self.model.functions:
                return target
            return None
        if (
            isinstance(ref, ast.Attribute)
            and isinstance(ref.value, ast.Name)
            and ref.value.id == "self"
            and info.cls is not None
        ):
            cinfo = self.model.classes.get(f"{mod}:{info.cls.name}")
            if cinfo is not None:
                return cinfo.methods.get(ref.attr)
        return None

    def _resolve_module_attr_call(
        self, node: ast.Call, mod: str
    ) -> Optional[str]:
        """`tgn.make_step_fn(...)` where ``tgn`` arrived via
        ``from alaz_tpu.models import tgn`` — the from-imported-MODULE
        form ProgramModel.resolve_call does not chase (its import map
        records it as `alaz_tpu.models:tgn`)."""
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)):
            return None
        target = self.model.imports.get(mod, {}).get(fn.value.id)
        if target is None or ":" not in target:
            return None
        qn = f"{target.replace(':', '.')}:{fn.attr}"
        return qn if qn in self.model.functions else None

    def _resolve_reexport_call(
        self, node: ast.Call, mod: str
    ) -> Optional[str]:
        """`train_on_batches(...)` imported via a package re-export
        (``from alaz_tpu.train import train_on_batches`` where
        ``train/__init__.py`` re-exports it from ``trainstep``) —
        ProgramModel.resolve_call stops at the package's import target;
        chase the re-export chain a few hops to the defining module."""
        fn = node.func
        if not isinstance(fn, ast.Name):
            return None
        target = self.model.imports.get(mod, {}).get(fn.id)
        for _ in range(3):
            if target is None or target in self.model.functions:
                return target
            if ":" not in target:
                return None
            pkg, name = target.split(":", 1)
            target = self.model.imports.get(pkg, {}).get(name)
        return target if target in self.model.functions else None

    def resolve_call_ext(
        self,
        node: ast.Call,
        mod: str,
        cls,
        local_prefix: str,
    ) -> Optional[str]:
        """ProgramModel.resolve_call plus the from-imported-module and
        package-re-export forms — the one resolver every alazjit pass
        shares, so the traced closure and the reachability closure see
        the same call graph."""
        return (
            self.model.resolve_call(node, mod, cls, local_prefix)
            or self._resolve_module_attr_call(node, mod)
            or self._resolve_reexport_call(node, mod)
        )

    def _fn_edges(self, qn: str, info: FunctionInfo) -> Set[str]:
        mod = self.model.module_of[id(info.ctx)]
        local_prefix = qn + "."
        out: Set[str] = set()
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            target = self.resolve_call_ext(node, mod, info.cls, local_prefix)
            if target is not None:
                out.add(target)
            else:
                cls_qn = self.model.resolve_class(mod, node.func)
                if cls_qn is not None:
                    ctor = self.model.classes[cls_qn].methods.get("__init__")
                    if ctor is not None:
                        out.add(ctor)
            for ref in list(node.args) + [kw.value for kw in node.keywords]:
                t = self._resolve_ref(ref, mod, info, local_prefix)
                if t is not None:
                    out.add(t)
        # a reachable function's nested defs run on its behalf
        out.update(
            other for other in self.model.functions if other.startswith(local_prefix)
        )
        return out

    def _close_reachable(self) -> Set[str]:
        roots = {
            qn
            for qn, info in self.model.functions.items()
            if self._is_root(qn, info)
        }
        reached = set(roots)
        work = list(roots)
        while work:
            qn = work.pop()
            info = self.model.functions.get(qn)
            if info is None:
                continue
            for nxt in self._fn_edges(qn, info):
                if nxt not in reached:
                    reached.add(nxt)
                    work.append(nxt)
        return reached

    def _close_loop_taint(self) -> Set[str]:
        """Functions that run O(iterations) from the entry surface: the
        callee of any loop-resident call site in a *reachable* function
        is loop-called, and so (transitively) is everything it calls —
        ``main`` looping ``run_scenario(name)`` makes the whole
        detection leg per-iteration, three frames down. ALZ070 uses
        this to see an uncached maker re-invoked per iteration even
        when no loop is syntactically in sight at the maker call."""
        seeds: Set[str] = set()
        for qn, info in self.model.functions.items():
            if qn not in self.reachable:
                continue
            mod = self.model.module_of[id(info.ctx)]
            local_prefix = qn + "."
            for node in _walk_shallow(info.node):
                if not isinstance(node, ast.Call):
                    continue
                in_loop = False
                for anc in info.ctx.ancestors(node):
                    if anc is info.node:
                        break  # this function's own scope only
                    if isinstance(anc, _LOOP_NODES):
                        in_loop = True
                        break
                if not in_loop:
                    continue
                target = self.resolve_call_ext(node, mod, info.cls, local_prefix)
                if target is not None:
                    seeds.add(target)
        tainted: Set[str] = set()
        work = list(seeds)
        while work:
            qn = work.pop()
            if qn in tainted:
                continue
            tainted.add(qn)
            info = self.model.functions.get(qn)
            if info is None:
                continue
            mod = self.model.module_of[id(info.ctx)]
            local_prefix = qn + "."
            for node in _walk_shallow(info.node):
                if isinstance(node, ast.Call):
                    t = self.resolve_call_ext(node, mod, info.cls, local_prefix)
                    if t is not None and t not in tainted:
                        work.append(t)
            # nested defs run on the tainted fn's behalf
            work.extend(
                other
                for other in self.model.functions
                if other.startswith(local_prefix) and other not in tainted
            )
        return tainted

    # -- discovery ----------------------------------------------------------

    def _discover(self) -> List[JitSite]:
        raw: List[JitSite] = []
        for ctx in self.ctxs:
            raw.extend(self._discover_file(ctx))
        raw.sort(key=lambda s: (s.ctx.path, s.line, s.col))
        # ordinal suffix only on key collision, in (path, line) order
        counts: Dict[str, int] = {}
        for s in raw:
            counts[s.key] = counts.get(s.key, 0) + 1
        seen: Dict[str, int] = {}
        for s in raw:
            if counts[s.key] > 1:
                n = seen.get(s.key, 0) + 1
                seen[s.key] = n
                s.key = f"{s.key}#{n}"
        return raw

    def _discover_file(self, ctx: FileContext) -> Iterable[JitSite]:
        mod = self.model.module_of[id(ctx)]

        defs_by_name: Dict[str, List[ast.AST]] = {}
        for n in ast.walk(ctx.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(n.name, []).append(n)

        def enclosing_fn(node: ast.AST) -> Optional[ast.AST]:
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    return anc
            return None

        def resolve_def(name: str, call: ast.Call) -> Optional[ast.AST]:
            # same-name defs are common (every sharded maker nests a
            # `run`): prefer the candidate sharing the call's enclosing
            # function (the jax_rules.traced_functions convention)
            candidates = defs_by_name.get(name, [])
            if len(candidates) == 1:
                return candidates[0]
            if not candidates:
                return None
            home = enclosing_fn(call)
            local = [d for d in candidates if enclosing_fn(d) is home]
            return (local or candidates)[0]

        decorator_ids: Set[int] = set()
        for n in ast.walk(ctx.tree):
            for dec in getattr(n, "decorator_list", []):
                if isinstance(dec, ast.Call):
                    decorator_ids.add(id(dec))

        surface_calls = [
            n
            for n in ast.walk(ctx.tree)
            if isinstance(n, ast.Call) and _surface_name(n) is not None
        ]
        consumed: Set[int] = set()
        for c in surface_calls:
            w = _wrapped_expr(c)
            if isinstance(w, ast.Call) and _surface_name(w) is not None:
                consumed.add(id(w))

        folded_defs: Set[int] = set()  # defs whose decorators a call site absorbed
        sites: List[JitSite] = []

        for c in surface_calls:
            if id(c) in consumed or id(c) in decorator_ids:
                continue
            transforms: List[str] = []
            chain_calls: List[ast.Call] = []
            cur: ast.AST = c
            while isinstance(cur, ast.Call) and _surface_name(cur) is not None:
                transforms.append(_surface_name(cur))  # type: ignore[arg-type]
                chain_calls.append(cur)
                cur = _wrapped_expr(cur)
            if (
                isinstance(cur, ast.Call)
                and getattr(cur.func, "attr", getattr(cur.func, "id", None))
                == "partial"
                and cur.args
            ):
                # jit(partial(step, cfg=cfg)): the surface fn is step
                cur = cur.args[0]
            fn_node: Optional[ast.AST] = None
            fn_name = "<unresolved>"
            if isinstance(cur, ast.Lambda):
                fn_node, fn_name = cur, "<lambda>"
            elif isinstance(cur, ast.Name):
                fn_name = cur.id
                fn_node = resolve_def(cur.id, c)
            elif isinstance(cur, ast.Attribute):
                fn_name = cur.attr
            if fn_node is not None and not isinstance(fn_node, ast.Lambda):
                for tname, dcall in _decorator_transforms(fn_node):
                    transforms.append(tname)
                    if dcall is not None:
                        chain_calls.append(dcall)
                folded_defs.add(id(fn_node))
            sites.append(
                self._make_site(
                    ctx, mod, c, fn_node, fn_name, transforms, chain_calls
                )
            )

        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.FunctionDef) or id(n) in folded_defs:
                continue
            decs = _decorator_transforms(n)
            if not decs:
                continue
            transforms = [t for t, _ in decs]
            chain_calls = [dc for _, dc in decs if dc is not None]
            sites.append(
                self._make_site(ctx, mod, n, n, n.name, transforms, chain_calls)
            )
        return sites

    def _make_site(
        self,
        ctx: FileContext,
        mod: str,
        anchor: ast.AST,
        fn_node: Optional[ast.AST],
        fn_name: str,
        transforms: List[str],
        chain_calls: List[ast.Call],
    ) -> JitSite:
        static: Set[str] = set()
        static_call: Optional[ast.Call] = None
        for cc in chain_calls:
            if any(
                kw.arg in ("static_argnums", "static_argnames")
                for kw in cc.keywords
            ):
                static_call = cc
                break
        if static_call is not None:
            if fn_node is not None:
                static = _static_names_from_call(static_call, fn_node)
            else:
                for kw in static_call.keywords:
                    if kw.arg == "static_argnames":
                        static.update(_str_literals(kw.value))

        encl_parts: List[str] = []
        cached = False
        for anc in ctx.ancestors(anchor):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _has_caching_decorator(anc):
                    cached = True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                encl_parts.append(anc.name)
        encl_parts.reverse()
        # the wrapped def itself can carry the cache decorator too
        if fn_node is not None and _has_caching_decorator(fn_node):
            cached = True

        encl_qualname = f"{mod}:{'.'.join(encl_parts)}" if encl_parts else None
        if encl_qualname is None:
            reachable = True  # module level: constructed at import time
        else:
            reachable = encl_qualname in self.reachable
        encl_disp = ".".join(encl_parts) if encl_parts else "<module>"
        return JitSite(
            key=f"{mod}:{encl_disp}/{fn_name}",
            mod=mod,
            fn_name=fn_name,
            transforms=transforms,
            ctx=ctx,
            line=anchor.lineno,
            col=anchor.col_offset,
            call=chain_calls[0] if chain_calls else None,
            fn_node=fn_node,
            static_args=sorted(static),
            cached_maker=cached,
            reachable=reachable,
            encl_qualname=encl_qualname,
        )

    # -- shared lookups for the rules ---------------------------------------

    def site_fn_names(self) -> Set[str]:
        return {s.fn_name for s in self.sites}

    def maker_functions(self) -> Dict[str, JitSite]:
        """Enclosing-fn qualname -> its jit-bearing site, for every
        cache-owning site built inside a function (the maker pattern);
        the index ALZ070's caller-side checks dispatch on."""
        out: Dict[str, JitSite] = {}
        for s in self.sites:
            if s.is_entry and s.encl_qualname is not None:
                out.setdefault(s.encl_qualname, s)
        return out


# -- device-taint helpers shared by ALZ071/ALZ072 ---------------------------

_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
_SHAPE_CALLS = {"len"}


def device_names(node: ast.AST) -> Set[str]:
    """Names in ``node`` whose value would be a *device* tracer — the
    shape-aware twin of jax_rules._names_in. Subtrees that only read
    trace-time-static facts are skipped: ``x.shape`` / ``x.ndim`` /
    ``x.dtype`` / ``x.size`` attribute reads, ``len(x)``, and
    ``x is None`` / ``x is not None`` comparisons (branching on those is
    shape-safe Python, not data-dependent control flow)."""
    out: Set[str] = set()

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in _SHAPE_ATTRS:
            return
        if isinstance(n, ast.Call):
            fn = n.func
            if isinstance(fn, ast.Name) and fn.id in _SHAPE_CALLS:
                return
        if isinstance(n, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops
        ):
            others = [n.left] + list(n.comparators)
            if any(
                isinstance(o, ast.Constant) and o.value is None for o in others
            ):
                return
        if isinstance(n, ast.Name):
            out.add(n.id)
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return out


def local_device_taint(fn: ast.AST, seed: Set[str]) -> Set[str]:
    """Propagate device taint from ``seed`` params through simple
    assignments to a fixpoint, shape-aware: ``n = x.shape[0]`` does NOT
    taint ``n`` even when ``x`` is tainted."""
    tainted = set(seed)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for _ in range(10):
        before = len(tainted)
        for stmt in body:
            for node in ast.walk(stmt):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.For):
                    # `for i, x in enumerate(params)`: the index is a
                    # Python int even when the iterable is tainted
                    tgt: ast.AST = node.target
                    if (
                        isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "enumerate"
                        and isinstance(tgt, ast.Tuple)
                        and len(tgt.elts) == 2
                    ):
                        tgt = tgt.elts[1]
                    targets, value = [tgt], node.iter
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                if value is not None and (device_names(value) & tainted):
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
        if len(tainted) == before:
            break
    return tainted
