"""alazjit: device-plane static analysis — the seventh tier-1 head.

Discovers the whole jitted surface (every jit/vmap/pmap/shard_map
construction site) over the shared project model, lints the
retrace/host-sync/dtype hazards the CompileEventPlane can only report
after they bite (ALZ070-ALZ073), and pins the discovered surface as a
reviewed golden (resources/specs/jit_surface.json, ALZ074).
"""

from tools.alazjit.driver import jit_paths, jit_source, main

__all__ = ["jit_paths", "jit_source", "main"]
