#!/bin/bash
# Round-3 TPU bench capture: every metric the VERDICT asked for, run
# SERIALLY (one TPU process at a time — two concurrent benches starve
# each other and can wedge the accelerator tunnel). Each line lands in
# BENCH_MODELS_r03.json; the profiler trace lands in traces/.
#
#   bash tools/bench_r03.sh [out.json]
#
# Prereq: the accelerator answers (probe with a small matmul first).
set -u
cd "$(dirname "$0")/.."
OUT="${1:-BENCH_MODELS_r03.json}"
: > "$OUT"

run() { # run <label> <args...>
  local label="$1"; shift
  echo "== $label: python bench.py $*" >&2
  local line
  line=$(python bench.py --direct "$@" 2>/tmp/bench_r03_err.log | tail -1)
  rc=$?
  if [ -n "$line" ]; then
    echo "$line" >> "$OUT"
  else
    echo "{\"metric\": \"$label\", \"value\": 0, \"error\": \"empty output rc=$rc\"}" >> "$OUT"
  fi
  tail -2 /tmp/bench_r03_err.log >&2 || true
}

# headline (same invocation the driver makes) + MFU
run graphsage
# per-model single-chip numbers (BASELINE configs 3/4 evidence)
run gat      --model gat
run experts  --model experts
run tgn      --model tgn
# full-pipeline ingest->score rows/s
run e2e      --e2e
# locality study: adversarial uniform vs community+clustered (+banded kernel)
run layout-community          --structure community --layout random
run layout-clustered          --structure community --layout clustered
run layout-clustered-banded   --structure community --layout clustered --src-gather banded
# profiler trace (the :8181 pprof analog)
mkdir -p traces
run profile  --profile traces/r03_graphsage --iters 5 --repeats 1

echo "--- $OUT ---"
cat "$OUT"
