"""alaznat driver: parse native sources → offset/GIL rules → golden
cross-checks → C++ disable filter → report. Mirrors the alazrace driver
contract (same Finding type, same exit codes, `--write-offsets` like
`--write-threads`) so `make nat` and tier-1 read one uniform finding
stream — plus the dynamic half: `--sanitize` builds the ASan/UBSan
shared objects and drives the fuzz corpus through them, `--fuzz-run` is
the in-process worker those sanitized subprocesses execute.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from tools.alazlint.core import Finding
from tools.alaznat import natgolden, natrules
from tools.alaznat.natmodel import (
    NatSource,
    filter_native_disables,
    parse_native_source,
)

REPO = Path(__file__).resolve().parent.parent.parent

# what `make nat` / bench's nat_findings sweep: the native layer. The
# analyzer itself is Python and is covered by the five AST heads
# (tools/alaznat sits in `make lint`'s path list like its siblings).
DEFAULT_PATHS = (str(natgolden.NATIVE_DIR),)


def _collect(paths: Sequence[str]) -> Dict[Path, NatSource]:
    sources: Dict[Path, NatSource] = {}
    for p in paths:
        pth = Path(p)
        if pth.is_dir():
            for cc in sorted(pth.glob("*.cc")):
                sources[cc] = parse_native_source(cc)
        elif pth.suffix == ".cc" and pth.exists():
            sources[pth] = parse_native_source(pth)
    return sources


def _run_rules(
    sources: Dict[Path, NatSource], tree_mode: bool
) -> List[Finding]:
    """The static passes. ``tree_mode`` arms the golden checks (ALZ062
    drift + pinned-constant provenance) which are statements about the
    whole native tree — single-file/fixture runs get the local rules
    only, so scanning a fixture doesn't re-litigate the tree golden."""
    raw: List[Finding] = []
    for p, ns in sorted(sources.items()):
        role = natgolden.FILE_ROLES.get(p.name, "library")
        if role == "library":
            raw.extend(
                natrules.check_alz060_literals(
                    ns, natgolden.PINNED_CONSTANTS
                )
            )
        raw.extend(natrules.check_alz060_struct_drift(ns))
        raw.extend(natrules.check_alz061(ns))
    if tree_mode:
        raw.extend(natgolden.verify_pinned_constants())
        raw.extend(natgolden.check_alz062(sources))
    return filter_native_disables(raw, sources)


def nat_paths(
    paths: Sequence[str], tree_mode: bool = False
) -> List[Finding]:
    findings = _run_rules(_collect(paths), tree_mode)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def _print_findings(findings: List[Finding], as_json: bool, label: str) -> None:
    if as_json:
        print(
            json.dumps(
                {
                    "findings": [f.as_json() for f in findings],
                    "count": len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        print(f"{label}: {len(findings)} finding(s)")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if "--write-offsets" in argv:
        path = natgolden.write_offsets_golden()
        print(f"wrote {path}")
        return 0
    if "--fuzz-run" in argv:
        # worker mode: the whole corpus, in-process, against whatever
        # library ALZ_NATIVE_LIB points at (the sanitized .so when run
        # under --sanitize; the regular build when invoked by hand)
        from tools.alaznat import fuzz

        report = fuzz.run_fuzz()
        print(json.dumps(report, indent=2))
        return 1 if report["problems"] else 0
    if "--sanitize" in argv:
        from tools.alaznat import fuzz

        findings, skipped = fuzz.sanitize()
        if skipped is not None:
            print(f"alaznat: sanitize skipped — {skipped}", file=sys.stderr)
            return 0
        _print_findings(findings, as_json, "alaznat --sanitize")
        return 1 if findings else 0
    # the golden checks are statements about the WHOLE native tree —
    # they run on the default invocation (`make nat`); explicit paths
    # get the local rules only (the alazrace precedent)
    paths = argv or list(DEFAULT_PATHS)
    findings = nat_paths(paths, tree_mode=not argv)
    _print_findings(findings, as_json, "alaznat")
    return 1 if findings else 0
