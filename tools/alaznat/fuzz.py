"""The dynamic half of alaznat: a structured fuzz corpus driven through
all four native exports with the Python engine as bit-parity oracle,
under real ASan/UBSan builds of the ingest core.

Execution model: sanitized shared objects cannot be dlopen'd into a
stock interpreter (the sanitizer runtime must be the first DSO), so
``sanitize()`` builds ``libalaz_ingest.{asan,ubsan}.so`` and spawns one
subprocess per sanitizer with ``LD_PRELOAD=<runtime>`` and
``ALZ_NATIVE_LIB=<instrumented .so>`` — the seam graph/native._load()
honors — running ``python -m tools.alaznat --fuzz-run``, which replays
the whole corpus in-process. A sanitizer report aborts the subprocess
(abort_on_error / -fno-sanitize-recover), a parity divergence surfaces
as a problem line in the worker's JSON; either becomes an ALZ063
finding. The corpus itself lives in ``tests/nat_fixtures/corpus.json``
and replays sanitizer-free as tier-1 regression fixtures
(tests/test_alaznat.py), so every adversarial shape that ever drove the
sanitizers also gates every plain `make test` forever.

Corpus case shape::

    {"name": "...", "export": "group_edges" | "degree_cap" |
     "close_window" | "process_l7", "gen": {...}, "expect": "parity"}

``expect: "refused"`` marks inputs the native side must *decline* (return
the fall-back sentinel) rather than answer — e.g. ``cap == 0`` degree
sampling, where the C++ export returns -1 and the binding hands the
caller back to numpy.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from tools.alazlint.core import Finding

REPO = Path(__file__).resolve().parent.parent.parent
NATIVE_DIR = REPO / "alaz_tpu" / "native"
CORPUS_PATH = REPO / "tests" / "nat_fixtures" / "corpus.json"

_SUBPROCESS_TIMEOUT_S = 900

# sanitizer -> (instrumented lib, preloaded runtime, make target, env)
SANITIZERS = {
    "asan": (
        "libalaz_ingest.asan.so",
        "libasan.so",
        "asan",
        {"ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1"},
    ),
    "ubsan": (
        "libalaz_ingest.ubsan.so",
        "libubsan.so",
        "ubsan",
        {"UBSAN_OPTIONS": "halt_on_error=1,print_stacktrace=1"},
    ),
}


def load_corpus(path: Path = CORPUS_PATH) -> List[dict]:
    return json.loads(path.read_text())["cases"]


# -- generators (pure functions of the case's gen spec) ----------------------


def _rng(spec: dict):
    return np.random.default_rng(int(spec.get("seed", 0)))


def gen_group(spec: dict):
    """(keys, sum_cols, max_cols) for alz_group_edges. Columns are
    integer-valued float64 (< 2^53) so sums are order-independent and the
    parity check can demand EXACT equality."""
    rng = _rng(spec)
    n = int(spec.get("n", 0))
    mode = spec.get("keys", "random")
    if mode == "single":
        keys = np.full(n, 42, dtype=np.int64)
    elif mode == "extreme":
        keys = rng.integers(
            -(2**63), 2**63 - 1, n, dtype=np.int64, endpoint=True
        )
        if n >= 2:
            keys[0] = -(2**63)
            keys[-1] = 2**63 - 1
    else:
        keys = rng.integers(
            0, int(spec.get("key_space", 64)), n
        ).astype(np.int64)
    scale = int(spec.get("val_scale", 1000))
    sum_cols = [
        rng.integers(0, scale, n).astype(np.float64)
        for _ in range(int(spec.get("n_sum", 2)))
    ]
    max_cols = [
        rng.integers(0, scale, n).astype(np.float64)
        for _ in range(int(spec.get("n_max", 1)))
    ]
    return keys, sum_cols, max_cols


def gen_degree(spec: dict):
    """(dst_sorted, prio, cap) for alz_sample_degree_cap. dst arrives
    dst-sorted — the export's documented precondition (it runs over the
    already-grouped edge list alz_group_edges emits)."""
    rng = _rng(spec)
    n = int(spec.get("n", 0))
    mode = spec.get("dst", "random")
    if mode == "hot":
        dst = np.zeros(n, dtype=np.int32)
    else:
        dst = np.sort(
            rng.integers(0, int(spec.get("n_dst", 8)), n)
        ).astype(np.int32)
    pmode = spec.get("prio", "random")
    if pmode == "ties":
        prio = np.full(n, 7, dtype=np.uint64)
    elif pmode == "umax":
        prio = np.full(n, 2**64 - 1, dtype=np.uint64)
    else:
        prio = rng.integers(0, 2**64 - 1, n, dtype=np.uint64, endpoint=True)
    return dst, prio, int(spec.get("cap", 1))


def gen_close(spec: dict):
    """List of REQUEST_DTYPE parts for the windowed-store pair. Each
    part spec: {n, window_ms, ...mutations} — window_ms ordering across
    parts exercises window rolls and late stragglers."""
    from alaz_tpu.datastore.dto import EP_POD, EP_SERVICE, make_requests

    parts = []
    for i, p in enumerate(spec.get("parts", [])):
        rng = np.random.default_rng(int(spec.get("seed", 0)) * 1000 + i)
        n = int(p.get("n", 0))
        rows = make_requests(n)
        rows["from_uid"] = rng.integers(1, int(p.get("n_src", 15)) + 1, n)
        rows["to_uid"] = rng.integers(100, 100 + int(p.get("n_dst", 7)), n)
        rows["from_type"], rows["to_type"] = EP_POD, EP_SERVICE
        rows["protocol"] = rng.integers(1, 4, n)
        rows["latency_ns"] = rng.integers(10, 1000, n)
        rows["status_code"] = np.where(rng.random(n) < 0.1, 500, 200)
        rows["completed"] = True
        rows["start_time_ms"] = int(p.get("window_ms", 1000))
        if p.get("dup_edges"):
            rows["from_uid"] = 3
            rows["to_uid"] = 104
            rows["protocol"] = 1
        if p.get("hostile"):
            # extremes within contract: u32-max status, <2^53 latency
            # (float64-exact accumulation), u8-max protocol. uids stay
            # ≤ ~2^20: they are Interner-owned sequential ids, and the
            # python oracle's slot map is DENSE over max(uid) — a 2^31
            # uid would make the oracle allocate gigabytes, not expose
            # a native bug (the native side hashes, and never sees
            # non-interner uids in production)
            rows["status_code"] = 2**32 - 1
            rows["latency_ns"] = 2**52
            rows["protocol"] = 255
            rows["from_uid"] = 2**20 - 2
            rows["to_uid"] = 2**20 - 1
            rows["tls"] = True
            rows["completed"] = False
        parts.append(rows)
    return parts


def _v1ify(ev, frac: float, seed: int, orphan_frac: float = 0.0):
    """Blank embedded addresses on ``frac`` of rows; return the TCP
    events establishing the (pid, fd) socket lines that re-derive them
    (mirrors tests/test_engine_backend._v1ify — the V1 join path)."""
    from alaz_tpu.events.schema import TcpEventType, make_tcp_events

    rng = np.random.default_rng(seed)
    ev = ev.copy()
    n = ev.shape[0]
    v1 = rng.random(n) < frac
    idx = np.flatnonzero(v1)
    orphans = idx[rng.random(idx.shape[0]) < orphan_frac]
    ev["pid"][orphans] = 999_999
    keys = (ev["pid"][idx].astype(np.uint64) << np.uint64(32)) | ev["fd"][
        idx
    ].astype(np.uint64)
    _, first = np.unique(keys, return_index=True)
    first = first[ev["pid"][idx[first]] != 999_999]
    tcp = make_tcp_events(first.shape[0])
    tcp["pid"] = ev["pid"][idx[first]]
    tcp["fd"] = ev["fd"][idx[first]]
    tcp["timestamp_ns"] = 1
    tcp["type"] = TcpEventType.ESTABLISHED
    tcp["saddr"] = ev["saddr"][idx[first]]
    tcp["sport"] = ev["sport"][idx[first]]
    tcp["daddr"] = ev["daddr"][idx[first]]
    tcp["dport"] = ev["dport"][idx[first]]
    ev["saddr"][idx] = 0
    ev["sport"][idx] = 0
    ev["daddr"][idx] = 0
    ev["dport"][idx] = 0
    return ev, tcp


def gen_l7(spec: dict):
    """(ev, tcp, msgs, chunks) for the Aggregator A/B: a synth trace
    with adversarial mutations layered on."""
    from alaz_tpu.replay.synth import make_ingest_trace

    seed = int(spec.get("seed", 0))
    n = int(spec.get("n", 0))
    ev, msgs = make_ingest_trace(
        max(n, 32),
        pods=int(spec.get("pods", 20)),
        svcs=int(spec.get("svcs", 4)),
        windows=int(spec.get("windows", 2)),
        seed=seed,
    )
    ev = ev[:n]
    if spec.get("dup_conn"):
        ev["pid"] = 4242
        ev["fd"] = 7
    tcp = None
    if float(spec.get("v1_frac", 0.0)) > 0:
        ev, tcp = _v1ify(
            ev,
            frac=float(spec["v1_frac"]),
            seed=seed,
            orphan_frac=float(spec.get("orphan_frac", 0.0)),
        )
    if spec.get("truncated"):
        # hostile payload accounting: the count field claims more bytes
        # than the 256-byte payload buffer holds — the native pass must
        # never trust payload_size as a read length
        half = ev.shape[0] // 2
        ev["payload_size"][:half] = 2**32 - 1
        ev["payload_read_complete"][:half] = False
        ev["payload_size"][half:] = 300
        ev["payload_read_complete"][half:] = True
    if spec.get("hostile"):
        rng = np.random.default_rng(seed + 1)
        m = ev.shape[0]
        ev["status"] = rng.choice(
            np.array([0, 99, 2**31, 2**32 - 1], dtype=np.uint64), m
        )
        ev["duration_ns"] = rng.choice(
            np.array([0, 1, 2**52], dtype=np.uint64), m
        )
        ev["method"] = 255
        ev["protocol"] = rng.choice(
            np.array([0, 9, 200, 255], dtype=np.uint8), m
        )
        ev["kafka_api_version"] = -1
        ev["mysql_prep_stmt_id"] = 2**32 - 1
        ev["tid"] = 2**32 - 1
        ev["seq"] = 2**32 - 1
    return ev, tcp, msgs, [int(c) for c in spec.get("chunks", [])]


# -- runners (native vs Python-oracle, exact comparisons) --------------------


def _force_numpy_grouping():
    from alaz_tpu.graph import builder

    builder.set_native_grouping(False)


def _reset_grouping():
    from alaz_tpu.graph import builder

    builder.set_native_grouping(None)


def run_group(case: dict) -> List[str]:
    from alaz_tpu.graph import builder, native

    keys, sc, mc = gen_group(case.get("gen", {}))
    got = native.group_edges(keys, sc, mc)
    if got is None:
        return ["native group_edges unavailable (library not loaded)"]
    _force_numpy_grouping()
    try:
        want = builder.group_reduce(keys, sc, mc)
    finally:
        _reset_grouping()
    problems: List[str] = []
    gk, gc, gr, gs, gm = got
    wk, wc, wr, ws, wm = want
    if not np.array_equal(gk, wk):
        problems.append("group keys diverge from numpy oracle")
    if not np.array_equal(gc, wc):
        problems.append("group counts diverge from numpy oracle")
    # rep is any-member-valid by contract: check membership, not identity
    if gk.shape == wk.shape and gk.shape[0] and not np.array_equal(
        keys[gr], gk
    ):
        problems.append("group rep indices point outside their groups")
    for i, (a, b) in enumerate(zip(gs, ws)):
        if not np.array_equal(a, b):
            problems.append(f"group sum col {i} diverges from numpy oracle")
    for i, (a, b) in enumerate(zip(gm, wm)):
        if not np.array_equal(a, b):
            problems.append(f"group max col {i} diverges from numpy oracle")
    return problems


def run_degree(case: dict) -> List[str]:
    from alaz_tpu.graph import builder, native

    dst, prio, cap = gen_degree(case.get("gen", {}))
    got = native.sample_degree_cap(dst, prio, cap)
    if case.get("expect") == "refused":
        return (
            []
            if got is None
            else ["native sample_degree_cap answered an input it must refuse"]
        )
    if got is None:
        return ["native sample_degree_cap unavailable (library not loaded)"]
    _force_numpy_grouping()
    try:
        want = builder.degree_cap_select(dst, prio, cap)
    finally:
        _reset_grouping()
    if not np.array_equal(got, want):
        return [
            f"degree-cap kept set diverges: native {got.shape[0]} rows "
            f"vs numpy {want.shape[0]}"
        ]
    return []


def _edge_map(b) -> Dict[tuple, np.ndarray]:
    uids = b.node_uids
    return {
        (
            int(uids[b.edge_src[i]]),
            int(uids[b.edge_dst[i]]),
            int(b.edge_type[i]),
        ): b.edge_feats[i]
        for i in range(b.n_edges)
    }


def run_close(case: dict) -> List[str]:
    from alaz_tpu.events.intern import Interner
    from alaz_tpu.graph import native
    from alaz_tpu.graph.builder import WindowedGraphStore

    spec = case.get("gen", {})
    parts = gen_close(spec)
    kwargs = {}
    if "degree_cap" in spec:
        kwargs = {
            "degree_cap": int(spec["degree_cap"]),
            "sample_seed": int(spec.get("sample_seed", 11)),
        }
    try:
        ns = native.NativeWindowedStore(window_s=1.0, **kwargs)
    except RuntimeError:
        return ["native windowed store unavailable (library not loaded)"]
    try:
        for p in parts:
            ns.persist_requests(p.copy())
        ns.flush()
    finally:
        ns.close()
    ps = WindowedGraphStore(Interner(), window_s=1.0, **kwargs)
    _force_numpy_grouping()
    try:
        for p in parts:
            ps.persist_requests(p.copy())
        ps.flush()
    finally:
        _reset_grouping()
    problems: List[str] = []
    nw = [b.window_start_ms for b in ns.batches]
    pw = [b.window_start_ms for b in ps.batches]
    if nw != pw:
        return [f"window sequence diverges: native {nw} vs numpy {pw}"]
    for nb, pb in zip(ns.batches, ps.batches):
        m1, m2 = _edge_map(nb), _edge_map(pb)
        if set(m1) != set(m2):
            problems.append(
                f"window {nb.window_start_ms}: edge key sets diverge "
                f"({len(m1)} native vs {len(m2)} numpy)"
            )
            continue
        for k in m1:
            if not np.allclose(m1[k], m2[k], atol=1e-6):
                problems.append(
                    f"window {nb.window_start_ms}: edge {k} features diverge"
                )
                break
    return problems


def _serial_rows(ev, tcp, msgs, native_engine: bool, chunks, rate_limit=None):
    """One serial Aggregator run (mirrors tests/test_engine_backend.
    _run_serial_rows): returns (REQUEST rows incl. retry flushes, stats
    dict, ledger snapshot)."""
    from alaz_tpu.aggregator.cluster import ClusterInfo
    from alaz_tpu.aggregator.engine import Aggregator, set_native_engine
    from alaz_tpu.datastore.inmem import InMemDataStore
    from alaz_tpu.events.intern import Interner

    set_native_engine(native_engine)
    try:
        interner = Interner()
        ds = InMemDataStore(retain=True)
        cluster = ClusterInfo(interner)
        for m in msgs:
            cluster.handle_msg(m)
        agg = Aggregator(ds, interner=interner, cluster=cluster)
        if rate_limit is not None:
            agg.rate_limit = rate_limit
        if tcp is not None and tcp.shape[0]:
            agg.process_tcp(tcp, now_ns=10_000_000_000)
        outs = []
        lo = 0
        for hi in list(chunks) + [ev.shape[0]]:
            if hi > lo:
                outs.append(agg.process_l7(ev[lo:hi], now_ns=10_000_000_000))
                lo = hi
        for dt in (25_000_000, 75_000_000, 200_000_000):
            r = agg.flush_retries(10_000_000_000 + dt)
            if r is not None:
                outs.append(r)
        rows = (
            np.concatenate(outs)
            if outs
            else np.zeros(0, ds.all_requests().dtype)
        )
        return rows, agg.stats.as_dict(), agg.ledger.snapshot()
    finally:
        set_native_engine(None)


def run_l7(case: dict) -> List[str]:
    from alaz_tpu.aggregator import native_l7

    if not native_l7.available():
        return ["native L7 engine unavailable (library not loaded)"]
    ev, tcp, msgs, chunks = gen_l7(case.get("gen", {}))
    p_rows, p_stats, p_led = _serial_rows(ev, tcp, msgs, False, chunks)
    n_rows, n_stats, n_led = _serial_rows(ev, tcp, msgs, True, chunks)
    problems: List[str] = []
    if not np.array_equal(p_rows, n_rows):
        problems.append(
            f"REQUEST rows diverge: python {p_rows.shape[0]} "
            f"vs native {n_rows.shape[0]} rows (or payload bytes differ)"
        )
    if p_stats != n_stats:
        keys = [k for k in p_stats if p_stats[k] != n_stats.get(k)]
        problems.append(f"stats diverge on {keys}")
    if p_led != n_led:
        problems.append("drop-ledger snapshots diverge")
    return problems


_RUNNERS = {
    "group_edges": run_group,
    "degree_cap": run_degree,
    "close_window": run_close,
    "process_l7": run_l7,
}


def run_case(case: dict) -> List[str]:
    return _RUNNERS[case["export"]](case)


def run_fuzz(corpus_path: Path = CORPUS_PATH) -> dict:
    """The whole corpus, in-process, against whatever library the
    binding resolves (ALZ_NATIVE_LIB under --sanitize). Returns
    {"cases": n, "problems": [{"case", "export", "problem"}, ...]}."""
    cases = load_corpus(corpus_path)
    problems: List[dict] = []
    for case in cases:
        for p in run_case(case):
            problems.append(
                {"case": case["name"], "export": case["export"], "problem": p}
            )
    return {"cases": len(cases), "problems": problems}


# -- sanitizer orchestration -------------------------------------------------


def _runtime_path(runtime: str) -> Optional[str]:
    gcc = shutil.which("gcc") or shutil.which("cc")
    if gcc is None:
        return None
    try:
        out = subprocess.run(
            [gcc, f"-print-file-name={runtime}"],
            capture_output=True,
            text=True,
            timeout=30,
        ).stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        return None
    # gcc echoes the bare name back when it has no such runtime
    return out if out and os.path.sep in out and Path(out).exists() else None


def toolchain_gap() -> Optional[str]:
    """Why sanitize() cannot run here, or None when it can. No-install
    discipline: a missing compiler/runtime is a graceful skip, never an
    attempted install."""
    if shutil.which("g++") is None and shutil.which("c++") is None:
        return "no C++ compiler on PATH"
    for _, (_, runtime, _, _) in SANITIZERS.items():
        if _runtime_path(runtime) is None:
            return f"gcc has no {runtime} runtime"
    return None


def _finding(msg: str) -> Finding:
    return Finding("ALZ063", msg, str(NATIVE_DIR / "ingest.cc"), 1, 0)


def sanitize() -> Tuple[List[Finding], Optional[str]]:
    """Build the ASan/UBSan libraries and replay the corpus under each.
    Returns (findings, skip_reason): skip_reason is non-None only when
    the toolchain cannot run sanitizers at all (then findings is [])."""
    gap = toolchain_gap()
    if gap is not None:
        return [], gap
    build = subprocess.run(
        ["make", "-C", str(NATIVE_DIR), "asan", "ubsan"],
        capture_output=True,
        text=True,
        timeout=_SUBPROCESS_TIMEOUT_S,
    )
    if build.returncode != 0:
        return [
            _finding(
                "sanitizer build failed (make -C alaz_tpu/native asan "
                f"ubsan):\n{build.stdout[-1500:]}{build.stderr[-1500:]}"
            )
        ], None
    findings: List[Finding] = []
    for san, (libname, runtime, _, opts) in SANITIZERS.items():
        rt = _runtime_path(runtime)
        env = os.environ.copy()
        env.update(opts)
        env["LD_PRELOAD"] = rt or ""
        env["ALZ_NATIVE_LIB"] = str(NATIVE_DIR / libname)
        env["JAX_PLATFORMS"] = "cpu"
        try:
            run = subprocess.run(
                [sys.executable, "-m", "tools.alaznat", "--fuzz-run"],
                capture_output=True,
                text=True,
                cwd=str(REPO),
                env=env,
                timeout=_SUBPROCESS_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            findings.append(_finding(f"{san} fuzz run timed out"))
            continue
        report = None
        try:
            report = json.loads(run.stdout)
        except json.JSONDecodeError:
            pass
        bad = (
            "ERROR: AddressSanitizer" in run.stderr
            or "runtime error:" in run.stderr
            or "ERROR: UndefinedBehaviorSanitizer" in run.stderr
        )
        if bad or (run.returncode != 0 and report is None):
            findings.append(
                _finding(
                    f"{san} fuzz run failed (rc={run.returncode}):\n"
                    f"{run.stderr[-2000:]}"
                )
            )
            continue
        for p in (report or {}).get("problems", [])[:20]:
            findings.append(
                _finding(
                    f"{san} corpus case {p['case']} ({p['export']}): "
                    f"{p['problem']}"
                )
            )
    return findings, None
