"""ALZ060/ALZ061 — the static half of alaznat.

ALZ060 (offset/magic discipline): every integer constant in the native
batch passes must be *derivable* — from a layout alazspec pins in
``resources/specs/wire_layouts.json`` (struct totals, field offsets,
field sizes — cstructs, dtype mirrors, l7_engine input/output, shm_ring
headers, frame constants), from the file's own enums/constexprs (which
the golden offset map pins), or from the pinned-constant table
(``nat_offsets.json`` — hash mixers, conn-key, time-unit constants, each
with a Python-side provenance that is re-verified live). A bare
``memcpy(dst + 75, ...)`` with no pinned layout deriving 75 is exactly
the drift this head exists to catch. The same pass cross-checks the
pack(1)-aware struct layouts parsed from source against the golden wire
table — the triangle alazspec cannot close (its parser models neither
``#pragma pack`` nor array fields).

ALZ061 (GIL discipline): every export is called through ctypes, which
releases the GIL for the duration of the call — the whole native layer
is one GIL-dropped region. Any CPython API use (``Py*`` identifier,
``Python.h`` include) reachable there is a crash waiting for a second
thread; the rule bans the tokens outright, disable-escapable like every
other rule.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set

from tools.alazlint.core import Finding
from tools.alaznat.natmodel import NatSource

REPO = Path(__file__).resolve().parent.parent.parent
WIRE_LAYOUTS = REPO / "resources" / "specs" / "wire_layouts.json"

# values below this are index/shift/arity furniture, not byte offsets
_SMALL = 64

# structs whose parsed layout must byte-match a pinned wire-table layout
# of the same struct name (the source↔golden leg of the triangle; the
# golden↔dtype and dtype↔binary legs are alazspec ALZ020/ALZ021)
_WIRE_STRUCT_NAMES = (
    "AlzRecord", "EdgeSlot", "NodeSlot", "AlzL7Event", "AlzRequest",
)


def _is_pow2ish(v: int) -> bool:
    """Powers of two and all-ones masks: structural capacities,
    alignments, and bit masks — not byte-layout knowledge."""
    if v <= 0:
        return False
    return (v & (v - 1)) == 0 or (v & (v + 1)) == 0


def _layout_numbers(layout: str) -> Set[int]:
    """Every number a pinned layout string derives: total size, field
    offsets, field sizes, and offset+size end positions (the natural
    operands of a bounds check or a tail memset)."""
    out: Set[int] = set()
    parts = layout.split(";")
    head = parts[0].split(":")
    if len(head) == 2 and head[1].isdigit():
        out.add(int(head[1]))
    for p in parts[1:]:
        bits = p.split(":")
        if len(bits) == 3 and bits[1].isdigit() and bits[2].isdigit():
            off, sz = int(bits[1]), int(bits[2])
            out.update((off, sz, off + sz))
    return out


def _walk_wire(node) -> Iterable:
    if isinstance(node, dict):
        for v in node.values():
            yield from _walk_wire(v)
    elif isinstance(node, list):
        for v in node:
            yield from _walk_wire(v)
    else:
        yield node


def wire_numbers(wire_path: Path = WIRE_LAYOUTS) -> Set[int]:
    """All integers derivable from the golden wire table: layout-string
    numbers plus plain numeric pins (frame magic/header_size, shm magic,
    priority-mix constants, version fields)."""
    try:
        wire = json.loads(wire_path.read_text())
    except (OSError, json.JSONDecodeError):
        return set()
    out: Set[int] = set()
    for leaf in _walk_wire(wire):
        if isinstance(leaf, bool):
            continue
        if isinstance(leaf, int):
            out.add(leaf)
        elif isinstance(leaf, str):
            if ";" in leaf and ":" in leaf:
                out |= _layout_numbers(leaf)
            elif leaf.lower().startswith("0x"):
                try:
                    out.add(int(leaf, 16))
                except ValueError:
                    pass
    return out


def wire_layout_strings(wire_path: Path = WIRE_LAYOUTS) -> Dict[str, str]:
    """struct name -> pinned layout string, for every layout string in
    the wire table (cstructs, dtype mirrors, l7_engine, shm_ring)."""
    try:
        wire = json.loads(wire_path.read_text())
    except (OSError, json.JSONDecodeError):
        return {}
    out: Dict[str, str] = {}
    for leaf in _walk_wire(wire):
        if isinstance(leaf, str) and ";" in leaf and ":" in leaf:
            name = leaf.split(":", 1)[0]
            out.setdefault(name, leaf)
    return out


def derivable_numbers(
    ns: NatSource,
    pinned: Dict[int, str],
    wire_path: Path = WIRE_LAYOUTS,
) -> Set[int]:
    out = wire_numbers(wire_path)
    out |= set(pinned)
    for layout in ns.structs.values():
        out |= _layout_numbers(layout.layout_string())
    for values in ns.enums.values():
        out |= set(values.values())
    out |= set(ns.constexprs.values())
    return out


def check_alz060_literals(
    ns: NatSource, pinned: Dict[int, str], wire_path: Path = WIRE_LAYOUTS
) -> List[Finding]:
    derivable = derivable_numbers(ns, pinned, wire_path)
    out: List[Finding] = []
    for lit in ns.literals:
        v = lit.value
        if v < _SMALL or _is_pow2ish(v) or v in derivable:
            continue
        out.append(
            Finding(
                "ALZ060",
                f"magic number {lit.token} is not derivable from any "
                "pinned layout — byte offsets/strides/sizes in the native "
                "batch passes must come from a wire-table layout "
                "(resources/specs/wire_layouts.json), an in-file "
                "enum/constexpr, or the pinned-constant table "
                "(resources/specs/nat_offsets.json); pin it with a "
                "provenance or derive it from the struct",
                str(ns.path),
                lit.line,
                lit.col,
            )
        )
    return out


def check_alz060_struct_drift(
    ns: NatSource, wire_path: Path = WIRE_LAYOUTS
) -> List[Finding]:
    """source structs vs golden wire layouts + static_assert pins."""
    out: List[Finding] = []
    pinned_layouts = wire_layout_strings(wire_path)
    for name in _WIRE_STRUCT_NAMES:
        layout = ns.structs.get(name)
        if layout is None:
            continue
        want = pinned_layouts.get(name)
        if want is None:
            out.append(
                Finding(
                    "ALZ060",
                    f"struct {name} has no pinned layout in the wire table "
                    f"({wire_path.name}) — a wire struct must be pinned "
                    "before native code does byte math over it "
                    "(`make specs` regenerates)",
                    str(ns.path),
                    1,
                    0,
                )
            )
            continue
        got = layout.layout_string()
        if got != want:
            out.append(
                Finding(
                    "ALZ060",
                    f"struct {name} drifted from its pinned wire layout:\n"
                    f"  source: {got}\n  golden: {want}\n"
                    "— realign the struct or regenerate the goldens "
                    "(`make specs`) and review the diff",
                    str(ns.path),
                    1,
                    0,
                )
            )
    for sname, size in ns.size_asserts:
        layout = ns.structs.get(sname)
        if layout is not None and layout.size != size:
            out.append(
                Finding(
                    "ALZ060",
                    f"static_assert pins sizeof({sname}) == {size} but the "
                    f"declared fields lay out to {layout.size} bytes — the "
                    "assert and the struct tell different stories",
                    str(ns.path),
                    1,
                    0,
                )
            )
    return out


# -- ALZ061: GIL discipline --------------------------------------------------

import re as _re

_PY_API_RE = _re.compile(r"\bPy[A-Z_]\w*")
_PYTHON_H_RE = _re.compile(r'#\s*include\s*[<"][^>"]*Python\.h[>"]')


def check_alz061(ns: NatSource) -> List[Finding]:
    out: List[Finding] = []
    for ln, line in enumerate(ns.stripped.split("\n"), 1):
        m = _PYTHON_H_RE.search(line)
        if m is not None:
            out.append(
                Finding(
                    "ALZ061",
                    "Python.h included in GIL-dropped native code — every "
                    "export here runs with the GIL released (ctypes drops "
                    "it for the duration of the call); CPython API use is "
                    "a crash under any concurrent Python thread",
                    str(ns.path),
                    ln,
                    m.start(),
                )
            )
            continue
        m = _PY_API_RE.search(line)
        if m is not None:
            out.append(
                Finding(
                    "ALZ061",
                    f"CPython API token `{m.group(0)}` in GIL-dropped "
                    "native code — the ctypes boundary releases the GIL "
                    "around every export, so no Py* call is safe anywhere "
                    "in this layer; marshal through plain buffers instead",
                    str(ns.path),
                    ln,
                    m.start(),
                )
            )
    return out
