import sys

from tools.alaznat.driver import main

if __name__ == "__main__":
    sys.exit(main())
