"""C-source facts for the alaznat rules: struct layouts (pack(1)- and
array-aware — the two shapes tools/alazspec/cstructs.py deliberately
does not model), enum/constexpr constants, static_assert size pins,
integer-literal sites, and the C++ disable-comment scan.

The parser is a restricted-subset extractor exactly like alazspec's
``CSource``: it parses the declaration shapes ingest.cc actually uses
and records anything else as opaque (a functor struct, a struct holding
atomics/vectors/methods). That keeps it honest — the five wire structs
(AlzRecord, EdgeSlot, NodeSlot, AlzL7Event, AlzRequest) parse fully and
cross-check against the golden wire table; everything the parser cannot
lay out is excluded from the derivable set rather than guessed at.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# fixed-width scalar sizes the native sources use (size, natural align)
_TYPE_SIZES = {
    "uint8_t": 1, "int8_t": 1, "char": 1, "bool": 1,
    "uint16_t": 2, "int16_t": 2,
    "uint32_t": 4, "int32_t": 4, "int": 4, "unsigned": 4, "float": 4,
    "uint64_t": 8, "int64_t": 8, "size_t": 8, "double": 8,
}

_STRUCT_RE = re.compile(r"^struct\s+(\w+)\s*\{", re.M)
_FIELD_RE = re.compile(r"^\s*([A-Za-z_]\w*)\s+(\w+)\s*(?:\[(\d+)\])?\s*;\s*$")
_ENUM_RE = re.compile(r"^enum\s+(\w+)\s*\{(.*?)\};", re.M | re.S)
_CONSTEXPR_RE = re.compile(
    r"^\s*constexpr\s+[\w:]+\s+(\w+)\s*=\s*"
    r"(0[xX][0-9a-fA-F]+|\d+)\s*(?:<<\s*(\d+))?", re.M
)
_STATIC_ASSERT_RE = re.compile(
    r"static_assert\s*\(\s*sizeof\s*\(\s*(\w+)\s*\)\s*==\s*(\d+)"
)
_PRAGMA_PACK_RE = re.compile(r"#pragma\s+pack\s*\(\s*(push\s*,\s*1|pop)\s*\)")

# integer literals with their suffixes; the stripped source has no
# strings/comments left, so a bare regex cannot false-positive on text
_INT_LIT_RE = re.compile(
    r"\b(0[xX][0-9a-fA-F]+|\d+)(?:[uU]?[lL]{0,2}|[lL]{1,2}[uU]?)\b"
)

# C++ analog of the core's ``# alazlint: disable=...`` comment — scanned
# from the RAW source (comments survive there), same-line suppression,
# same justification discipline (ALZ000 on a bare disable)
_DISABLE_RE = re.compile(
    r"//\s*alazlint:\s*disable=([A-Z0-9,\s]+?)(?:\s+--\s+(\S.*))?\s*$"
)


def strip_comments(src: str) -> str:
    """Remove //, /* */ comments and string/char literal CONTENTS while
    preserving the line structure, so reported line numbers stay true."""
    out: List[str] = []
    i, n = 0, len(src)
    while i < n:
        if src.startswith("//", i):
            j = src.find("\n", i)
            i = n if j < 0 else j  # keep the newline itself
        elif src.startswith("/*", i):
            j = src.find("*/", i + 2)
            seg = src[i:(j + 2 if j >= 0 else n)]
            out.append("\n" * seg.count("\n"))
            i = n if j < 0 else j + 2
        elif src[i] in "\"'":
            q = src[i]
            j = i + 1
            while j < n and src[j] != q:
                j += 2 if src[j] == "\\" else 1
            out.append(q + q)  # empty literal placeholder
            i = j + 1
        else:
            out.append(src[i])
            i += 1
    return "".join(out)


@dataclass
class CField:
    name: str
    offset: int
    size: int


@dataclass
class CStructLayout:
    name: str
    size: int
    packed: bool
    fields: List[CField] = field(default_factory=list)

    def layout_string(self) -> str:
        """Same rendering as events/schema.dtype_layout and alazspec's
        ``CStruct.layout_string`` — the cross-check currency."""
        parts = [f"{self.name}:{self.size}"]
        parts += [f"{f.name}:{f.offset}:{f.size}" for f in self.fields]
        return ";".join(parts)


@dataclass
class LiteralSite:
    line: int
    col: int
    token: str  # as written, suffix included
    value: int


@dataclass
class NatSource:
    """Parsed facts of one native source file."""

    path: Path
    source: str  # raw, comments intact (disable scan)
    stripped: str  # comment/string-stripped, lines preserved
    structs: Dict[str, CStructLayout] = field(default_factory=dict)
    opaque_structs: List[str] = field(default_factory=list)
    enums: Dict[str, Dict[str, int]] = field(default_factory=dict)
    constexprs: Dict[str, int] = field(default_factory=dict)
    size_asserts: List[Tuple[str, int]] = field(default_factory=list)
    literals: List[LiteralSite] = field(default_factory=list)
    # line -> {code or "" (all codes): justification or None}
    disables: Dict[int, Dict[str, Optional[str]]] = field(default_factory=dict)


def _layout(name: str, body: str, packed: bool) -> Optional[CStructLayout]:
    """SysV layout of a plain-field struct body; None when any line is
    not a ``type name;`` / ``type name[N];`` declaration (opaque)."""
    fields: List[CField] = []
    off = 0
    max_align = 1
    for raw in body.split("\n"):
        line = raw.strip()
        if not line:
            continue
        m = _FIELD_RE.match(line)
        if m is None:
            return None
        tname, fname, count = m.group(1), m.group(2), m.group(3)
        elem = _TYPE_SIZES.get(tname)
        if elem is None:
            return None
        size = elem * int(count) if count else elem
        align = 1 if packed else min(elem, 8)
        max_align = max(max_align, align)
        off = (off + align - 1) // align * align
        fields.append(CField(fname, off, size))
        off += size
    total = (off + max_align - 1) // max_align * max_align
    return CStructLayout(name, total, packed, fields)


def _brace_span(src: str, open_idx: int) -> int:
    """Index just past the ``}`` matching the ``{`` at open_idx."""
    depth = 0
    for i in range(open_idx, len(src)):
        if src[i] == "{":
            depth += 1
        elif src[i] == "}":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(src)


def _packed_regions(stripped: str) -> List[Tuple[int, int]]:
    """[start, end) character spans under ``#pragma pack(push, 1)``."""
    spans: List[Tuple[int, int]] = []
    start: Optional[int] = None
    for m in _PRAGMA_PACK_RE.finditer(stripped):
        if m.group(1).startswith("push"):
            if start is None:
                start = m.end()
        else:
            if start is not None:
                spans.append((start, m.start()))
                start = None
    if start is not None:
        spans.append((start, len(stripped)))
    return spans


def _parse_enum_body(body: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    nxt = 0
    for part in body.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, _, val = part.partition("=")
            try:
                nxt = int(val.strip(), 0)
            except ValueError:
                continue
            out[name.strip()] = nxt
        else:
            out[part] = nxt
        nxt += 1
    return out


def _scan_disables(source: str) -> Dict[int, Dict[str, Optional[str]]]:
    out: Dict[int, Dict[str, Optional[str]]] = {}
    for ln, line in enumerate(source.split("\n"), 1):
        m = _DISABLE_RE.search(line)
        if m is None:
            continue
        why = m.group(2)
        codes = [c.strip() for c in m.group(1).split(",") if c.strip()]
        entry = out.setdefault(ln, {})
        for code in codes:
            entry[code] = why
    return out


def parse_native_source(path: Path) -> NatSource:
    source = path.read_text()
    stripped = strip_comments(source)
    ns = NatSource(path=path, source=source, stripped=stripped)

    packed_spans = _packed_regions(stripped)

    def is_packed(idx: int) -> bool:
        return any(a <= idx < b for a, b in packed_spans)

    for m in _STRUCT_RE.finditer(stripped):
        open_idx = stripped.index("{", m.start())
        end = _brace_span(stripped, open_idx)
        body = stripped[open_idx + 1 : end - 1]
        layout = _layout(m.group(1), body, is_packed(m.start()))
        if layout is None:
            ns.opaque_structs.append(m.group(1))
        else:
            ns.structs[layout.name] = layout

    for m in _ENUM_RE.finditer(stripped):
        ns.enums[m.group(1)] = _parse_enum_body(m.group(2))

    for m in _CONSTEXPR_RE.finditer(stripped):
        val = int(m.group(2), 0)
        if m.group(3):
            val <<= int(m.group(3))
        ns.constexprs[m.group(1)] = val

    for m in _STATIC_ASSERT_RE.finditer(stripped):
        ns.size_asserts.append((m.group(1), int(m.group(2))))

    for ln, line in enumerate(stripped.split("\n"), 1):
        if line.lstrip().startswith("#"):
            continue  # preprocessor lines (includes, pragma, define)
        for lm in _INT_LIT_RE.finditer(line):
            ns.literals.append(
                LiteralSite(ln, lm.start(), lm.group(0), int(lm.group(1), 0))
            )

    ns.disables = _scan_disables(source)
    return ns


def filter_native_disables(findings, sources: Dict[Path, NatSource]):
    """The C++ twin of core.filter_disables: a ``// alazlint:
    disable=ALZxxx -- why`` on the flagged line suppresses that code;
    a disable with no justification surfaces as ALZ000 (same discipline
    as the Python side — the escape hatch must carry its why)."""
    from tools.alazlint.core import Finding

    out = []
    seen_bare: set = set()
    for f in findings:
        ns = sources.get(Path(f.path))
        entry = ns.disables.get(f.line, {}) if ns is not None else {}
        if f.code in entry or "" in entry:
            why = entry.get(f.code, entry.get(""))
            if why is None and (f.path, f.line) not in seen_bare:
                seen_bare.add((f.path, f.line))
                out.append(
                    Finding(
                        "ALZ000",
                        "alazlint disable comment without a justification "
                        "— write `// alazlint: disable=CODE -- <why>`",
                        f.path,
                        f.line,
                        0,
                    )
                )
            continue
        out.append(f)
    return out
