"""The golden native offset map: ``resources/specs/nat_offsets.json``
and ALZ062 (drift).

The map pins what the static half DERIVED — per-file struct layouts
(pack(1)- and array-aware), enum/constexpr tables, static_assert size
pins, the GIL-region contract of every export, the pinned-constant
table, and the sanitizer build matrix — the same way alazspec's
specfiles pin shapes and alazrace's ``threads.json`` pins thread
topology: regenerated deterministically (``make specs`` / ``python -m
tools.alaznat --write-offsets``), committed, byte-fixpoint under regen.
A new offset, a struct growing a field, or an export joining the
GIL-dropped surface shows up as a one-line JSON diff in the PR that
caused it. ALZ062 flags any live map that disagrees with the committed
one.

The pinned-constant table is the lint's escape from magic-number
whack-a-mole: every non-layout constant the native code legitimately
shares with the Python side (hash mixers, the conn-key mixer, time-unit
conversions, HTTP status classes) is pinned WITH its Python provenance,
and the provenance is re-verified live at check time — pinning a
constant that no longer matches its Python twin is itself a finding.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Dict, List, Optional

from tools.alazlint.core import Finding
from tools.alaznat.natmodel import NatSource, parse_native_source

REPO = Path(__file__).resolve().parent.parent.parent
NATIVE_DIR = REPO / "alaz_tpu" / "native"
OFFSETS_GOLDEN = REPO / "resources" / "specs" / "nat_offsets.json"

# per-file lint role: the offset/magic rule (ALZ060) holds library
# sources to the derivable-set contract; harness sources (test drivers,
# example agents — their literals are traffic shapes, not wire
# knowledge) get the GIL rule and the struct cross-check only. A file
# NOT listed here defaults to "library": new native code is strict until
# a reviewed golden regen classifies it.
FILE_ROLES = {
    "ingest.cc": "library",
    "tsan_test.cc": "harness",
    "agent_example.cc": "harness",
}

# value -> provenance. Every entry is re-verified against its Python
# twin by verify_pinned_constants() — see _VERIFIERS below.
PINNED_CONSTANTS: Dict[int, str] = {
    0xFF51AFD7ED558CCD: (
        "splitmix64 finalizer c1 — graph/builder._MIX_C1 "
        "(wire_layouts sampling.priority_mix)"
    ),
    0xC4CEB9FE1A85EC53: (
        "splitmix64 finalizer c2 — graph/builder._MIX_C2 "
        "(wire_layouts sampling.priority_mix)"
    ),
    0x9E3779B97F4A7C15: (
        "conn-key (pid,fd) mixer — aggregator/engine.py socket-line "
        "grouping key (64-bit golden ratio)"
    ),
    0x9E3779B9: (
        "32-bit golden-ratio hash combiner — AlzIpHash (native-only; "
        "boost::hash_combine constant)"
    ),
    60_000_000_000: (
        "ONE_MINUTE_NS — aggregator/sockline.py socket-line pick window"
    ),
    1_000_000: "ns -> ms divisor (write_time_ns -> REQUEST start_time_ms)",
    500: "HTTP 5xx class floor — graph/builder.py err5 edge feature",
    400: "HTTP 4xx class floor — graph/builder.py err4 edge feature",
}


def _grep_hex(path: Path, value: int) -> bool:
    text = path.read_text().lower()
    return f"0x{value:x}" in text


def _verify_mix(which: str, value: int) -> Optional[str]:
    from alaz_tpu.graph import builder

    live = getattr(builder, which)
    if live != value:
        return f"graph/builder.{which} is 0x{live:X}, pinned 0x{value:X}"
    return None


def _verify_conn_key(value: int) -> Optional[str]:
    if not _grep_hex(REPO / "alaz_tpu" / "aggregator" / "engine.py", value):
        return (
            f"0x{value:X} not found in aggregator/engine.py — the conn-key "
            "mixer moved or changed"
        )
    return None


def _verify_minute(value: int) -> Optional[str]:
    from alaz_tpu.aggregator.sockline import ONE_MINUTE_NS

    if ONE_MINUTE_NS != value:
        return f"sockline.ONE_MINUTE_NS is {ONE_MINUTE_NS}, pinned {value}"
    return None


def _verify_status_class(value: int) -> Optional[str]:
    text = (REPO / "alaz_tpu" / "graph" / "builder.py").read_text()
    if not re.search(rf">=\s*{value}\b", text):
        return (
            f"status class {value} not found in graph/builder.py — the "
            "err4/err5 feature classes moved"
        )
    return None


_VERIFIERS = {
    0xFF51AFD7ED558CCD: lambda v: _verify_mix("_MIX_C1", v),
    0xC4CEB9FE1A85EC53: lambda v: _verify_mix("_MIX_C2", v),
    0x9E3779B97F4A7C15: _verify_conn_key,
    60_000_000_000: _verify_minute,
    500: _verify_status_class,
    400: _verify_status_class,
}


def verify_pinned_constants() -> List[Finding]:
    """A pinned constant whose Python provenance no longer agrees is an
    ALZ060 finding — the table must never drift into fiction."""
    out: List[Finding] = []
    for value, verify in _VERIFIERS.items():
        problem = verify(value)
        if problem is not None:
            out.append(
                Finding(
                    "ALZ060",
                    f"pinned constant drifted from its provenance: {problem} "
                    f"(pinned as: {PINNED_CONSTANTS[value]}) — update the "
                    "pinned-constant table AND the native code together",
                    str(OFFSETS_GOLDEN),
                    1,
                    0,
                )
            )
    return out


def _const_key(value: int) -> str:
    return f"0x{value:X}" if value > 0xFFFF else str(value)


def _file_entry(ns: NatSource) -> dict:
    name = ns.path.name
    return {
        "role": FILE_ROLES.get(name, "library"),
        "structs": {
            n: s.layout_string() for n, s in sorted(ns.structs.items())
        },
        "opaque_structs": sorted(ns.opaque_structs),
        "enums": {
            n: dict(sorted(vals.items(), key=lambda kv: kv[1]))
            for n, vals in sorted(ns.enums.items())
        },
        "constexprs": dict(sorted(ns.constexprs.items())),
        "size_asserts": {n: sz for n, sz in sorted(ns.size_asserts)},
    }


def compute_offset_map(sources: Dict[Path, NatSource]) -> dict:
    from alaz_tpu.graph import native as gn

    return {
        "files": {
            ns.path.name: _file_entry(ns)
            for ns in sorted(sources.values(), key=lambda s: s.path.name)
        },
        # the GIL-region contract: ctypes releases the GIL around every
        # call, so each export IS a GIL-dropped region end to end —
        # what ALZ061 enforces, pinned here so the contract is reviewed
        # topology, not tribal knowledge
        "gil_contract": {
            "boundary": "ctypes (releases the GIL for the call duration)",
            "exports": {
                name: "dropped" for name in sorted(gn.NATIVE_EXPORTS)
            },
        },
        "pinned_constants": {
            _const_key(v): why
            for v, why in sorted(PINNED_CONSTANTS.items())
        },
        # sanitizer build matrix (the dynamic half): binary -> sources,
        # mirrored by alazspec's check_binary_stamps staleness scan
        "sanitizer_builds": {
            "libalaz_ingest.asan.so": ["ingest.cc"],
            "libalaz_ingest.ubsan.so": ["ingest.cc"],
        },
    }


def render(offset_map: dict) -> str:
    return json.dumps(offset_map, indent=2, sort_keys=True) + "\n"


def parse_sources(
    native_dir: Path = NATIVE_DIR,
) -> Dict[Path, NatSource]:
    return {
        p: parse_native_source(p) for p in sorted(native_dir.glob("*.cc"))
    }


def write_offsets_golden(
    sources: Optional[Dict[Path, NatSource]] = None,
    path: Path = OFFSETS_GOLDEN,
) -> Path:
    sources = sources if sources is not None else parse_sources()
    path.write_text(render(compute_offset_map(sources)))
    return path


def _diff_paths(golden, live, prefix="") -> List[str]:
    if isinstance(golden, dict) and isinstance(live, dict):
        out: List[str] = []
        for k in sorted(set(golden) | set(live)):
            p = f"{prefix}.{k}" if prefix else k
            if k not in golden:
                out.append(f"{p} (new)")
            elif k not in live:
                out.append(f"{p} (gone)")
            else:
                out.extend(_diff_paths(golden[k], live[k], p))
        return out
    if golden != live:
        return [f"{prefix}: golden {golden!r} vs live {live!r}"]
    return []


def check_alz062(
    sources: Optional[Dict[Path, NatSource]] = None,
    golden_path: Path = OFFSETS_GOLDEN,
) -> List[Finding]:
    sources = sources if sources is not None else parse_sources()
    live = compute_offset_map(sources)
    try:
        golden = json.loads(golden_path.read_text())
    except (OSError, json.JSONDecodeError):
        return [
            Finding(
                "ALZ062",
                f"golden native offset map {golden_path.name} missing or "
                "unreadable — regenerate with `python -m tools.alaznat "
                "--write-offsets` (or `make specs`) and commit",
                str(golden_path),
                1,
                0,
            )
        ]
    out: List[Finding] = []
    for drift in _diff_paths(golden, live)[:20]:
        out.append(
            Finding(
                "ALZ062",
                f"native offset map drifted from {golden_path.name}: "
                f"{drift} — an offset, struct, export, or pin changed; "
                "regenerate with --write-offsets and REVIEW the diff "
                "(byte layout changing is a design event, not a drive-by)",
                str(golden_path),
                1,
                0,
            )
        )
    return out
