# Build/test entry points (the reference drives the same lifecycle from
# its Makefile: native artifact build, image build, multi-arch buildx
# push — Makefile:47-65).

IMAGE     ?= alaz-tpu
TAG       ?= latest
# arm64 runs the data-plane (JAX_VARIANT=cpu); TPU hosts are amd64
PLATFORMS ?= linux/amd64,linux/arm64

.PHONY: native test lint sanitize abi-check flow race nat sanitize-native jit chaos scenarios specs image image-multiarch bench

native:  ## libalaz_ingest.so (source-hash stamped) + the out-of-process agent example
	$(MAKE) -C alaz_tpu/native all agent

# sanitize/abi-check/chaos/scenarios run first as their own gates; the
# main run skips their test files so the (not-cheap) stress and
# spec-regen work isn't paid twice per invocation (tier-1 CI runs plain
# `pytest tests/` and still covers both)
test: lint sanitize abi-check flow race nat sanitize-native jit chaos scenarios
	python -m pytest tests/ -x -q --ignore=tests/test_sanitize.py --ignore=tests/test_alazspec.py

flow:  ## alazflow: whole-program row-conservation + blocking-discipline dataflow (ALZ040-ALZ044), incl. cause-vocabulary/metric-registry triangulation
	python -m tools.alazflow --json

race:  ## alazrace: whole-program thread-escape + lockset race detection (ALZ050-ALZ054), incl. golden concurrency-map drift (resources/specs/threads.json)
	python -m tools.alazrace --json

nat:  ## alaznat static half: native offset/magic provenance + GIL discipline + golden offset-map drift over alaz_tpu/native/*.cc (ALZ060-ALZ062)
	env JAX_PLATFORMS=cpu python -m tools.alaznat --json

jit:  ## alazjit: device-plane static analysis — jit-surface discovery + retrace/host-sync/dtype hazard rules (ALZ070-ALZ073) + golden surface/budget-coverage drift (ALZ074, resources/specs/jit_surface.json)
	python -m tools.alazjit --json

sanitize-native:  ## alaznat dynamic half: ASan/UBSan builds of the ingest core + the adversarial fuzz corpus through all four exports with the Python engine as parity oracle (ALZ063); skips gracefully without the gcc sanitizer runtimes
	env JAX_PLATFORMS=cpu python -m tools.alaznat --sanitize --json

chaos:  ## chaos suite sweep: fixed seeds, all four fault seams, invariant gates + one composed scenario×chaos case + the two-tenant worker-kill conservation composition + the process-backend pipeline leg (SIGKILL mid-wave, ISSUE 15) — no accelerator needed
	env JAX_PLATFORMS=cpu python -m alaz_tpu.chaos --seeds 0 1 2 --workers 2 --composed hot_key --tenants --ingest-backend both

scenarios:  ## incident scenario sweep (ISSUE 7): fixed seeds, all five scenarios, host-plane + detection gates, the hot_key 500k-fan-in stress bound, plus the K=3 multi-tenant isolation gate (ISSUE 14)
	env JAX_PLATFORMS=cpu python -m alaz_tpu.replay --seeds 0 --workers 2 --stress --isolation

sanitize:  ## alazsan runtime heads: lock-order stress + retrace budgets + transfer guard (CPU-only, no TPU needed)
	env JAX_PLATFORMS=cpu python -m pytest tests/test_sanitize.py -q

abi-check:  ## alazspec: C-struct/dtype/enum ABI parity + golden shape/dtype/sharding contract diff (ALZ020-ALZ023)
	env JAX_PLATFORMS=cpu python -m tools.alazspec --abi --check-specs --json

specs:  ## regenerate golden specfiles + wire layout table + metric registry + concurrency map (resources/specs) — review and commit the diff
	env JAX_PLATFORMS=cpu python -m tools.alazspec --write-specs
	python -m tools.alazflow --write-metrics
	python -m tools.alazrace --write-threads
	env JAX_PLATFORMS=cpu python -m tools.alaznat --write-offsets
	python -m tools.alazjit --write-surface

lint:  ## alazlint AST gate incl. whole-program ALZ006/ALZ014 and spec hygiene ALZ024 (also self-enforced in tier-1 via tests/test_lint.py) + ruff when installed
	python -m tools.alazlint alaz_tpu/ tools/alazlint tools/alazspec tools/alazflow tools/alazrace tools/alaznat tools/alazjit --json
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check alaz_tpu tools; \
	else \
		echo "ruff not installed; skipped (config in pyproject.toml)"; \
	fi

bench:
	python bench.py

image:  ## single-arch local build (docker build)
	docker build -t $(IMAGE):$(TAG) .

# Multi-arch via buildx (reference Makefile:61-65 / ebpf-builder
# analog): base images are multi-arch manifests and the native stage
# compiles in-container, so each platform gets its own correctly-built
# .so. The Dockerfile selects the JAX variant per-arch from TARGETARCH
# (tpu on amd64, cpu on arm64 — TPU wheels are amd64-only), so one
# manifest serves both node pools and the amd64 layer keeps TPU
# capability.
image-multiarch:
	docker buildx build --platform $(PLATFORMS) \
		-t $(IMAGE):$(TAG) --push .

image-multiarch-local:  ## cross-build without pushing (sanity)
	docker buildx build --platform $(PLATFORMS) \
		-t $(IMAGE):$(TAG) .
