# Build/test entry points (the reference drives the same lifecycle from
# its Makefile: native artifact build, image build, multi-arch buildx
# push — Makefile:47-65).

IMAGE     ?= alaz-tpu
TAG       ?= latest
# arm64 runs the data-plane (JAX_VARIANT=cpu); TPU hosts are amd64
PLATFORMS ?= linux/amd64,linux/arm64

.PHONY: native test lint sanitize image image-multiarch bench

native:  ## libalaz_ingest.so + the out-of-process agent example
	$(MAKE) -C alaz_tpu/native all agent

# sanitize runs first as its own gate; the main run skips that file so
# the suite isn't paid twice (tier-1 CI runs plain `pytest tests/` and
# still covers it)
test: lint sanitize
	python -m pytest tests/ -x -q --ignore=tests/test_sanitize.py

sanitize:  ## alazsan runtime heads: lock-order stress + retrace budgets + transfer guard (CPU-only, no TPU needed)
	env JAX_PLATFORMS=cpu python -m pytest tests/test_sanitize.py -q

lint:  ## alazlint AST gate incl. whole-program ALZ006/ALZ014 (also self-enforced in tier-1 via tests/test_lint.py) + ruff when installed
	python -m tools.alazlint alaz_tpu/ tools/alazlint --json
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check alaz_tpu tools; \
	else \
		echo "ruff not installed; skipped (config in pyproject.toml)"; \
	fi

bench:
	python bench.py

image:  ## single-arch local build (docker build)
	docker build -t $(IMAGE):$(TAG) .

# Multi-arch via buildx (reference Makefile:61-65 / ebpf-builder
# analog): base images are multi-arch manifests and the native stage
# compiles in-container, so each platform gets its own correctly-built
# .so. The Dockerfile selects the JAX variant per-arch from TARGETARCH
# (tpu on amd64, cpu on arm64 — TPU wheels are amd64-only), so one
# manifest serves both node pools and the amd64 layer keeps TPU
# capability.
image-multiarch:
	docker buildx build --platform $(PLATFORMS) \
		-t $(IMAGE):$(TAG) --push .

image-multiarch-local:  ## cross-build without pushing (sanity)
	docker buildx build --platform $(PLATFORMS) \
		-t $(IMAGE):$(TAG) .
