"""alazsan — the *runtime* half of the two-headed sanitizer (the static
half lives in ``tools/alazlint``; both share the ALZ rule vocabulary).

Two heads:

- :mod:`alaz_tpu.sanitize.lockorder` — instrumented ``Lock`` / ``RLock``
  / ``Condition`` wrappers that record per-thread acquisition stacks
  into a global lock-order graph and report cycles (the dynamic twin of
  static ALZ014). Enable with ``lockorder.instrument()`` around the code
  that *constructs* the locks.

- :mod:`alaz_tpu.sanitize.retrace` — a compile-log watcher that counts
  XLA compiles per jit entry point (``CompileWatcher``), an asserted
  per-entry-point **retrace budget** (``retrace_budget`` — the dynamic
  twin of static ALZ006), and a transfer guard for steady-state scoring
  (``no_implicit_transfers``).

Both are production-code-free: nothing in ``alaz_tpu`` imports them
outside of tests/bench instrumentation, so the hot paths carry zero
sanitizer overhead when the sanitizer is off.
"""

from alaz_tpu.sanitize.lockorder import (  # noqa: F401
    LockOrderMonitor,
    LockOrderViolation,
    instrument,
)
from alaz_tpu.sanitize.retrace import (  # noqa: F401
    CompileWatcher,
    RetraceBudgetExceeded,
    no_implicit_transfers,
    retrace_budget,
)
