"""Lock-order sanitizer: instrumented threading primitives + a global
lock-order graph.

``instrument()`` patches ``threading.Lock`` / ``RLock`` / ``Condition``
so every primitive *constructed inside the context* is wrapped. Each
wrapper reports acquisitions to a :class:`LockOrderMonitor`, which keeps
a per-thread held stack and a global directed graph: an edge A→B means
"some thread acquired B while holding A", recorded with the acquiring
thread's stack. A cycle in that graph is a potential deadlock even if no
run has deadlocked yet — two threads walking the two orders concurrently
is all it takes. Cycles are detected eagerly at edge-insert time (into
``monitor.violations`` — raising inside an arbitrary worker thread would
be swallowed) and on demand via ``cycles()`` / ``assert_acyclic()``.

Nodes are per *instance* (two different ``BatchQueue`` locks are
distinct nodes — ordering two queue locks both ways is a real deadlock
that a per-class graph would miss), labeled by their construction site.
Re-entrant re-acquisition (RLock, condition re-entry) adds no self
edges. ``Condition.wait`` releases and re-acquires the underlying lock,
and the bookkeeping follows it.

Scope: a test/bench-time sanitizer. The wrappers add a dict update per
acquisition — fine under stress tests, not meant for the serving path.
"""

from __future__ import annotations

import threading
import traceback
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

_SELF_FILE = __file__


class LockOrderViolation(RuntimeError):
    """A cycle exists in the observed lock-order graph."""


@dataclass
class OrderEdge:
    """First observation of "held ``src`` while acquiring ``dst``"."""

    src: str
    dst: str
    thread: str
    stack: str
    count: int = 1


def _acquisition_site(skip_threading: bool = True) -> str:
    """file:lineno of the outermost caller frame that isn't sanitizer or
    threading machinery — the label a human can map back to code."""
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        if fn == _SELF_FILE or (skip_threading and fn.endswith("threading.py")):
            continue
        return f"{fn}:{frame.lineno}"
    return "<unknown>"


def _acquisition_stack(limit: int = 12) -> str:
    frames = [
        f
        for f in traceback.extract_stack()
        if f.filename != _SELF_FILE and not f.filename.endswith("threading.py")
    ]
    return "".join(traceback.StackSummary.from_list(frames[-limit:]).format())


class LockOrderMonitor:
    """Global lock-order graph + per-thread held stacks."""

    def __init__(self) -> None:
        # the monitor's own lock is a REAL primitive created before any
        # patching, and is only ever held for dict updates — it can never
        # be held while acquiring a tracked lock, so it adds no edges and
        # no deadlock surface of its own
        self._meta = threading.Lock()
        self._held = threading.local()
        self._next_id = 0
        self.labels: Dict[int, str] = {}  # guarded-by: self._meta
        self.edges: Dict[Tuple[int, int], OrderEdge] = {}  # guarded-by: self._meta
        self.adj: Dict[int, Set[int]] = {}  # guarded-by: self._meta
        self.violations: List[str] = []  # guarded-by: self._meta
        self.acquisitions = 0  # guarded-by: self._meta

    # -- registration / bookkeeping ----------------------------------------

    def register(self, label: str) -> int:
        with self._meta:
            nid = self._next_id
            self._next_id += 1
            self.labels[nid] = label
            self.adj.setdefault(nid, set())
            return nid

    def _stack_of_thread(self) -> List[int]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = []
            self._held.stack = stack
        return stack

    def on_acquired(self, nid: int) -> None:
        held = self._stack_of_thread()
        if nid not in held and held:
            self._record_edges(held, nid)
        with self._meta:
            self.acquisitions += 1
        held.append(nid)

    def on_released(self, nid: int) -> None:
        held = self._stack_of_thread()
        # remove the LAST occurrence: re-entrant holds release inner-first
        for i in range(len(held) - 1, -1, -1):
            if held[i] == nid:
                del held[i]
                return

    def _record_edges(self, held: List[int], nid: int) -> None:
        tname = threading.current_thread().name
        stack: Optional[str] = None
        with self._meta:
            for h in dict.fromkeys(held):  # de-dup, preserve order
                key = (h, nid)
                edge = self.edges.get(key)
                if edge is not None:
                    edge.count += 1
                    continue
                if stack is None:
                    stack = _acquisition_stack()
                self.edges[key] = OrderEdge(
                    self.labels[h], self.labels[nid], tname, stack
                )
                self.adj.setdefault(h, set()).add(nid)
                self.adj.setdefault(nid, set())
                # eager cycle check: does nid already reach h?
                if self._reaches(nid, h):
                    self.violations.append(
                        f"lock-order cycle closed by {tname}: "
                        f"{self.labels[h]} -> {self.labels[nid]} while a "
                        f"path {self.labels[nid]} -> ... -> {self.labels[h]} "
                        f"already exists; acquisition stack:\n{stack}"
                    )

    def _reaches(self, src: int, dst: int) -> bool:
        # callers hold self._meta
        seen = {src}
        frontier = [src]
        while frontier:
            n = frontier.pop()
            if n == dst:
                return True
            for m in self.adj.get(n, ()):  # alazlint: disable=ALZ010 -- _reaches is only called from _record_edges, which holds self._meta
                if m not in seen:
                    seen.add(m)
                    frontier.append(m)
        return False

    # -- reporting ----------------------------------------------------------

    def cycles(self) -> List[List[str]]:
        """Strongly connected components of size ≥ 2, as label lists."""
        with self._meta:
            adj = {n: set(ms) for n, ms in self.adj.items()}
            labels = dict(self.labels)
        sccs: List[List[str]] = []
        index: Dict[int, int] = {}
        low: Dict[int, int] = {}
        on_stack: Set[int] = set()
        stack: List[int] = []
        counter = [0]

        def connect(root: int) -> None:
            work = [(root, iter(sorted(adj.get(root, ()))))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(adj.get(w, ())))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append([labels[w] for w in sorted(comp)])

        for n in sorted(adj):
            if n not in index:
                connect(n)
        return sccs

    def graph_summary(self) -> Dict[str, int]:
        with self._meta:
            return {
                "locks": len(self.labels),
                "edges": len(self.edges),
                "acquisitions": self.acquisitions,
            }

    def assert_acyclic(self) -> None:
        cycles = self.cycles()
        with self._meta:
            violations = list(self.violations)
        if cycles or violations:
            detail = "\n".join(
                [f"cycle: {' <-> '.join(c)}" for c in cycles] + violations
            )
            raise LockOrderViolation(
                f"lock-order graph has {len(cycles)} cycle(s):\n{detail}"
            )


# ---------------------------------------------------------------------------
# Instrumented primitives
# ---------------------------------------------------------------------------


class TrackedLock:
    """Wraps a real Lock/RLock; reports acquisitions to the monitor."""

    def __init__(self, monitor: LockOrderMonitor, inner, label: str):
        self._monitor = monitor
        self._inner = inner
        self._nid = monitor.register(label)
        self.label = label

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._monitor.on_acquired(self._nid)
        return got

    def release(self) -> None:
        self._inner.release()
        self._monitor.on_released(self._nid)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<TrackedLock {self.label} inner={self._inner!r}>"


class TrackedCondition:
    """Condition over a TrackedLock. The REAL ``threading.Condition``
    runs against the real inner lock (so wait/notify semantics are
    untouched); this wrapper only mirrors the acquire/release bookkeeping
    — including the release-and-reacquire inside ``wait``."""

    def __init__(self, monitor: LockOrderMonitor, lockw: TrackedLock):
        self._monitor = monitor
        self._lockw = lockw
        self._cond = threading.Condition(lockw._inner)

    # context manager / lock surface ----------------------------------------

    def acquire(self, *a, **kw) -> bool:
        return self._lockw.acquire(*a, **kw)

    def release(self) -> None:
        self._lockw.release()

    def __enter__(self) -> "TrackedCondition":
        self._lockw.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._lockw.release()

    # condition surface ------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._monitor.on_released(self._lockw._nid)
        try:
            return self._cond.wait(timeout)  # alazlint: disable=ALZ013 -- delegation shim: the CALLER owns the predicate loop (wait_for below, and every instrumented call site keeps its own while)
        finally:
            # re-acquired: re-record (edges from still-held outer locks
            # re-apply — waiting with another lock held is itself an order)
            self._monitor.on_acquired(self._lockw._nid)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # reimplemented over self.wait so the bookkeeping sees every
        # release/reacquire (threading's wait_for would bypass ours)
        import time as _time

        end = None if timeout is None else _time.monotonic() + timeout
        result = predicate()
        while not result:
            remaining = None if end is None else end - _time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# ---------------------------------------------------------------------------
# Patch-in installation
# ---------------------------------------------------------------------------


@contextmanager
def instrument() -> Iterator[LockOrderMonitor]:
    """Patch ``threading.Lock/RLock/Condition`` so every primitive
    constructed inside the context is tracked. Locks constructed BEFORE
    entry stay untracked (their acquisitions are invisible, not broken).
    Restores the real factories on exit; tracked locks created inside
    keep working (and keep recording) afterwards."""
    monitor = LockOrderMonitor()
    real_lock = threading.Lock
    real_rlock = threading.RLock
    real_condition = threading.Condition

    def make_lock():
        return TrackedLock(monitor, real_lock(), _acquisition_site())

    def make_rlock():
        return TrackedLock(monitor, real_rlock(), _acquisition_site())

    def make_condition(lock=None):
        if isinstance(lock, TrackedLock):
            return TrackedCondition(monitor, lock)
        if isinstance(lock, TrackedCondition):  # pragma: no cover - odd but legal
            return TrackedCondition(monitor, lock._lockw)
        if lock is None:
            return TrackedCondition(monitor, make_rlock())
        # unknown foreign lock type: leave untracked rather than guess
        return real_condition(lock)

    threading.Lock = make_lock  # type: ignore[assignment]
    threading.RLock = make_rlock  # type: ignore[assignment]
    threading.Condition = make_condition  # type: ignore[assignment]
    try:
        yield monitor
    finally:
        threading.Lock = real_lock  # type: ignore[assignment]
        threading.RLock = real_rlock  # type: ignore[assignment]
        threading.Condition = real_condition  # type: ignore[assignment]
