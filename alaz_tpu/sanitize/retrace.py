"""Retrace sanitizer: compile counting per jit entry point + budgets +
transfer guard.

XLA compiles are the serving-path cliff: a shape outside the bucket set,
a fresh ``jax.jit`` wrapper per call, or a Python-type flip in an
argument each quietly compile a new program (seconds, on the scorer's
critical path). ``CompileWatcher`` captures jax's ``log_compiles``
records — each carries the traced function's *name* ("Compiling
score_apply with global shapes …"), so compiles attribute cleanly to the
named entry points (``score_apply``, ``batched_score_apply``,
``tgn_step``, ``train_step``). ``retrace_budget`` turns a count into an
asserted budget; ``no_implicit_transfers`` bans implicit host↔device
traffic for steady-state sections (explicit ``jnp.asarray`` staging and
``np.asarray`` readback stay legal under jax's "disallow" level — it is
the *implicit* transfers, e.g. a raw numpy array silently shipped per
call, that the guard rejects).

Implementation note: the log capture rides the public
``jax_log_compiles`` config + a logging handler on the ``"jax"`` logger
(records propagate up from ``jax._src.interpreters.pxla``), which is
stable across the jax 0.4.x line — unlike the private cache-miss
callback APIs.
"""

from __future__ import annotations

import logging
import re
from collections import Counter
from contextlib import contextmanager
from typing import Dict, Iterator, Optional, Tuple


class RetraceBudgetExceeded(AssertionError):
    """A jit entry point compiled more often than its declared budget."""


# the declared steady-state budgets, by traced-function name: after
# warmup, ZERO compiles — every serving-path entry point pre-compiles one
# program per (model, shape bucket) and never again. Tests warm explicit
# bucket sets and then assert these.
STEADY_STATE_BUDGETS: Dict[str, int] = {
    "score_apply": 0,  # runtime/service serial scorer (trainstep.make_score_fn)
    "batched_score_apply": 0,  # runtime/service vmapped group scorer
    "tgn_step": 0,  # models/tgn.make_step_fn streaming step
    "train_step": 0,  # train/trainstep.make_train_step
}

_COMPILING_RE = re.compile(r"^Compiling ([^\s]+)")
# the paired completion message carries the wall duration; the fn name
# arrives wrapped as jit(<name>) (dispatch.py's module-name framing)
_FINISHED_RE = re.compile(
    r"^Finished XLA compilation of (?:jit\()?([^)\s]+)\)? in ([0-9eE.+-]+) sec"
)


class _CaptureHandler(logging.Handler):
    def __init__(self, watcher: "CompileWatcher"):
        super().__init__(level=logging.DEBUG)
        self._watcher = watcher

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 - a broken record must not kill the app
            return
        m = _COMPILING_RE.match(msg)
        if m:
            self._watcher._record(m.group(1), msg)
            return
        m = _FINISHED_RE.match(msg)
        if m:
            try:
                secs = float(m.group(2))
            except ValueError:
                secs = 0.0
            self._watcher._finished(m.group(1), secs)


class CompileWatcher:
    """Context manager counting XLA compiles per traced-function name.

    >>> with CompileWatcher() as w:
    ...     fn(x)
    ...     assert w.count("score_apply") == 1

    Nesting is safe (each watcher owns its handler; ``jax_log_compiles``
    is saved/restored). Counts include every shape instantiation — one
    per (entry point, shape bucket) is the expected steady state.

    ``on_event`` (ISSUE 11, the production hookup): an optional callback
    fired per captured event — ``("compiling", name, None)`` when a
    compile starts (the countable event budgets assert on) and
    ``("finished", name, secs)`` when the paired "Finished XLA
    compilation" message lands with its wall duration. A raising
    callback is swallowed: the capture must never take down the
    compiling thread.

    Retention: ``events``/``finished`` are rings of the last
    ``max_events`` entries — a watcher held open for a service lifetime
    (the production plane) in exactly the pathology it exists to detect
    (a per-window steady-state retrace) must not grow RSS unbounded.
    Budget tests measure deltas over bounded windows far below the cap;
    the production plane keeps its own cumulative counters.
    """

    def __init__(self, on_event=None, max_events: int = 4096) -> None:
        from collections import deque

        # (traced fn name, full message), oldest dropped past max_events
        self.events: "deque[Tuple[str, str]]" = deque(maxlen=max_events)
        self.finished: "deque[Tuple[str, float]]" = deque(maxlen=max_events)
        self._on_event = on_event
        self._handler: Optional[_CaptureHandler] = None
        self._prev_log_compiles: Optional[bool] = None

    def _record(self, name: str, msg: str) -> None:
        self.events.append((name, msg))
        if self._on_event is not None:
            try:
                self._on_event("compiling", name, None)
            except Exception:  # noqa: BLE001 - see docstring
                pass

    def _finished(self, name: str, secs: float) -> None:
        self.finished.append((name, secs))
        if self._on_event is not None:
            try:
                self._on_event("finished", name, secs)
            except Exception:  # noqa: BLE001 - see docstring
                pass

    def __enter__(self) -> "CompileWatcher":
        import jax

        self._handler = _CaptureHandler(self)
        logging.getLogger("jax").addHandler(self._handler)
        self._prev_log_compiles = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        return self

    def __exit__(self, *exc) -> None:
        import jax

        if self._prev_log_compiles is not None:
            jax.config.update("jax_log_compiles", self._prev_log_compiles)
        if self._handler is not None:
            logging.getLogger("jax").removeHandler(self._handler)
            self._handler = None

    # -- queries -------------------------------------------------------------

    @property
    def counts(self) -> Counter:
        return Counter(name for name, _ in self.events)

    @property
    def total(self) -> int:
        return len(self.events)

    def count(self, name: str) -> int:
        """Compiles of one traced-function name (exact match)."""
        return self.counts[name]


@contextmanager
def retrace_budget(
    budgets: Dict[str, int], watcher: Optional[CompileWatcher] = None
) -> Iterator[CompileWatcher]:
    """Assert per-entry-point compile budgets over a ``with`` block.

    ``budgets`` maps traced-function names to the maximum number of
    compiles allowed inside the block (0 = steady state, N = warmup of N
    buckets). Pass an already-open ``watcher`` to share one capture;
    counts are measured as a delta either way."""
    own = watcher is None
    w = CompileWatcher() if watcher is None else watcher
    if own:
        w.__enter__()
    base = {name: w.count(name) for name in budgets}
    try:
        yield w
    finally:
        if own:
            w.__exit__()
    over = {
        name: (w.count(name) - base[name], limit)
        for name, limit in budgets.items()
        if w.count(name) - base[name] > limit
    }
    if over:
        detail = ", ".join(
            f"{name}: {got} compile(s) > budget {limit}"
            for name, (got, limit) in sorted(over.items())
        )
        raise RetraceBudgetExceeded(
            f"retrace budget exceeded — {detail}. A steady-state scorer "
            "compiles once per (model, shape bucket) during warmup and "
            "never again; new compiles here mean shape churn outside the "
            "bucket set, a fresh jit wrapper per call, or a Python-type "
            "flip in an argument (see tools/alazlint ALZ006)."
        )


@contextmanager
def no_implicit_transfers() -> Iterator[None]:
    """Ban implicit host↔device transfers for the enclosed block — the
    steady-state scorer contract: staging is explicit (``jnp.asarray``
    into arenas), readback is explicit (``np.asarray`` on results), and
    anything else silently serializing the pipeline raises."""
    import jax

    with jax.transfer_guard("disallow"):
        yield
