"""Process-mode sharded ingest (ISSUE 15): shared-memory ring buffers +
spawn shard workers + the out-of-GIL ``ShardedIngest`` backend.

Selected by ``RuntimeConfig.ingest_backend = "process"``
(``INGEST_BACKEND`` env); the thread backend in ``aggregator/sharded.py``
stays the default. See ARCHITECTURE §3r.
"""

from alaz_tpu.shm.process_pool import ProcessShardedIngest
from alaz_tpu.shm.ring import (
    RingClosed,
    RingConsumer,
    RingProducer,
    ShmRing,
)

__all__ = [
    "ProcessShardedIngest",
    "ShmRing",
    "RingProducer",
    "RingConsumer",
    "RingClosed",
]
