"""Wire framing for the shm rings (ISSUE 15).

Three payload families cross the fork boundary:

- **event batches** — L7/TCP/PROC wire dtypes byte-for-byte
  (``events/schema.py``; alazspec already pins those layouts), one
  record per shard slice. No new serialization: the wire dtype IS the
  contract, same as the socket frames.
- **control** — close waves / seals as two ``<q`` words; k8s resource
  messages pickled (control plane, never row-counted).
- **window results** — the worker's per-window ``EdgePartial`` keyed by
  its LOCAL interner ids, plus the **interner delta**: the string table
  rows the worker interned since its previous ship. The parent folds
  the delta into the shared Interner and remaps uids before
  ``build_from_partials`` — the id-exchange that replaces PR 5's shared
  lock-striped interner with zero cross-process locking.

The window frame layout (header, delta framing, column order) is pinned
in ``resources/specs/wire_layouts.json`` ``shm_ring`` — both sides of
the spawn boundary import THIS module, and alazspec anchors any drift.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from alaz_tpu.graph.builder import EdgePartial

# window-frame header: window id, raw request rows folded into the
# partial, group count, label flag, interner-delta [base, base+count),
# and the span-plane stamps (CLOCK_MONOTONIC — comparable across
# processes on the deployment target): first-row seen, close start,
# close duration.
WIN_HEADER = struct.Struct("<qQIIIIddd")
ACK_FRAME = struct.Struct("<qq")  # (wave, upto; W_FLOOR-1 = None)
SEAL_FRAME = struct.Struct("<q")
CLOSE_FRAME = struct.Struct("<qq")  # (wave, upto)

UPTO_NONE = -(2**62) - 1  # distinct from W_FLOOR ("close everything")

# EdgePartial column order + dtypes — the serialization contract
# (alazspec `shm_ring.window_columns`). label_sum rides only when
# has_label is set.
PARTIAL_COLUMNS: Tuple[Tuple[str, str], ...] = (
    ("from_uid", "<i4"),
    ("to_uid", "<i4"),
    ("from_type", "|u1"),
    ("to_type", "|u1"),
    ("proto", "<i4"),
    ("count", "<f8"),
    ("lat_sum", "<f8"),
    ("lat_max", "<f8"),
    ("err5_sum", "<f8"),
    ("err4_sum", "<f8"),
    ("tls_sum", "<f8"),
)
LABEL_COLUMN = ("label_sum", "<f8")


def win_header_layout_string() -> str:
    return (
        f"ShmWinHeader:{WIN_HEADER.size};window:0:8;rows:8:8;n_groups:16:4;"
        "has_label:20:4;delta_base:24:4;delta_count:28:4;first_row_t:32:8;"
        "close_start_t:40:8;close_dur_s:48:8"
    )


def encode_window(
    window: int,
    partial: EdgePartial,
    delta_base: int,
    delta_strings: List[str],
    first_row_t: float,
    close_start_t: float,
    close_dur_s: float,
) -> bytes:
    """One closed window → bytes: header, delta table (u32 lengths +
    utf-8 blob), then the partial's columns in PARTIAL_COLUMNS order."""
    blobs = [s.encode("utf-8") for s in delta_strings]
    has_label = partial.label_sum is not None
    parts = [
        WIN_HEADER.pack(
            int(window),
            int(partial.rows),
            int(partial.from_uid.shape[0]),
            1 if has_label else 0,
            int(delta_base),
            len(blobs),
            float(first_row_t),
            float(close_start_t),
            float(close_dur_s),
        ),
        np.asarray([len(b) for b in blobs], dtype=np.uint32).tobytes(),
    ]
    parts.extend(blobs)
    cols = list(PARTIAL_COLUMNS) + ([LABEL_COLUMN] if has_label else [])
    for name, dt in cols:
        parts.append(
            np.ascontiguousarray(getattr(partial, name), dtype=np.dtype(dt))
            .tobytes()
        )
    return b"".join(parts)


def decode_window(payload) -> Tuple[int, EdgePartial, int, List[str], float, float, float]:
    """Inverse of :func:`encode_window`:
    (window, partial-with-LOCAL-uids, delta_base, delta_strings,
    first_row_t, close_start_t, close_dur_s)."""
    (
        window, rows, n_groups, has_label, delta_base, delta_count,
        first_row_t, close_start_t, close_dur_s,
    ) = WIN_HEADER.unpack_from(payload, 0)
    off = WIN_HEADER.size
    lens = np.frombuffer(payload, dtype=np.uint32, count=delta_count, offset=off)
    off += 4 * delta_count
    strings: List[str] = []
    for n in lens.tolist():
        strings.append(bytes(payload[off : off + n]).decode("utf-8"))
        off += n
    cols = {}
    spec = list(PARTIAL_COLUMNS) + ([LABEL_COLUMN] if has_label else [])
    for name, dt in spec:
        dtype = np.dtype(dt)
        arr = np.frombuffer(payload, dtype=dtype, count=n_groups, offset=off)
        off += dtype.itemsize * n_groups
        cols[name] = arr.copy()  # writable: the parent remaps uids in place
    partial = EdgePartial(
        from_uid=cols["from_uid"],
        to_uid=cols["to_uid"],
        from_type=cols["from_type"],
        to_type=cols["to_type"],
        proto=cols["proto"],
        count=cols["count"],
        lat_sum=cols["lat_sum"],
        lat_max=cols["lat_max"],
        err5_sum=cols["err5_sum"],
        err4_sum=cols["err4_sum"],
        tls_sum=cols["tls_sum"],
        label_sum=cols.get("label_sum"),
        rows=int(rows),
    )
    return (
        int(window), partial, int(delta_base), strings,
        float(first_row_t), float(close_start_t), float(close_dur_s),
    )


def encode_events(events: np.ndarray):
    """Wire-dtype rows → a byte view (the dtype layouts alazspec already
    pins are the serialization). Zero-copy when the slice is already
    contiguous: the ring write is the ONE copy — a ``tobytes`` here
    would pay a second full-row-width pass on the scatter thread."""
    arr = np.ascontiguousarray(events)
    try:
        return memoryview(arr).cast("B")
    except (TypeError, ValueError):  # exotic dtype without PEP-3118 view
        return arr.tobytes()


def decode_events(payload, dtype: np.dtype) -> np.ndarray:
    """Byte buffer → a WRITABLE wire-dtype array. When the ring already
    handed us a fresh writable uint8 array (its one mandatory copy-out),
    this is a zero-copy reinterpret; a bytes payload (tests, exotic
    paths) pays the copy here instead."""
    arr = np.frombuffer(payload, dtype=dtype)
    if isinstance(payload, np.ndarray) and payload.flags.writeable:
        return arr
    return arr.copy()


def encode_close(wave: int, upto: Optional[int]) -> bytes:
    return CLOSE_FRAME.pack(int(wave), UPTO_NONE if upto is None else int(upto))


def decode_close(payload: bytes) -> Tuple[int, Optional[int]]:
    wave, upto = CLOSE_FRAME.unpack_from(payload, 0)
    return int(wave), (None if upto == UPTO_NONE else int(upto))
