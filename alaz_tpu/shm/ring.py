"""Fixed-slot shared-memory ring buffers — the process-mode transport.

ISSUE 15 tentpole: the host plane's thread backend cannot scale past the
GIL (PR 5 measured 1.22× at 2 workers — the wall is GIL-held
intern/dict/small-op Python), so shard workers move into child
PROCESSES. This module is the only channel between them: one SPSC ring
per direction per worker, laid out in ``multiprocessing.shared_memory``
segments. Everything that crosses the fork boundary is bytes in these
rings — wire-dtype rows, pickled k8s control messages, serialized
``EdgePartial`` frames with interner delta tables (codec.py). No Python
object is ever shared; no lock is ever shared (alazrace's process-role
carve-out is sound because of this file's contract).

Layout (alazspec pins every constant below in
``resources/specs/wire_layouts.json`` ``shm_ring`` — a layout edited on
one side of the spawn boundary anchors at analysis time):

    [CTRL 64B][STATS 512B][slot 0][slot 1]...[slot n-1]

- **CTRL** — magic/version/geometry plus the two cursors: ``tail``
  (consumer-written, slots consumed, monotonic) and ``head_hint``
  (producer-written after each commit; an occupancy gauge and the
  respawn resume aid, never the synchronization source).
- **STATS** — the worker's crash-surviving accounting mirror (the
  response ring's producer owns it): done-record counter, store
  watermark, request/late counters, the per-cause DropLedger mirror and
  the AggregatorStats columns. A SIGKILLed worker's books stay readable
  here, which is what makes exact row conservation through a kill
  provable (process_pool._settle_dead_shard).
- **Slots** — fixed stride. A record occupies ``ceil((32+nbytes)/
  slot_size)`` consecutive slots: a 32-byte header in the first slot,
  payload bytes running contiguously through the rest (continuation
  slots carry no headers). A record never wraps the segment end — the
  producer emits a PAD record spanning the remainder and restarts at
  slot 0 (cursors stay monotonic in slot units; position = cursor %
  n_slots).

Publication protocol (single-producer single-consumer, lock-free):
the producer writes payload first, then the header's non-seq fields,
then ``seq = start_cursor + 1`` as one aligned 8-byte store. The
consumer polls the slot at ``tail % n_slots`` for ``seq == tail + 1``;
a match happens-after every prior store under the x86-TSO store-order
guarantee (the data plane's deployment target; the same ordering
assumption the C++ native ring makes). Reused slots can never alias: a
stale seq at that position is exactly ``n_slots`` laps old.

Crash semantics (the supervision plane's contract): the consumer takes
ZERO-COPY views and advances ``tail`` only at ``commit()``, AFTER the
record is fully processed. A kill mid-record therefore REPLAYS it to
the respawned worker against fresh state (the dead process's partial
effects and its buffered ledger adds died with its memory — no loss, no
double count); a kill after commit loses only rows the dead process
still held privately, which the parent attributes from its own
produced-record log minus the mirror (process_pool._settle_dead_shard).
"""

from __future__ import annotations

import struct
import time
from multiprocessing import shared_memory
from typing import Optional

import numpy as np

from alaz_tpu.utils.ledger import DropLedger

# ---------------------------------------------------------------------------
# Pinned constants (alazspec `shm_ring` section; `make specs` regenerates)
# ---------------------------------------------------------------------------

SHM_MAGIC = 0x414C5A52  # "ALZR"
SHM_VERSION = 1

CTRL_BYTES = 64
STATS_BYTES = 512
DATA_OFFSET = CTRL_BYTES + STATS_BYTES

# slot stride and count defaults (RuntimeConfig.shm_slot_bytes /
# shm_ring_slots; SHM_SLOT_BYTES / SHM_RING_SLOTS env knobs)
DEFAULT_SLOT_BYTES = 1 << 16
DEFAULT_RING_SLOTS = 512

# CTRL field offsets
_C_MAGIC = 0  # u32
_C_VERSION = 4  # u32
_C_SLOT_SIZE = 8  # u32
_C_N_SLOTS = 12  # u32
_C_TAIL = 16  # u64, consumer cursor (slots, monotonic)
_C_CLOSED = 24  # u32, producer-side close latch
_C_HEAD_HINT = 32  # u64, producer cursor (post-commit hint/gauge)

# record header: one per record, in its first slot. The seq word is
# written SEPARATELY (and last) — publication order is the protocol —
# so the non-seq fields have their own struct at offset 8.
SLOT_HEADER = struct.Struct("<QIIIIq")  # seq, kind, nbytes, rows, span, now_ns
SLOT_BODY = struct.Struct("<IIIIq")  # kind, nbytes, rows, span, now_ns (@+8)
SLOT_HEADER_BYTES = SLOT_HEADER.size  # 32
_NOW_NONE = -1  # now_ns sentinel for "caller passed None"

# record kinds — parent → worker (request ring) ...
K_PAD = 0  # slot-alignment filler (spans to segment end)
K_L7 = 1  # L7_EVENT_DTYPE rows (wire bytes)
K_TCP = 2  # TCP_EVENT_DTYPE rows
K_PROC = 3  # PROC_EVENT_DTYPE rows
K_K8S = 4  # pickled K8sResourceMessage (control plane)
K_CLOSE = 5  # close wave: payload <qq> = (wave, upto; codec.UPTO_NONE = -(2**62)-1 means "close everything" — distinct from W_FLOOR)
K_GC = 6  # housekeeping broadcast
K_REAP = 7
K_RETRIES = 8  # flush_retries(now_ns)
K_SEAL = 9  # merged-horizon seal: payload <q> = upto
K_STOP = 10  # clean shutdown
# ... and worker → parent (response ring)
K_WINDOW = 16  # one closed window's EdgePartial + interner delta (codec.py)
K_ACK = 17  # close-wave ack: payload <qq> = (wave, upto)

KIND_NAMES = {
    K_PAD: "pad", K_L7: "l7", K_TCP: "tcp", K_PROC: "proc", K_K8S: "k8s",
    K_CLOSE: "close", K_GC: "gc", K_REAP: "reap", K_RETRIES: "retries",
    K_SEAL: "seal", K_STOP: "stop", K_WINDOW: "window", K_ACK: "ack",
}

# "no window closed yet" sentinel — mirrors aggregator/sharded._W_FLOOR
W_FLOOR = -(2**62)

# STATS field offsets (worker-written u64/i64/f64 slots; parent reads)
S_DONE_RECORDS = 0  # u64, records fully processed (task_done analog)
S_WATERMARK = 8  # i64, shard store watermark (W_FLOOR = none)
S_REQUEST_COUNT = 16  # u64, store request_count mirror
S_LATE_DROPPED = 24  # u64, store late_dropped mirror
S_PENDING_RETRIES = 32  # u64, aggregator retry-queue rows
S_LAST_PERSIST = 40  # f64, monotonic stamp (0.0 = never)
S_HEARTBEAT = 48  # u64, item counter (liveness)
S_LEDGER = 56  # u64 × len(DropLedger.CAUSES), cause order pinned
S_AGG_STATS = 104  # u64 × len(AGG_STAT_FIELDS)
S_READY_GEN = 192  # u64, worker writes generation+1 once its loop is up

# AggregatorStats mirror column order — pinned so both sides of the
# spawn boundary index the same slots (alazspec anchors drift)
AGG_STAT_FIELDS = (
    "l7_in",
    "l7_joined",
    "l7_dropped_no_socket",
    "l7_dropped_not_pod",
    "l7_requeued",
    "tcp_in",
    "proc_in",
    "k8s_in",
    "edges_out",
    "kafka_out",
    "l7_rate_limited",
)

_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")


def ctrl_layout_string() -> str:
    """Canonical CTRL layout — same shape as ``dtype_layout`` strings."""
    return (
        f"ShmCtrl:{CTRL_BYTES};magic:{_C_MAGIC}:4;version:{_C_VERSION}:4;"
        f"slot_size:{_C_SLOT_SIZE}:4;n_slots:{_C_N_SLOTS}:4;"
        f"tail:{_C_TAIL}:8;closed:{_C_CLOSED}:4;head_hint:{_C_HEAD_HINT}:8"
    )


def stats_layout_string() -> str:
    ledger_w = 8 * len(DropLedger.CAUSES)
    agg_w = 8 * len(AGG_STAT_FIELDS)
    return (
        f"ShmStats:{STATS_BYTES};done_records:{S_DONE_RECORDS}:8;"
        f"watermark:{S_WATERMARK}:8;request_count:{S_REQUEST_COUNT}:8;"
        f"late_dropped:{S_LATE_DROPPED}:8;"
        f"pending_retries:{S_PENDING_RETRIES}:8;"
        f"last_persist:{S_LAST_PERSIST}:8;heartbeat:{S_HEARTBEAT}:8;"
        f"ledger:{S_LEDGER}:{ledger_w};agg_stats:{S_AGG_STATS}:{agg_w};"
        f"ready_gen:{S_READY_GEN}:8"
    )


def slot_header_layout_string() -> str:
    return (
        f"ShmSlotHeader:{SLOT_HEADER_BYTES};seq:0:8;kind:8:4;nbytes:12:4;"
        f"rows:16:4;span:20:4;now_ns:24:8"
    )


class RingClosed(Exception):
    """The producer closed the ring (stop path)."""


class ShmRing:
    """One shared-memory ring segment: CTRL + STATS + fixed slots.

    The parent CREATES both rings per worker and is the only side that
    ever unlinks them; the child ATTACHES by name. All cursor/stats
    traffic goes through the accessors below — aligned 8-byte
    pack/unpack calls, single stores under the GIL on each side.
    """

    def __init__(
        self,
        name: Optional[str] = None,
        slot_bytes: int = DEFAULT_SLOT_BYTES,
        n_slots: int = DEFAULT_RING_SLOTS,
        create: bool = False,
    ):
        if create:
            if slot_bytes % 64 or slot_bytes <= SLOT_HEADER_BYTES:
                raise ValueError("slot_bytes must be a 64-multiple > 32")
            if n_slots < 4:
                raise ValueError("n_slots must be >= 4")
            size = DATA_OFFSET + slot_bytes * n_slots
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=size
            )
            buf = self._shm.buf
            # pre-fault every page NOW (one vectorized zero pass): the
            # first production lap through an untouched tmpfs segment
            # otherwise pays its page faults inside the hot put path —
            # measured as ~5× on the per-record store
            np.frombuffer(buf, dtype=np.uint8)[:] = 0
            _U32.pack_into(buf, _C_MAGIC, SHM_MAGIC)
            _U32.pack_into(buf, _C_VERSION, SHM_VERSION)
            _U32.pack_into(buf, _C_SLOT_SIZE, slot_bytes)
            _U32.pack_into(buf, _C_N_SLOTS, n_slots)
            _I64.pack_into(buf, CTRL_BYTES + S_WATERMARK, W_FLOOR)
            self.slot_bytes = slot_bytes
            self.n_slots = n_slots
        else:
            # attach side (the worker). The spawn children share the
            # parent's resource-tracker process, and the tracker's cache
            # is a set — the parent's create-side registration already
            # covers the segment, and the parent's unlink is the one
            # unregister. An attach-side unregister here would race it
            # into a tracker KeyError at exit.
            self._shm = shared_memory.SharedMemory(name=name)
            buf = self._shm.buf
            magic = _U32.unpack_from(buf, _C_MAGIC)[0]
            version = _U32.unpack_from(buf, _C_VERSION)[0]
            if magic != SHM_MAGIC or version != SHM_VERSION:
                raise ValueError(
                    f"shm ring {name}: bad magic/version "
                    f"0x{magic:08X}/{version} (want 0x{SHM_MAGIC:08X}/"
                    f"{SHM_VERSION}) — parent and worker builds disagree"
                )
            self.slot_bytes = _U32.unpack_from(buf, _C_SLOT_SIZE)[0]
            self.n_slots = _U32.unpack_from(buf, _C_N_SLOTS)[0]
        self.name = self._shm.name

    @property
    def buf(self):
        return self._shm.buf

    # -- cursors / flags ----------------------------------------------------

    @property
    def tail(self) -> int:
        return _U64.unpack_from(self._shm.buf, _C_TAIL)[0]

    def set_tail(self, v: int) -> None:
        _U64.pack_into(self._shm.buf, _C_TAIL, v)

    @property
    def head_hint(self) -> int:
        return _U64.unpack_from(self._shm.buf, _C_HEAD_HINT)[0]

    def set_head_hint(self, v: int) -> None:
        _U64.pack_into(self._shm.buf, _C_HEAD_HINT, v)

    @property
    def closed(self) -> bool:
        return _U32.unpack_from(self._shm.buf, _C_CLOSED)[0] != 0

    def close_ring(self) -> None:
        """Producer-side close latch (monotonic False→True)."""
        _U32.pack_into(self._shm.buf, _C_CLOSED, 1)

    @property
    def pending_slots(self) -> int:
        """Occupancy gauge: committed-but-unconsumed slots (hint-based —
        momentarily stale by at most one in-flight record)."""
        return max(0, self.head_hint - self.tail)

    # -- stats block --------------------------------------------------------

    def stat_u64(self, off: int) -> int:
        return _U64.unpack_from(self._shm.buf, CTRL_BYTES + off)[0]

    def set_stat_u64(self, off: int, v: int) -> None:
        _U64.pack_into(self._shm.buf, CTRL_BYTES + off, v)

    def stat_i64(self, off: int) -> int:
        return _I64.unpack_from(self._shm.buf, CTRL_BYTES + off)[0]

    def set_stat_i64(self, off: int, v: int) -> None:
        _I64.pack_into(self._shm.buf, CTRL_BYTES + off, v)

    def stat_f64(self, off: int) -> float:
        return _F64.unpack_from(self._shm.buf, CTRL_BYTES + off)[0]

    def set_stat_f64(self, off: int, v: float) -> None:
        _F64.pack_into(self._shm.buf, CTRL_BYTES + off, v)

    def ledger_mirror(self) -> dict:
        """{cause: count} snapshot of the worker's DropLedger mirror."""
        buf = self._shm.buf
        return {
            c: _U64.unpack_from(buf, CTRL_BYTES + S_LEDGER + 8 * i)[0]
            for i, c in enumerate(DropLedger.CAUSES)
        }

    def agg_stats_mirror(self) -> dict:
        buf = self._shm.buf
        return {
            f: _U64.unpack_from(buf, CTRL_BYTES + S_AGG_STATS + 8 * i)[0]
            for i, f in enumerate(AGG_STAT_FIELDS)
        }

    # -- lifecycle ----------------------------------------------------------

    def detach(self) -> None:
        try:
            self._shm.close()
        except Exception:
            pass

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except Exception:
            pass


class RingProducer:  # role-private: one instance per ring ENDPOINT — the parent pool serializes its producers under the per-ring put_lock, the worker process is single-threaded; the cursor never has two same-process writers
    """Single-producer cursor over a ring. NOT thread-safe — the pool
    serializes parent-side puts per ring with its own lock; the worker
    is single-threaded by construction."""

    def __init__(self, ring: ShmRing, start_cursor: int = 0):
        self.ring = ring
        self.cursor = int(start_cursor)

    def _free(self) -> int:
        return self.ring.n_slots - (self.cursor - self.ring.tail)

    def _reserve(self, nbytes: int) -> Optional[int]:
        """Space-check + wrap-pad for an ``nbytes``-payload record;
        returns the record's byte offset, or None when the ring is full.
        Raises :class:`RingClosed` once the close latch is set."""
        ring = self.ring
        if ring.closed:
            raise RingClosed(ring.name)
        span = -(-(SLOT_HEADER_BYTES + nbytes) // ring.slot_bytes)
        if span > ring.n_slots - 1:
            raise ValueError(
                f"record of {nbytes}B needs {span} slots > ring capacity "
                f"{ring.n_slots - 1} — raise SHM_SLOT_BYTES/SHM_RING_SLOTS "
                "or shrink the chunk"
            )
        pos = self.cursor % ring.n_slots
        pad = ring.n_slots - pos if pos + span > ring.n_slots else 0
        buf = ring.buf
        if pad:
            # the pad commits INDEPENDENTLY of the record: when
            # pad + span exceeds the whole ring, waiting for both at
            # once can never succeed from this position (the cursor
            # would never move — a livelock the big-record + unlucky-
            # position combination hits); emitting the pad alone
            # advances to slot 0, where the record CAN fit once the
            # consumer drains
            if self._free() < pad:
                return None
            off = DATA_OFFSET + pos * ring.slot_bytes
            SLOT_BODY.pack_into(
                buf, off + 8, K_PAD, 0, 0, pad, _NOW_NONE
            )  # non-seq fields first ...
            _U64.pack_into(buf, off, self.cursor + 1)  # ... seq commits
            self.cursor += pad
            pos = 0
        if self._free() < span:
            return None
        return DATA_OFFSET + pos * ring.slot_bytes

    def _commit(self, off: int, kind: int, nbytes: int, rows: int, now_ns) -> None:
        ring = self.ring
        span = -(-(SLOT_HEADER_BYTES + nbytes) // ring.slot_bytes)
        SLOT_BODY.pack_into(
            ring.buf, off + 8, int(kind), nbytes, int(rows), span,
            _NOW_NONE if now_ns is None else int(now_ns),
        )
        _U64.pack_into(ring.buf, off, self.cursor + 1)  # publish: seq LAST
        self.cursor += span
        ring.set_head_hint(self.cursor)

    def try_put(self, kind: int, payload, rows: int = 0, now_ns=None) -> bool:
        """One attempt: commit the record or return False (ring full)."""
        payload = memoryview(payload) if payload is not None else memoryview(b"")
        nbytes = payload.nbytes
        off = self._reserve(nbytes)
        if off is None:
            return False
        if nbytes:
            # numpy-mediated memcpy: a raw memoryview slice assignment
            # of a cast structured view runs ~5× slower than np.copyto
            # on this path
            dst = np.frombuffer(
                self.ring.buf, dtype=np.uint8, count=nbytes,
                offset=off + SLOT_HEADER_BYTES,
            )
            dst[:] = np.frombuffer(payload, dtype=np.uint8)
        self._commit(off, kind, nbytes, rows, now_ns)
        return True

    def try_put_rows(
        self, kind: int, events, idx, now_ns=None
    ) -> bool:
        """Fused shard-scatter put: gather ``events[idx]`` DIRECTLY into
        the ring slot (``np.take(out=)``), so the scatter thread pays
        ONE row-width copy per record instead of gather-to-temp +
        temp-to-ring — the scatter thread's production rate is the
        pipeline ceiling at high worker counts. ``idx=None`` writes the
        whole batch."""
        k = int(events.shape[0] if idx is None else idx.shape[0])
        nbytes = k * events.dtype.itemsize
        off = self._reserve(nbytes)
        if off is None:
            return False
        dst = np.frombuffer(
            self.ring.buf, dtype=events.dtype, count=k,
            offset=off + SLOT_HEADER_BYTES,
        )
        if idx is None:
            dst[:] = events
        else:
            np.take(events, idx, out=dst)
        self._commit(off, kind, nbytes, k, now_ns)
        return True

    def put_rows(
        self, kind: int, events, idx, now_ns=None,
        timeout: Optional[float] = None,
    ) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.try_put_rows(kind, events, idx, now_ns=now_ns):
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.0005)

    def put(
        self, kind: int, payload, rows: int = 0, now_ns=None,
        timeout: Optional[float] = None,
    ) -> bool:
        """Deadline-bounded put: poll until the record fits or the
        deadline passes (False — the caller sheds to the ledger, the
        drop-not-block contract one hop past the fork)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.try_put(kind, payload, rows=rows, now_ns=now_ns):
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.0005)


class Record:
    __slots__ = ("kind", "payload", "rows", "now_ns")

    def __init__(self, kind: int, payload, rows: int, now_ns):
        # payload: writable uint8 ndarray (one ring copy) or b""
        self.kind = kind
        self.payload = payload
        self.rows = rows
        self.now_ns = now_ns

    def __len__(self) -> int:  # ledger attribution unit
        return self.rows


class RingConsumer:  # role-private: one instance per ring ENDPOINT — parent-side consumers run only under the pool's _io_lock (single-flight drains), the worker process is single-threaded
    """Single-consumer cursor with DEFERRED commit. ``try_get_view``
    hands out a ZERO-COPY view into the ring; the slots stay reserved
    (the producer's free-space check reads ``tail``) until the caller
    ``commit()``s — after processing. The payoff is twofold: no
    per-record copy on the worker's critical path, and better kill
    semantics — a worker SIGKILLed mid-record never advanced ``tail``,
    so the respawned worker REPLAYS the record against its fresh state
    instead of losing it (the old process's partial effects died with
    its memory; the ledger mirror is flushed only after commit, so a
    replay can never double-attribute). The cursor is persisted in CTRL
    ``tail``, which is exactly the replay point."""

    def __init__(self, ring: ShmRing, start_cursor: Optional[int] = None):
        self.ring = ring
        self.cursor = ring.tail if start_cursor is None else int(start_cursor)
        self._pending_span = 0  # uncommitted record's slot span

    def try_get_view(self) -> Optional[Record]:
        """Next committed record as a zero-copy view, WITHOUT freeing
        its slots — call :meth:`commit` when done with the payload.
        At most one view may be outstanding."""
        if self._pending_span:
            raise RuntimeError("previous record not committed")
        ring = self.ring
        buf = ring.buf
        while True:
            pos = self.cursor % ring.n_slots
            off = DATA_OFFSET + pos * ring.slot_bytes
            seq = _U64.unpack_from(buf, off)[0]
            if seq != self.cursor + 1:
                return None  # not committed yet
            _, kind, nbytes, rows, span, now_ns = SLOT_HEADER.unpack_from(
                buf, off
            )
            if kind == K_PAD:
                self.cursor += span
                ring.set_tail(self.cursor)
                continue
            payload = (
                np.frombuffer(
                    buf, dtype=np.uint8, count=nbytes,
                    offset=off + SLOT_HEADER_BYTES,
                )
                if nbytes
                else b""
            )
            self._pending_span = span
            return Record(
                kind, payload, rows, None if now_ns == _NOW_NONE else now_ns
            )

    def commit(self) -> None:
        """Free the outstanding record's slots (the consume point: a
        kill BEFORE this replays the record, a kill after loses only
        what the dead process still held privately)."""
        if self._pending_span:
            self.cursor += self._pending_span
            self._pending_span = 0
            self.ring.set_tail(self.cursor)

    def try_get(self) -> Optional[Record]:
        """Copying get: view + materialize + commit — for consumers that
        stash the payload past the commit point (tests, simple tools)."""
        rec = self.try_get_view()
        if rec is None:
            return None
        if isinstance(rec.payload, np.ndarray):
            rec.payload = rec.payload.copy()
        self.commit()
        return rec

    def get(self, timeout: Optional[float] = None) -> Optional[Record]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rec = self.try_get()
            if rec is not None:
                return rec
            if self.ring.closed:
                # drain-then-stop: one more committed record may have
                # raced the close latch
                rec = self.try_get()
                if rec is not None:
                    return rec
                return None
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.0005)

    def get_view(self, timeout: Optional[float] = None) -> Optional[Record]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rec = self.try_get_view()
            if rec is not None:
                return rec
            if self.ring.closed:
                rec = self.try_get_view()
                if rec is not None:
                    return rec
                return None
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.0005)
