"""Process-mode ``ShardedIngest`` backend (ISSUE 15 tentpole).

``ProcessShardedIngest`` duck-types the thread backend's whole surface —
the ``Aggregator`` ingestion side (``process_l7`` / ``process_tcp`` /
``process_proc`` / ``process_k8s`` / ``gc`` / ``reap_zombies`` /
``flush_retries``) and the windowed-store side (``flush`` / ``drain`` /
``stats`` / the supervision gauges) — so ``runtime.service.Service`` and
the chaos/bench harnesses swap it in behind
``RuntimeConfig.ingest_backend = "process"`` with no caller changes.

Same scatter/close-wave/merge skeleton as ``aggregator/sharded.py``,
with every thread-mode sharing point replaced by an explicit exchange:

    submit (any thread) → hash-partition by connection key
        → [N request rings] → shard worker PROCESSES (spawn), each
          running the private Aggregator → ShardPartialStore loop with
          a PER-PROCESS Interner/ClusterInfo/DropLedger — out of the
          parent's GIL entirely
        → close waves: broadcast K_CLOSE; each worker aggregates its
          shard ON ITS OWN CORE and ships uid-LOCAL EdgePartial frames
          + an interner delta table through its response ring, then acks
        → merge thread: folds deltas into the SHARED Interner, remaps
          uids through the per-worker exchange table, recombines with
          ``GraphBuilder.build_from_partials`` — bit-identical to serial
          and to thread mode (the PR 5 equivalence property, extended).

Conservation through a SIGKILL (the chaos process-kill gate): the
parent logs every row it scatters per worker; the ring tail says exactly
which records the dead worker fully processed (commit-after-process —
a record mid-flight at the kill REPLAYS to the respawn, see ring.py);
the worker's ledger mirror in the STATS block says which consumed rows
it attributed; received partials say which it emitted. The residual —
rows pending in the dead store — is attributed ``dropped/shm<i>_kill``
at respawn, so ``pushed == emitted + ledger.total`` stays EXACT through
the kill.

Lock order (one direction, alazsan-stressed): ``_merge_lock`` →
``_io_lock`` (response drains / respawn) → ``_state_lock`` (acks,
stash, horizon) → per-ring producer locks → ledger/tracer leaf locks.
"""

from __future__ import annotations

import multiprocessing
import os
import pickle
import signal
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from alaz_tpu.aggregator.cluster import ClusterInfo
from alaz_tpu.aggregator.engine import AggregatorStats, _conn_keys
from alaz_tpu.aggregator.sharded import WorkerCrash, _W_FLOOR
from alaz_tpu.config import RuntimeConfig
from alaz_tpu.datastore.interface import DataStore
from alaz_tpu.events.intern import Interner
from alaz_tpu.events.k8s import K8sResourceMessage
from alaz_tpu.graph.builder import GraphBuilder
from alaz_tpu.graph.snapshot import GraphBatch
from alaz_tpu.logging import get_logger
from alaz_tpu.obs.recorder import FlightRecorder
from alaz_tpu.obs.spans import SpanTracer
from alaz_tpu.shm import codec
from alaz_tpu.shm.ring import (
    DEFAULT_RING_SLOTS,
    DEFAULT_SLOT_BYTES,
    KIND_NAMES,
    K_ACK,
    K_CLOSE,
    K_GC,
    K_K8S,
    K_L7,
    K_PROC,
    K_REAP,
    K_RETRIES,
    K_SEAL,
    K_STOP,
    K_TCP,
    K_WINDOW,
    RingClosed,
    RingConsumer,
    RingProducer,
    S_DONE_RECORDS,
    S_LAST_PERSIST,
    S_LATE_DROPPED,
    S_PENDING_RETRIES,
    S_REQUEST_COUNT,
    S_WATERMARK,
    ShmRing,
    W_FLOOR,
)
from alaz_tpu.shm.worker import WorkerSpec, shard_worker_main
from alaz_tpu.utils.ledger import DropLedger

log = get_logger("alaz_tpu.shm.pool")

_KIND_BY_NAME = {"l7": K_L7, "tcp": K_TCP}


class _WorkerHandle:
    """Parent-side books for one shard worker process. Mutated under the
    pool's ``_io_lock`` (drain/respawn) except the producer cursor and
    row log, which the per-ring ``put_lock`` serializes."""

    def __init__(self, index: int, req: ShmRing, resp: ShmRing):
        self.index = index
        self.req = req
        self.resp = resp
        self.producer = RingProducer(req)
        self.put_lock = threading.Lock()
        self.consumer = RingConsumer(resp, start_cursor=0)
        self.proc: Optional[multiprocessing.process.BaseProcess] = None
        self.generation = 0  # lockless-ok: monotonic int bumped only under the pool's _io_lock; racy reads (wave re-drive baseline, ring gauges) tolerate one-poll staleness
        self.spawned_at = 0.0  # monotonic; crash-loop detection
        self.fast_deaths = 0  # consecutive deaths within 1s of spawn
        self.respawn_after = 0.0  # backoff gate (io thread)
        # record/row books (the kill-conservation + backlog inputs):
        # EVERY produced record logs (end_cursor, l7_rows) so the
        # parent can reconstruct both the consumed-record count (the
        # done-counter reconciliation at settle) and the consumed L7
        # rows (the conservation equation)
        self.row_log: deque = deque()  # (end_cursor, l7_rows) unconsumed  # guarded-by: self.put_lock
        self.rows_consumed = 0  # pruned L7-row total (io thread)
        self.records_consumed = 0  # pruned record count (io thread)
        self.rows_in_partials = 0  # WINDOW frames received (io thread)
        self.mirror_folded: Dict[str, int] = {
            c: 0 for c in DropLedger.CAUSES
        }
        self.rows_lost_attributed = 0
        self.produced_records = 0  # guarded-by: self.put_lock
        # id-exchange table: worker-local interner id -> shared id
        self.remap = np.zeros(1024, dtype=np.int32)
        self.remap_size = 0

    # -- id exchange --------------------------------------------------------

    def fold_delta(
        self, base: int, strings: List[str], interner: Interner
    ) -> None:
        """Fold one delta-table ship into the shared interner and extend
        the remap. Ships arrive in ring order, so bases are contiguous;
        a gap means a protocol bug and must be loud."""
        if base != self.remap_size:
            raise RuntimeError(
                f"shm shard{self.index}: interner delta base {base} != "
                f"remap size {self.remap_size} (gen {self.generation})"
            )
        if not strings:
            return
        ids = interner.intern_many(strings)
        need = base + len(strings)
        if need > self.remap.shape[0]:
            grown = np.zeros(max(need, 2 * self.remap.shape[0]), np.int32)
            grown[: self.remap_size] = self.remap[: self.remap_size]
            self.remap = grown
        self.remap[base:need] = ids
        self.remap_size = need

    def remap_uids(self, local_ids: np.ndarray) -> np.ndarray:
        if local_ids.shape[0] and int(local_ids.max()) >= self.remap_size:
            raise RuntimeError(
                f"shm shard{self.index}: partial references local id "
                f"{int(local_ids.max())} beyond exchanged table "
                f"{self.remap_size}"
            )
        return self.remap[local_ids]

    # -- consumption accounting --------------------------------------------

    def prune_consumed(self) -> None:
        """Advance the consumed books past every record the worker has
        fully processed (ring tail passed it — commit-after-process)."""
        tail = self.req.tail
        with self.put_lock:
            while self.row_log and self.row_log[0][0] <= tail:
                self.rows_consumed += self.row_log.popleft()[1]
                self.records_consumed += 1


class ProcessShardedIngest:
    """N shard worker PROCESSES over shared-memory rings with close-wave
    merging — the out-of-GIL backend for the sharded host plane.

    Differences from the thread backend a caller can observe:
    ``tee`` is refused (an export sink would see worker-LOCAL interner
    ids — resolve-at-export would ship wrong strings; route exports off
    the merged batches instead); ``label_fn`` must be picklable (it
    crosses the spawn boundary); and cluster topology must arrive
    through :meth:`process_k8s` — a pre-populated ``cluster=`` argument
    is PARENT-side state (export naming, degree-cap uid parity) that
    never crosses into the workers, whose private ClusterInfos only see
    the ring broadcast. Everything else — ordering, conservation,
    bit-identical output — is contract-equal and property-tested
    against serial and thread mode.
    """

    def __init__(
        self,
        n_workers: int,
        interner: Optional[Interner] = None,
        config: Optional[RuntimeConfig] = None,
        cluster: Optional[ClusterInfo] = None,
        window_s: float = 1.0,
        on_batch: Optional[Callable[[GraphBatch], None]] = None,
        label_fn=None,
        renumber: bool = False,
        tee: Optional[DataStore] = None,
        autostart: bool = True,
        ledger: Optional[DropLedger] = None,
        fault_hook: Optional[Callable[[int, str], None]] = None,
        shed_block_s: float = 5.0,
        degree_cap: int = 0,
        sample_seed: int = 0,
        tracer: Optional[SpanTracer] = None,
        recorder: Optional[FlightRecorder] = None,
        slot_bytes: Optional[int] = None,
        ring_slots: Optional[int] = None,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        if tee is not None:
            raise ValueError(
                "ingest_backend=process does not support a tee datastore: "
                "worker REQUEST rows carry process-local interner ids the "
                "export sink cannot resolve (use the thread backend for "
                "the export tee, or export from merged batches)"
            )
        if label_fn is not None:
            try:
                pickle.dumps(label_fn)
            except Exception as exc:
                raise ValueError(
                    "ingest_backend=process requires a picklable label_fn "
                    f"(it crosses the spawn boundary): {exc}"
                ) from exc
        self.n = int(n_workers)
        self.ledger = ledger if ledger is not None else DropLedger()
        if tracer is None:
            tracer = SpanTracer(complete_at_emit=True, recorder=recorder)
        self.tracer = tracer
        self.recorder = recorder
        if recorder is not None and self.ledger.recorder is None:
            self.ledger.recorder = recorder
        self.fault_hook = fault_hook  # lockless-ok: attach-once chaos seam (wiring/harness, before traffic); callers null-check an atomic reference read
        self.shed_block_s = float(shed_block_s)
        self.interner = interner if interner is not None else Interner()
        self.config = config if config is not None else RuntimeConfig()
        self.cluster = (
            cluster if cluster is not None else ClusterInfo(self.interner)
        )
        self.window_s = window_s
        self.window_ms = int(window_s * 1000)
        self.on_batch = on_batch
        self.label_fn = label_fn
        # in-class appends happen inside the close-wave merge region;
        # main reads .batches only after stop()/join (happens-before)
        self.batches: List[GraphBatch] = []  # guarded-by: self._merge_lock
        # slot geometry: config knobs unless the caller overrides
        if slot_bytes is None:
            slot_bytes = getattr(
                self.config, "shm_slot_bytes", DEFAULT_SLOT_BYTES
            )
        if ring_slots is None:
            ring_slots = getattr(
                self.config, "shm_ring_slots", DEFAULT_RING_SLOTS
            )
        self.slot_bytes = int(slot_bytes)
        self.ring_slots = int(ring_slots)
        # the cap applies at the merge-stage assembly over SHARED-id
        # uids — the same placement (and the same N-invariance argument)
        # as the thread backend
        self.builder = GraphBuilder(
            window_s=window_s, renumber=renumber,
            degree_cap=degree_cap, sample_seed=sample_seed,
            ledger=self.ledger, tracer=self.tracer,
        )
        self._ctx = multiprocessing.get_context("spawn")
        self.workers: List[_WorkerHandle] = []
        for i in range(self.n):
            req = ShmRing(
                slot_bytes=self.slot_bytes, n_slots=self.ring_slots,
                create=True,
            )
            resp = ShmRing(
                slot_bytes=self.slot_bytes, n_slots=self.ring_slots,
                create=True,
            )
            self.workers.append(_WorkerHandle(i, req, resp))

        # wave / stash / horizon plane
        self._state_lock = threading.Lock()
        self._wave_acks: Dict[int, set] = {}  # guarded-by: self._state_lock
        self._wave_seq = 0  # guarded-by: self._state_lock
        self._stash: Dict[int, List[tuple]] = {}  # window -> [(shard, partial)]  # guarded-by: self._state_lock
        self._inflight = 0  # guarded-by: self._state_lock
        self._merged_upto = _W_FLOOR  # guarded-by: self._state_lock
        self._worker_restarts = 0  # guarded-by: self._state_lock
        # response-ring consumption + respawn are single-flight
        self._io_lock = threading.Lock()
        # whole close waves serialize (merge thread vs flush callers)
        self._merge_lock = threading.Lock()
        self.merge_s = 0.0  # guarded-by: self._merge_lock
        self.windows_merged = 0  # guarded-by: self._merge_lock
        self._last_wave_monotonic = time.monotonic()  # lockless-ok: written inside the merge lock's bounded-acquire region (which the lockset walk models since ISSUE 19); the sanction covers the racy float READ — it IS the last_wave_age_s freshness gauge. Every site is a plain float store/read, never a container mutation, so GIL-atomicity holds

        self._stop = threading.Event()
        self._merge_thread: Optional[threading.Thread] = None  # guarded-by: self._state_lock
        # final-books snapshot: stop() settles every mirror into this
        # dict BEFORE unlinking the segments, so post-stop reads of
        # stats/request_count (the chaos gates do this) stay valid
        self._final: Optional[dict] = None  # lockless-ok: written once by the one thread that wins the stop latch, read after stop returns
        if autostart:
            self.start()

    # -- lifecycle -----------------------------------------------------------

    def _spawn(self, h: _WorkerHandle) -> None:
        spec = WorkerSpec(
            shard_index=h.index,
            n_shards=self.n,
            req_ring=h.req.name,
            resp_ring=h.resp.name,
            window_ms=self.window_ms,
            resp_start_cursor=h.consumer.cursor,
            label_fn=self.label_fn,
            config=self.config,
            generation=h.generation,
        )
        p = self._ctx.Process(
            target=shard_worker_main, args=(spec,),
            name=f"alaz-shmshard{h.index}g{h.generation}", daemon=True,
        )
        p.start()
        h.proc = p
        h.spawned_at = time.monotonic()

    def start(self) -> None:
        with self._state_lock:
            if self._merge_thread is not None:
                return
            self._stop.clear()
            t = threading.Thread(
                target=self._merger_loop, name="alaz-shm-merge", daemon=True
            )
            self._merge_thread = t
        with self._io_lock:  # process handles move only under the io lock
            for h in self.workers:
                if h.proc is None:
                    self._spawn(h)
        t.start()

    def stop(self) -> None:
        if self._stop.is_set() and self._final is not None:
            return  # idempotent (close() then __del__)
        self._stop.set()
        with self._state_lock:
            t = self._merge_thread
            self._merge_thread = None
        if t is not None:
            t.join(timeout=10)
        for h in self.workers:
            # stop record first (wakes a blocked poll with intent), THEN
            # the close latch (after the latch, puts raise RingClosed)
            try:
                with h.put_lock:
                    h.producer.try_put(K_STOP, b"")
            except (RingClosed, ValueError):
                pass
            h.req.close_ring()
        deadline = time.monotonic() + 5.0
        with self._io_lock:  # merge thread is down: uncontended, held for order
            for h in self.workers:
                p = h.proc
                if p is None:
                    continue
                p.join(timeout=max(0.1, deadline - time.monotonic()))
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=2.0)
                if p.is_alive():
                    p.kill()
                    p.join(timeout=2.0)
                h.proc = None
        # settle the books BEFORE the segments go away: drain straggler
        # responses, fold every ledger mirror (conservation gates read
        # the pipeline ledger after stop), snapshot the gauge surfaces
        with self._io_lock:
            for h in self.workers:
                try:
                    self._drain_shard(h)
                    self._fold_mirror(h)
                    h.prune_consumed()
                except Exception as exc:
                    log.warning(f"shm shard{h.index} final drain failed: {exc}")
            total = AggregatorStats()
            final = {
                "request_count": 0, "late_dropped": 0,
                "pending_retries": 0, "last_persist": None,
            }
            for h in self.workers:
                for k, v in h.req.agg_stats_mirror().items():
                    setattr(total, k, getattr(total, k) + int(v))
                final["request_count"] += h.req.stat_u64(S_REQUEST_COUNT)
                final["late_dropped"] += h.req.stat_u64(S_LATE_DROPPED)
                final["pending_retries"] += h.req.stat_u64(S_PENDING_RETRIES)
                lp = h.req.stat_f64(S_LAST_PERSIST)
                if lp > 0.0 and (
                    final["last_persist"] is None or lp > final["last_persist"]
                ):
                    final["last_persist"] = lp
            final["stats"] = total
            for h in self.workers:
                for r in (h.req, h.resp):
                    r.detach()
                    r.unlink()
            self._final = final

    def close(self) -> None:
        self.stop()

    def wait_ready(self, timeout_s: float = 30.0) -> bool:
        """Block until every CURRENT-generation worker's loop is up
        (spawn + re-import is ~0.5-1 s per process). Optional — the
        rings buffer traffic submitted earlier just fine — but callers
        measuring steady-state throughput (bench) call this so pool
        construction cost stays outside their window, exactly where the
        thread backend's thread-start cost already sits."""
        from alaz_tpu.shm.ring import S_READY_GEN

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(
                h.req.stat_u64(S_READY_GEN) >= h.generation + 1
                for h in self.workers
            ):
                return True
            if self._stop.is_set():
                return False
            time.sleep(0.005)
        return False

    def __del__(self):  # best-effort: never leak /dev/shm segments
        try:
            if not self._stop.is_set():
                self.stop()
        except Exception:
            pass

    # -- supervision (ISSUE 6 contract, process edition) ---------------------

    @property
    def worker_restarts(self) -> int:
        with self._state_lock:
            return self._worker_restarts

    @property
    def last_wave_age_s(self) -> float:
        return time.monotonic() - self._last_wave_monotonic

    def _kill_shard(self, i: int, why: str) -> None:
        """The chaos seam's effect: SIGKILL the shard process — the
        hardest death (no atexit, no flush, books frozen mid-flight)."""
        h = self.workers[i]
        with self._io_lock:
            p = h.proc
            if p is None or not p.is_alive() or p.pid is None:
                return
            pid = p.pid
            if self.recorder is not None:
                self.recorder.record(
                    "worker_kill", worker=i, pid=pid, reason=why
                )
            log.warning(f"shm shard{i} (pid {pid}) SIGKILL: {why}")
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass

    def _supervise(self) -> List[int]:
        """Detect dead shard processes, settle their books exactly, and
        respawn them against the SAME rings (the request backlog — rows
        the dead worker never copied out — drains into the replacement
        in order). Returns the respawned indices so a waiting close wave
        can re-drive its close."""
        restarted: List[int] = []
        if self._stop.is_set():
            return restarted
        for h in self.workers:
            with self._io_lock:
                p = h.proc
                if p is None or p.is_alive():
                    continue
                now = time.monotonic()
                if now < h.respawn_after:
                    continue  # backoff: a crash-looping spawn must not storm
                self._settle_dead_shard(h)
                # exponential backoff on instant deaths (a worker that
                # cannot survive startup — import error, bad spec —
                # would otherwise respawn at poll frequency forever)
                if now - h.spawned_at < 1.0:
                    h.fast_deaths += 1
                    h.respawn_after = now + min(
                        2.0, 0.05 * (2 ** min(h.fast_deaths, 6))
                    )
                    if h.fast_deaths == 3:
                        log.error(
                            f"shm shard{h.index} died instantly 3× — the "
                            "spawn target cannot start. Common cause: the "
                            "owning script lacks an `if __name__ == "
                            "'__main__':` guard (spawn re-imports __main__"
                            "); also check the worker log for import "
                            "errors. Backing off respawns."
                        )
                else:
                    h.fast_deaths = 0
                h.generation += 1
                self._spawn(h)
            with self._state_lock:
                self._worker_restarts += 1
                restarts = self._worker_restarts
            restarted.append(h.index)
            if self.recorder is not None:
                self.recorder.record(
                    "worker_restart", worker=h.index, restart=restarts,
                    process=True,
                )
            log.warning(
                f"shm shard{h.index} worker respawned "
                f"(gen {h.generation}, restart #{restarts})"
            )
            # horizon alignment: the replacement starts with a fresh
            # store; the seal queues BEHIND the request backlog, so
            # backlog rows for already-merged windows still ship and
            # attribute as late at the merge (conserved, never silent).
            # BEST-EFFORT (plain bounded put, no supervision retry): a
            # _put_control here would recurse back into _supervise on a
            # full ring of a crash-looping worker; a missed seal is
            # safe — the merge itself late-drops anything below the
            # horizon (the ≤ merged_upto guard)
            with self._state_lock:
                horizon = self._merged_upto
            if horizon > _W_FLOOR:
                try:
                    with h.put_lock:
                        if h.producer.put(
                            K_SEAL, codec.SEAL_FRAME.pack(horizon),
                            timeout=0.2,
                        ):
                            h.produced_records += 1
                            h.row_log.append((h.producer.cursor, 0))
                except RingClosed:
                    pass
        return restarted

    def _settle_dead_shard(self, h: _WorkerHandle) -> None:
        """Settle a dead worker's books (caller holds ``_io_lock``):
        drain every committed response, fold the ledger mirror, then
        attribute the residual — rows the worker consumed but neither
        shipped in a partial nor ledgered — as ``dropped``. The exact
        equation the chaos process-kill gate checks."""
        exitcode = None if h.proc is None else h.proc.exitcode
        self._drain_shard(h)  # partials/acks committed before death
        self._fold_mirror(h)
        h.prune_consumed()
        # done-counter reconciliation: a kill between the dead worker's
        # ring commit and its S_DONE_RECORDS write would otherwise
        # desync produced-vs-done by one FOREVER (phantom backlog —
        # unfinished never 0, drain() never settles). The parent's
        # pruned record count is the authoritative consumed count; the
        # respawn continues from it.
        h.req.set_stat_u64(S_DONE_RECORDS, h.records_consumed)
        mirror = sum(h.mirror_folded.values())
        lost = (
            h.rows_consumed
            - h.rows_in_partials
            - mirror
            - h.rows_lost_attributed
        )
        if lost > 0:
            self.ledger.add("dropped", lost, reason=f"shm{h.index}_kill")
            h.rows_lost_attributed += lost
        elif lost < 0:
            # negative = double counting somewhere — loud, never silent
            log.error(
                f"shm shard{h.index}: kill accounting gap {lost} "
                f"(consumed={h.rows_consumed} partials={h.rows_in_partials} "
                f"mirror={mirror})"
            )
        if self.recorder is not None:
            self.recorder.record(
                "worker_crash", worker=h.index, reason=f"exit={exitcode}",
                rows_lost=max(0, lost), process=True,
            )
        log.warning(
            f"shm shard{h.index} worker died (exit {exitcode}); "
            f"{max(0, lost)} in-flight rows attributed dropped"
        )
        # the replacement brings a fresh interner: reset the exchange
        h.remap_size = 0

    # -- ingestion surface (Aggregator duck type) ----------------------------

    def process_l7(self, events: np.ndarray, now_ns: Optional[int] = None) -> None:
        self._scatter("l7", events, now_ns)

    def process_tcp(self, events: np.ndarray, now_ns: Optional[int] = None) -> None:
        self._scatter("tcp", events, now_ns)

    def process_proc(self, events: np.ndarray) -> None:
        # (pid, fd) sharding splits a pid's fds across workers: broadcast
        payload = codec.encode_events(events)
        for h in self.workers:
            self._put_control(h, K_PROC, payload)

    def process_k8s(self, msg: K8sResourceMessage) -> None:
        # fold into the PARENT cluster first — the shared interner gets
        # uid strings in the same deterministic order as the serial
        # path (degree-cap priorities are uid-pure; parity depends on
        # this) — then broadcast so every worker's private cluster can
        # attribute its shard's traffic
        self.cluster.handle_msg(msg)
        payload = pickle.dumps(msg)
        for h in self.workers:
            self._put_control(h, K_K8S, payload)

    def gc(self, now_ns: Optional[int] = None) -> None:
        for h in self.workers:
            self._put_control(h, K_GC, b"", now_ns=now_ns)

    def reap_zombies(self) -> None:
        for h in self.workers:
            self._put_control(h, K_REAP, b"")

    def flush_retries(self, now_ns: int):
        for h in self.workers:
            self._put_control(h, K_RETRIES, b"", now_ns=now_ns)
        return None

    def _fault(self, i: int, kind: str) -> None:
        """Chaos seam, process edition: the hook runs parent-side at
        item boundaries; a WorkerCrash verdict becomes a SIGKILL of the
        shard process — mid-wave when the item is a close."""
        hook = self.fault_hook
        if hook is None:
            return
        try:
            hook(i, kind)
        except WorkerCrash as exc:
            self._kill_shard(i, str(exc))

    def _scatter(self, kind: str, events: np.ndarray, now_ns) -> None:
        with self._state_lock:
            self._inflight += 1
        try:
            if self.n == 1:
                self._put_rows(0, kind, events, None, now_ns)
                return
            shard = (
                _conn_keys(events["pid"], events["fd"]) % np.uint64(self.n)
            ).astype(np.int64)
            for i in range(self.n):
                idx = np.flatnonzero(shard == i)
                if idx.shape[0]:
                    self._put_rows(i, kind, events, idx, now_ns)
        finally:
            with self._state_lock:
                self._inflight -= 1

    def _put_rows(self, i: int, kind: str, events, idx, now_ns) -> None:
        """Bounded-backpressure row put: gather the shard slice straight
        into the ring (one copy — the scatter thread's rate is the
        pipeline ceiling) and block at most ``shed_block_s`` on a
        backlogged ring, then SHED to the ledger (ring-full is the
        queue-full of this backend)."""
        self._fault(i, kind)
        h = self.workers[i]
        n = int(events.shape[0] if idx is None else idx.shape[0])
        try:
            with h.put_lock:
                ok = h.producer.put_rows(
                    _KIND_BY_NAME[kind], events, idx, now_ns=now_ns,
                    timeout=self.shed_block_s,
                )
                if ok:
                    h.produced_records += 1
                    # ONLY L7 rows carry weight in the kill-conservation
                    # books: the equation is pushed-L7 == emitted +
                    # ledger, and a TCP event never becomes a REQUEST
                    # row or a partial — row-weighting it would
                    # attribute the worker's entire lifetime TCP intake
                    # as "dropped" at the first kill
                    h.row_log.append(
                        (h.producer.cursor, n if kind == "l7" else 0)
                    )
                    return
        except RingClosed:
            # per-event attribution, like the thread backend's
            # _put_or_shed (the kill-settle equation above is the only
            # place TCP must stay out — lifetime vs in-flight)
            self.ledger.add("dropped", n, reason="closed")
            return
        self.ledger.add("shed", n, reason=f"shard{i}_backlog")
        log.warning(
            f"shm shard{i} ring backlogged past {self.shed_block_s}s; "
            f"shed {n} rows"
        )

    def _put_control(
        self, h: _WorkerHandle, kind: int, payload, now_ns=None,
        deadline_s: float = 60.0,
    ) -> bool:
        """Control-plane put: retries a full ring with supervision
        between rounds (a ring stays full forever only when its worker
        died), but BOUNDED — a worker that cannot start at all (the
        crash-loop path) must cost a dropped control record and a loud
        log, never a wedged k8s/housekeeping/merge thread. Every control
        kind tolerates loss: closes re-drive by generation, seals are
        belt-and-braces under the merge's own horizon guard, gc/reap/
        retries are periodic, and a k8s fold for a worker that never
        runs folds nothing either way."""
        deadline = time.monotonic() + deadline_s
        while not self._stop.is_set():
            try:
                with h.put_lock:
                    ok = h.producer.put(kind, payload, now_ns=now_ns, timeout=0.5)
                    if ok:
                        h.produced_records += 1
                        h.row_log.append((h.producer.cursor, 0))
                        return True
            except RingClosed:
                return False
            if time.monotonic() > deadline:
                log.error(
                    f"shm shard{h.index}: control record "
                    f"{KIND_NAMES.get(kind, kind)} undeliverable for "
                    f"{deadline_s:.0f}s (worker unstartable?); dropping it"
                )
                return False
            self._supervise()
        return False

    # -- response drain / merge plane ----------------------------------------

    def _drain_shard(self, h: _WorkerHandle) -> None:
        """Drain one response ring (caller holds ``_io_lock``). Folds
        interner deltas, remaps partials into shared-id space, stamps
        the span plane, records acks. View+commit: decode_window copies
        the columns it keeps, so the frame itself never needs a
        materializing pass."""
        while True:
            rec = h.consumer.try_get_view()
            if rec is None:
                return
            try:
                self._consume_response(h, rec)
            finally:
                h.consumer.commit()

    def _consume_response(self, h: _WorkerHandle, rec) -> None:
        if rec.kind == K_WINDOW:
            (
                w, partial, base, strings, t_first, t_close, dur,
            ) = codec.decode_window(rec.payload)
            h.fold_delta(base, strings, self.interner)
            partial.from_uid = h.remap_uids(partial.from_uid)
            partial.to_uid = h.remap_uids(partial.to_uid)
            h.rows_in_partials += partial.rows
            ws_ms = w * self.window_ms
            tr = self.tracer
            if tr is not None:
                tr.first_row(ws_ms, t=t_first if t_first > 0 else None)
                tr.close_start(ws_ms, t=t_close if t_close > 0 else None)
                tr.observe(ws_ms, "shard_close", dur)
            with self._state_lock:
                self._stash.setdefault(w, []).append((h.index, partial))
        elif rec.kind == K_ACK:
            wave, _ = codec.decode_close(rec.payload)
            with self._state_lock:
                if wave in self._wave_acks:
                    self._wave_acks[wave].add(h.index)

    def _drain_responses(self) -> None:
        with self._io_lock:
            for h in self.workers:
                self._drain_shard(h)
                self._fold_mirror(h)
                h.prune_consumed()

    def _fold_mirror(self, h: _WorkerHandle) -> None:
        """Fold the worker's crash-surviving ledger mirror into the
        pipeline ledger (delta since last fold, per cause) — one
        bookkeeper for the conservation equation, parent side."""
        mirror = h.req.ledger_mirror()
        for cause, cur in mirror.items():
            delta = cur - h.mirror_folded[cause]
            if delta > 0:
                self.ledger.add(cause, delta, reason=f"shm{h.index}")
                h.mirror_folded[cause] = cur

    def _closable(self) -> Optional[int]:
        """Highest window id safe to close — the thread backend's rule,
        read through the STATS blocks: min over busy workers' processed
        watermarks; all-idle degenerates to max(wm) - 1, suppressed
        while a scatter is mid-flight."""
        with self._state_lock:
            inflight = self._inflight
        busy: List[int] = []
        idle: List[int] = []
        for h in self.workers:
            wm = h.req.stat_i64(S_WATERMARK)
            with h.put_lock:
                produced = h.produced_records
            backlog = produced - h.req.stat_u64(S_DONE_RECORDS)
            if backlog > 0:
                if wm == W_FLOOR:
                    return None  # queued work on a worker with no progress
                busy.append(wm)
            elif wm != W_FLOOR:
                idle.append(wm)
        if busy:
            return min(busy) - 1
        if idle and not inflight:
            return max(idle) - 1
        return None

    def _merger_loop(self) -> None:
        while not self._stop.is_set():
            self._drain_responses()
            self._supervise()
            closable = self._closable()
            with self._state_lock:
                ready = closable is not None and closable > self._merged_upto
            if self._stop.is_set():
                return
            if ready:
                self._run_close_wave(closable, timeout_s=60.0)
            else:
                time.sleep(0.02)

    def _start_wave(self) -> int:
        with self._state_lock:
            self._wave_seq += 1
            wave = self._wave_seq
            self._wave_acks[wave] = set()
            return wave

    def _run_close_wave(
        self, upto: Optional[int], timeout_s: Optional[float] = None
    ) -> bool:
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        if timeout_s is None:
            self._merge_lock.acquire()  # alazlint: disable=ALZ012,ALZ042 -- paired with the finally below; timeout branch needs acquire(timeout=) which `with` can't express. Unbounded only on explicit caller opt-in (every entry-surface caller passes a budget)
        elif not self._merge_lock.acquire(timeout=timeout_s):  # alazlint: disable=ALZ012 -- bounded acquire (a stalled merge must not wedge flush); released in the finally
            log.error(
                f"shm close wave: merge lock not free within {timeout_s}s; "
                "giving up this wave"
            )
            return False
        windows: List[int] = []
        try:
            gen0 = [h.generation for h in self.workers]
            wave = self._start_wave()
            close_payload = codec.encode_close(wave, upto)
            for h in self.workers:
                self._fault(h.index, "close")
                self._put_control(h, K_CLOSE, close_payload)
            remaining = (
                None if deadline is None
                else max(deadline - time.monotonic(), 0.05)
            )
            if not self._await_wave(wave, upto, remaining, gen0):
                return False
            self._drain_responses()  # every acked worker's windows are in
            t0 = time.perf_counter()
            with self._state_lock:
                windows = sorted(
                    w for w in self._stash if upto is None or w <= upto
                )
                taken = {w: self._stash.pop(w) for w in windows}
                merged_upto = self._merged_upto
            for w in windows:
                parts = [p for _, p in sorted(taken[w], key=lambda t: t[0])]
                ws_ms = w * self.window_ms
                if w <= merged_upto:
                    # a respawned worker's backlog re-shipped a window
                    # the horizon already passed: re-emitting would
                    # corrupt every downstream consumer — attribute and
                    # drop (the seal-horizon contract, parent side)
                    late_rows = sum(p.rows for p in parts)
                    self.ledger.add(
                        "late", late_rows, reason="sealed_horizon"
                    )
                    self.tracer.discard(ws_ms)
                    continue
                batch = self.builder.build_from_partials(
                    parts,
                    window_start_ms=ws_ms,
                    window_end_ms=(w + 1) * self.window_ms,
                )
                if self.on_batch is not None:
                    self.on_batch(batch)
                else:
                    self.batches.append(batch)
                self.tracer.emit(ws_ms)
            self.merge_s += time.perf_counter() - t0
            self.windows_merged += len(windows)
            self._last_wave_monotonic = time.monotonic()
        finally:
            self._merge_lock.release()
        target = upto
        if windows and (target is None or windows[-1] > target):
            target = windows[-1]
        if target is not None:
            seal = False
            with self._state_lock:
                if target > self._merged_upto:
                    self._merged_upto = target
                    seal = True
            if seal:
                payload = codec.SEAL_FRAME.pack(target)
                for h in self.workers:
                    self._put_control(h, K_SEAL, payload)
        return True

    def _await_wave(
        self,
        wave: int,
        upto: Optional[int],
        timeout_s: Optional[float],
        gen0: List[int],
    ) -> bool:
        """Wait for every worker's ack, draining and self-healing as it
        waits: a worker that died can never ack, so each round
        supervises (respawn) and RE-DRIVES the close to any worker whose
        generation moved past the wave-start baseline without an ack
        (its close record died in the old process's copy-out, or sits
        behind the backlog the replacement drains first — a duplicate
        close is idempotent, the straggler ack a set entry)."""
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        seen_gen = list(gen0)
        close_payload = codec.encode_close(wave, upto)
        while True:
            self._drain_responses()
            with self._state_lock:
                acked = set(self._wave_acks.get(wave, ()))
                if len(acked) >= self.n:
                    del self._wave_acks[wave]
                    return True
            if self._stop.is_set():
                with self._state_lock:
                    self._wave_acks.pop(wave, None)
                return False
            if deadline is not None and time.monotonic() > deadline:
                with self._state_lock:
                    self._wave_acks.pop(wave, None)
                log.error(
                    f"shm close wave {wave} timed out awaiting worker acks"
                )
                return False
            self._supervise()
            for h in self.workers:
                if h.generation != seen_gen[h.index] and h.index not in acked:
                    self._put_control(h, K_CLOSE, close_payload)
                    seen_gen[h.index] = h.generation
            time.sleep(0.002)

    # -- windowed-store surface ---------------------------------------------

    def flush(self, timeout_s: float = 30.0) -> bool:
        """Close and merge every open window; close requests queue
        BEHIND all scattered rows (ring FIFO), so the wave ack means
        each worker processed everything in flight. Bounded: a kill
        mid-wave respawns + re-drives; a stall past the budget yields
        False with all state intact."""
        return self._run_close_wave(None, timeout_s=timeout_s)

    def drain(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.unfinished == 0:
                return True
            time.sleep(0.002)
        return False

    @property
    def unfinished(self) -> int:
        if self._final is not None:
            return 0
        total = 0
        for h in self.workers:
            with h.put_lock:
                produced = h.produced_records
            total += max(0, produced - h.req.stat_u64(S_DONE_RECORDS))
        return total

    @property
    def pending_retries(self) -> int:
        if self._final is not None:
            return self._final["pending_retries"]
        return sum(
            h.req.stat_u64(S_PENDING_RETRIES) for h in self.workers
        )

    @property
    def request_count(self) -> int:
        if self._final is not None:
            return self._final["request_count"]
        return sum(h.req.stat_u64(S_REQUEST_COUNT) for h in self.workers)

    @property
    def late_dropped(self) -> int:
        if self._final is not None:
            return self._final["late_dropped"]
        return sum(h.req.stat_u64(S_LATE_DROPPED) for h in self.workers)

    @property
    def last_persist_monotonic(self) -> Optional[float]:
        if self._final is not None:
            return self._final["last_persist"]
        stamps = [
            h.req.stat_f64(S_LAST_PERSIST)
            for h in self.workers
            if h.req.stat_f64(S_LAST_PERSIST) > 0.0
        ]
        return max(stamps) if stamps else None

    @property
    def stats(self) -> AggregatorStats:
        """Aggregated engine stats across the shard worker processes —
        read from the crash-surviving STATS mirrors (a snapshot; the
        summed object is fresh per read; stop() freezes the final one)."""
        if self._final is not None:
            return self._final["stats"]
        total = AggregatorStats()
        for h in self.workers:
            for k, v in h.req.agg_stats_mirror().items():
                setattr(total, k, getattr(total, k) + int(v))
        return total

    def shm_req_pending(self) -> int:
        """Request-side committed-but-unconsumed slots, summed — the
        scrape-path gauge read. Lock-free on purpose: cursor-hint reads
        only, no put_lock traffic on the scatter path per scrape."""
        if self._final is not None:
            return 0
        return sum(h.req.pending_slots for h in self.workers)

    def shm_resp_pending(self) -> int:
        if self._final is not None:
            return 0
        return sum(h.resp.pending_slots for h in self.workers)

    def ring_stats(self) -> dict:
        """Per-worker ring occupancy/backlog gauges (obs plane)."""
        if self._final is not None:
            return {}
        out = {}
        for h in self.workers:
            with h.put_lock:
                produced = h.produced_records
            out[str(h.index)] = {
                "req_pending_slots": h.req.pending_slots,
                "resp_pending_slots": h.resp.pending_slots,
                "ring_slots": h.req.n_slots,
                "backlog_records": max(
                    0, produced - h.req.stat_u64(S_DONE_RECORDS)
                ),
                "generation": h.generation,
            }
        return out
