"""The shard worker process (ISSUE 15): one spawn target per shard.

Runs the SAME private per-shard loop the thread backend runs —
``Aggregator`` → ``ShardPartialStore`` over this shard's slice of the
connection-key partition — but in its own interpreter, out of the
parent's GIL entirely. Everything thread mode shared is replaced:

- the shared Interner → a PRIVATE per-process Interner; closed windows
  ship uid-LOCAL ``EdgePartial`` frames plus a delta string table, and
  the parent folds + remaps at merge (the id-exchange);
- the shared ClusterInfo → a private one, fed the same k8s control
  messages by ring broadcast (cross-process state is rings + deltas
  only — the alazrace process-role contract);
- the shared DropLedger → a private ledger whose per-cause totals
  mirror into the request ring's STATS block on every add, so the books
  survive a SIGKILL and the parent can prove exact conservation through
  the kill;
- the shared SpanTracer → a local span clock; first-row/close stamps
  ride the window frames (CLOCK_MONOTONIC is system-wide) and feed the
  parent's tracer, so the window lifecycle stays fully attributed.

Single-threaded by construction: the worker owns both ring cursors, its
stats block, and every object it builds — no locks are shared across
the spawn boundary, and none are needed inside it.
"""

from __future__ import annotations

import pickle
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from alaz_tpu.events.schema import (
    L7_EVENT_DTYPE,
    PROC_EVENT_DTYPE,
    TCP_EVENT_DTYPE,
)
from alaz_tpu.logging import get_logger
from alaz_tpu.shm import codec, ring as shm_ring
from alaz_tpu.shm.ring import (
    AGG_STAT_FIELDS,
    K_ACK,
    K_CLOSE,
    K_GC,
    K_K8S,
    K_L7,
    K_PROC,
    K_REAP,
    K_RETRIES,
    K_SEAL,
    K_STOP,
    K_TCP,
    K_WINDOW,
    RingClosed,
    RingConsumer,
    RingProducer,
    S_DONE_RECORDS,
    S_HEARTBEAT,
    S_LAST_PERSIST,
    S_LATE_DROPPED,
    S_LEDGER,
    S_PENDING_RETRIES,
    S_REQUEST_COUNT,
    S_WATERMARK,
    S_AGG_STATS,
    ShmRing,
    W_FLOOR,
)
from alaz_tpu.utils.ledger import DropLedger

log = get_logger("alaz_tpu.shm.worker")


@dataclass
class WorkerSpec:
    """Everything a spawned shard worker needs — picklable by contract
    (spawn pickles the Process args; a non-picklable ``label_fn`` is
    refused at pool construction, not at first traffic)."""

    shard_index: int
    n_shards: int
    req_ring: str  # shm segment names — the only shared state
    resp_ring: str
    window_ms: int
    resp_start_cursor: int = 0
    label_fn: Optional[object] = None
    config: Optional[object] = None  # RuntimeConfig (dataclass, picklable)
    generation: int = 0


class _ShmLedger(DropLedger):
    """DropLedger whose per-cause totals mirror into the request ring's
    STATS block — the crash-surviving half of the books. Totals CONTINUE
    across respawns (the base offsets are the predecessor's mirror), and
    the mirror flush is DEFERRED to record boundaries, AFTER the ring
    commit: a kill mid-record then replays the record with its buffered
    adds discarded (no double-attribution), and a kill between commit
    and flush shifts at most one record's causes into the parent's
    ``dropped`` residual — conservation stays exact either way."""

    def __init__(self, stats_ring: ShmRing):
        super().__init__()
        self._ring = stats_ring
        self._base = stats_ring.ledger_mirror()
        self._dirty: set = set()

    def add(self, cause, n, reason=None):
        super().add(cause, n, reason=reason)
        if n > 0:
            self._dirty.add(cause)

    def flush_mirror(self) -> None:
        for cause in self._dirty:
            idx = self.CAUSES.index(cause)
            self._ring.set_stat_u64(
                S_LEDGER + 8 * idx, self._base[cause] + self.count(cause)
            )
        self._dirty.clear()


class _SpanClock:  # role-private: built and mutated only inside one single-threaded shard worker process; nothing parent-side ever holds a reference
    """SpanTracer duck type for the worker side of the span plane: keeps
    the per-window first-row / close-start stamps and the shard-close
    duration; the stamps ride the window frames back to the parent's
    real tracer. CLOCK_MONOTONIC (= time.perf_counter here) is
    system-wide on the deployment target, so the parent can subtract."""

    def __init__(self):
        self.first: dict = {}
        self.close: dict = {}
        self.dur: dict = {}

    def first_row(self, ws_ms: int) -> None:
        if ws_ms not in self.first:
            self.first[ws_ms] = time.perf_counter()

    def close_start(self, ws_ms: int) -> None:
        if ws_ms not in self.close:
            self.close[ws_ms] = time.perf_counter()

    def observe(self, ws_ms: int, stage: str, dur_s: float) -> None:
        if stage == "shard_close":
            prev = self.dur.get(ws_ms, 0.0)
            if dur_s > prev:
                self.dur[ws_ms] = dur_s

    def pop(self, ws_ms: int):
        """(first_row_t, close_start_t, close_dur_s) for a shipped
        window; entries drop once shipped (bounded state)."""
        t0 = self.first.pop(ws_ms, 0.0)
        tc = self.close.pop(ws_ms, t0)
        return t0, tc, self.dur.pop(ws_ms, 0.0)

    def prune_upto(self, ws_ms_limit: int) -> None:
        """Drop stamps for windows the close horizon passed WITHOUT
        shipping (late-dropped stragglers, sealed windows): pop() never
        runs for those, and a long-lived worker must not accumulate one
        dict entry per late window forever."""
        for d in (self.first, self.close, self.dur):
            for w in [w for w in d if w <= ws_ms_limit]:
                del d[w]


def shard_worker_main(spec: WorkerSpec) -> None:
    """Spawn target: attach the rings and run the shard loop until the
    parent closes the request ring or sends K_STOP."""
    from alaz_tpu.aggregator.cluster import ClusterInfo
    from alaz_tpu.aggregator.engine import Aggregator
    from alaz_tpu.aggregator.sharded import ShardPartialStore
    from alaz_tpu.config import RuntimeConfig
    from alaz_tpu.events.intern import Interner

    req = ShmRing(name=spec.req_ring)
    resp = ShmRing(name=spec.resp_ring)
    consumer = RingConsumer(req)  # resumes at the persisted tail
    producer = RingProducer(resp, start_cursor=spec.resp_start_cursor)

    interner = Interner()
    cluster = ClusterInfo(interner)
    ledger = _ShmLedger(req)
    clock = _SpanClock()
    store = ShardPartialStore(
        spec.window_ms,
        label_fn=spec.label_fn,
        aggregate=True,  # partials always: raw rows carry local uids
        ledger=ledger,
        tracer=clock,
    )
    config = spec.config if spec.config is not None else RuntimeConfig()
    agg = Aggregator(
        store, interner=interner, config=config, cluster=cluster,
        ledger=ledger,
    )
    shipped_strings = 0  # interner rows already sent as deltas
    done = req.stat_u64(S_DONE_RECORDS)  # continue the predecessor's count
    heartbeat = req.stat_u64(S_HEARTBEAT)
    # a fresh process has a fresh (empty) store: announce "no watermark"
    # so the parent's close rule waits for real progress, not the dead
    # predecessor's horizon
    req.set_stat_i64(S_WATERMARK, W_FLOOR)
    # engine backend (ISSUE 16): ENGINE_BACKEND=native reaches this
    # spawned process through RuntimeConfig.engine_backend's env-reading
    # default (or the parent's explicit config). dlopen + layout-check
    # the .so BEFORE the readiness handshake so the first traffic batch
    # never pays the load inside a caller's measured window.
    if agg._use_native_engine():
        eng = agg._native_l7_engine()
        log.info(
            f"shm shard{spec.shard_index} L7 engine backend: native "
            f"(loaded={eng is not None})"
        )
    # readiness handshake: generation+1 (never 0) says THIS generation's
    # loop is about to poll — wait_ready() pins pool spawn cost outside
    # a caller's measured window (the bench's steady-state contract)
    req.set_stat_u64(shm_ring.S_READY_GEN, spec.generation + 1)

    def _ship_windows(taken: dict) -> None:
        nonlocal shipped_strings
        for w in sorted(taken):
            partial = taken[w]
            cur = len(interner)
            delta = (
                interner.strings_since(shipped_strings)
                if cur > shipped_strings
                else []
            )
            t0, tc, dur = clock.pop(w * spec.window_ms)
            payload = codec.encode_window(
                w, partial, shipped_strings, delta, t0, tc, dur
            )
            shipped_strings = cur
            _put_resp(K_WINDOW, payload, rows=partial.rows)

    def _put_resp(kind: int, payload: bytes, rows: int = 0) -> None:
        # must-deliver with a liveness escape: a response ring can only
        # stay full while the parent stopped draining — which means the
        # parent is gone or stopping, and the request ring's close latch
        # is the signal to give up
        while not producer.put(kind, payload, rows=rows, timeout=0.5):
            if req.closed:
                raise RingClosed(req.name)

    def _sync_stats() -> None:
        req.set_stat_i64(
            S_WATERMARK,
            W_FLOOR if store.watermark is None else int(store.watermark),
        )
        req.set_stat_u64(S_REQUEST_COUNT, store.request_count)
        req.set_stat_u64(S_LATE_DROPPED, store.late_dropped)
        req.set_stat_u64(S_PENDING_RETRIES, agg.pending_retries)
        lp = store.last_persist_monotonic
        req.set_stat_f64(S_LAST_PERSIST, 0.0 if lp is None else float(lp))
        for i, f in enumerate(AGG_STAT_FIELDS):
            req.set_stat_u64(S_AGG_STATS + 8 * i, getattr(agg.stats, f))

    log.info(
        f"shm shard{spec.shard_index} worker up "
        f"(gen {spec.generation}, ring {spec.req_ring})"
    )
    while True:
        # zero-copy view; the slots stay reserved until commit() below,
        # so a SIGKILL mid-record REPLAYS it against the respawn's fresh
        # state instead of losing it
        rec = consumer.get_view(timeout=0.05)
        if rec is None:
            if req.closed:
                break
            continue
        heartbeat += 1
        req.set_stat_u64(S_HEARTBEAT, heartbeat)
        kind = rec.kind
        try:
            if kind == K_L7:
                agg.process_l7(
                    codec.decode_events(rec.payload, L7_EVENT_DTYPE),
                    now_ns=rec.now_ns,
                )
            elif kind == K_TCP:
                agg.process_tcp(
                    codec.decode_events(rec.payload, TCP_EVENT_DTYPE),
                    now_ns=rec.now_ns,
                )
            elif kind == K_CLOSE:
                wave, upto = codec.decode_close(rec.payload)
                try:
                    store.close_upto(upto)
                    _ship_windows(store.take_ready(upto))
                    if upto is not None:
                        # stamps for windows the horizon passed without
                        # shipping (late stragglers) would otherwise
                        # leak one entry per window forever
                        clock.prune_upto(upto * spec.window_ms)
                finally:
                    # the ack must flow even if aggregation raised — a
                    # silent miss would strand the wave until timeout
                    # (same contract as the thread worker's finally)
                    _put_resp(K_ACK, codec.encode_close(wave, upto))
            elif kind == K_PROC:
                agg.process_proc(
                    codec.decode_events(rec.payload, PROC_EVENT_DTYPE)
                )
            elif kind == K_K8S:
                agg.process_k8s(pickle.loads(bytes(rec.payload)))
            elif kind == K_RETRIES:
                agg.flush_retries(
                    rec.now_ns if rec.now_ns is not None else time.time_ns()
                )
            elif kind == K_GC:
                agg.gc(rec.now_ns)
            elif kind == K_REAP:
                agg.reap_zombies()
            elif kind == K_SEAL:
                store.seal_upto(codec.SEAL_FRAME.unpack_from(rec.payload)[0])
            elif kind == K_STOP:
                break
        except RingClosed:
            consumer.commit()
            break
        except Exception as exc:  # keep the shard alive; mirror the thread worker
            # a poison batch's rows reach neither emit nor retry —
            # attribute them so conservation holds through it
            if kind in (K_L7, K_TCP):
                ledger.add("dropped", rec.rows, reason="batch_error")
            log.warning(
                f"shm shard{spec.shard_index} "
                f"{shm_ring.KIND_NAMES.get(kind, kind)} record failed: {exc}"
            )
        # ORDER IS THE CRASH CONTRACT: commit (consume point) first,
        # mirror flush second — a kill between the two shifts this one
        # record's causes into the parent's `dropped` residual, never
        # loses or double-counts a row
        consumer.commit()
        ledger.flush_mirror()
        done += 1
        req.set_stat_u64(S_DONE_RECORDS, done)
        if kind in (K_L7, K_TCP, K_RETRIES, K_CLOSE, K_SEAL):
            _sync_stats()
    ledger.flush_mirror()
    _sync_stats()
    req.detach()
    resp.detach()
    log.info(f"shm shard{spec.shard_index} worker exiting cleanly")
