"""alaz_tpu — a TPU-native service-map observability + graph-learning framework.

A ground-up re-design of the capabilities of getanteon/alaz (eBPF Kubernetes
service-map agent, see /root/reference) around a columnar streaming data plane
and a JAX/XLA/Pallas graph-learning backend:

- ``alaz_tpu.events``     — columnar event schemas (the ebpf/ consumer analog)
- ``alaz_tpu.protocols``  — L7 protocol classifiers/parsers (the ebpf/c analog)
- ``alaz_tpu.aggregator`` — vectorized stream join: events × sockets × k8s → edges
- ``alaz_tpu.datastore``  — pluggable sinks (DataStore interface analog)
- ``alaz_tpu.replay``     — simulator / trace replay harness (test plane)
- ``alaz_tpu.graph``      — windowed COO graph batching for the device
- ``alaz_tpu.ops``        — segment/gather ops incl. Pallas TPU kernels
- ``alaz_tpu.models``     — GraphSAGE / GAT / temporal GNN anomaly scorers
- ``alaz_tpu.parallel``   — mesh, sharding, collectives, halo exchange
- ``alaz_tpu.train``      — objectives, train/eval steps, checkpointing
- ``alaz_tpu.runtime``    — the end-to-end streaming service loop

Design principle: everything hot is a fixed-dtype array batch. Strings are
interned to int32 ids at the edge of the system; joins are vectorized numpy
on the host and everything on-device is static-shape, bf16-friendly XLA.

The package intentionally does NOT import jax at the top level: the data
plane (events/aggregator/datastore/replay) is importable and usable without
any accelerator present.
"""

__version__ = "0.1.0"

__all__ = ["__version__"]
