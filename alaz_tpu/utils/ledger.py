"""The drop ledger — unified row-loss accounting for the host plane.

The reference's loss story is scattered counters: perf-buffer drops
logged per ring (l7.go:681-687), channel-mouth drops (l7.go:764-770),
late-window drops in the store, HTTP batches that exhausted retries.
Under fault injection (alaz_tpu/chaos) that scatter is unauditable, so
the ledger centralizes it behind one contract:

    every row the pipeline loses is attributed to EXACTLY ONE cause,
    and row conservation becomes a checkable invariant:

        pushed == emitted + ledger.total

The causes are closed-world on purpose — a new loss path must pick one
(or grow the vocabulary here, `make specs` for the wire table, and the
metric registry, in ONE move — alazflow's ALZ041 pins all three sides):

- ``dropped``      — infrastructure loss: a full bounded queue at the
                     source boundary, or rows in flight on a worker
                     thread when it crashed.
- ``late``         — rows that arrived behind the sealed window horizon
                     (duplicate/reordered/stalled delivery).
- ``quarantined``  — rows in malformed wire frames the ingest socket
                     rejected while resyncing the stream.
- ``shed``         — deliberate backpressure: the pipeline chose to
                     drop under sustained overload rather than block
                     its producer past the shed window.
- ``sampled``      — degree-capped reservoir sampling at window close
                     (ISSUE 7): request rows on edges cut because their
                     dst exceeded ``degree_cap`` fan-in. Deliberate and
                     deterministic — the hot-key defense, not a fault.
- ``filtered``     — semantic aggregator rejection (ISSUE 8): rows the
                     join/attribution stage dropped by design — no
                     socket line after the retry ladder, non-pod
                     source, per-pid rate limit. Previously a separate
                     "semantic" side-channel (stats counters) the
                     conservation gates had to add back in; ledgering
                     them makes ``pushed == emitted + ledger.total``
                     exact with no second bookkeeper.

``reason`` sub-attribution is free-form ("shard2", "worker_crash") and
feeds debugging; the conservation math uses only the cause totals.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple


class DropLedger:
    """Thread-safe per-cause drop counters with reason sub-attribution.

    Shared by every stage of one pipeline (queues, shard stores, the
    scatter plane, the ingest socket), so a chaos run can check
    conservation with one read instead of chasing per-stage counters.
    """

    CAUSES = ("dropped", "late", "quarantined", "shed", "sampled", "filtered")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {c: 0 for c in self.CAUSES}  # guarded-by: self._lock
        self._reasons: Dict[Tuple[str, str], int] = {}  # guarded-by: self._lock
        # optional flight recorder (ISSUE 9, alaz_tpu/obs): when attached,
        # every ledger decision becomes a structured ring event — the
        # drop trail a post-incident dump replays. Attach-once at wiring
        # time (service / harness); adds are per-chunk, never per row.
        self.recorder = None  # lockless-ok: attach-once wiring before the pipeline runs; readers null-check an atomic reference swap

    def add(self, cause: str, n: int, reason: Optional[str] = None) -> None:
        """Attribute ``n`` lost rows to ``cause``. Unknown causes raise —
        the exactly-one-of contract forbids inventing buckets at a call
        site the conservation gates don't know about."""
        if cause not in self.CAUSES:
            raise ValueError(
                f"unknown drop cause {cause!r}; pick one of {self.CAUSES}"
            )
        if n <= 0:
            return
        with self._lock:
            self._counts[cause] += int(n)
            if reason is not None:
                key = (cause, reason)
                self._reasons[key] = self._reasons.get(key, 0) + int(n)
        rec = self.recorder
        if rec is not None:
            # outside the ledger lock: the recorder has its own ring
            # lock and never calls back into the ledger
            rec.record("ledger", cause=cause, n=int(n), reason=reason)

    def count(self, cause: str) -> int:
        with self._lock:
            return self._counts[cause]

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    def snapshot(self) -> dict:
        """One JSON-able view: cause totals, the grand total, and the
        reason breakdown as "cause/reason" keys."""
        with self._lock:
            out = dict(self._counts)
            out["total"] = sum(self._counts.values())
            out["reasons"] = {
                f"{c}/{r}": n for (c, r), n in sorted(self._reasons.items())
            }
            return out

    def conservation_gap(self, pushed: int, emitted: int) -> int:
        """``pushed - emitted - total`` — zero iff every pushed row is
        either emitted or attributed. Positive = rows vanished untracked;
        negative = double counting (both are bugs)."""
        return int(pushed) - int(emitted) - self.total
