"""Shared utilities: bounded queues, clocks, rate limiting."""

from alaz_tpu.utils.queues import BatchQueue, QueueClosed
from alaz_tpu.utils.clock import Clock, VirtualClock, WallClock
from alaz_tpu.utils.ratelimit import TokenBucket

__all__ = [
    "BatchQueue",
    "QueueClosed",
    "Clock",
    "VirtualClock",
    "WallClock",
    "TokenBucket",
]
