"""Token-bucket rate limiting.

The reference rate-limits per-pid event processing at 100 events/s with a
burst of 1000 (aggregator/data.go:339-353, golang.org/x/time/rate). This is
a vectorized variant: one call admits/charges a whole batch.
"""

from __future__ import annotations

import threading


class TokenBucket:
    def __init__(self, rate_per_s: float, burst: float, now_s: float = 0.0):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._tokens = float(burst)  # guarded-by: self._lock
        self._last = float(now_s)  # guarded-by: self._lock
        self._lock = threading.Lock()

    def admit(self, n: int, now_s: float) -> int:
        """Admit up to n units at time now_s; returns how many were admitted
        (the rest should be dropped, mirroring rate.Limiter.Allow).

        Only whole admitted units are charged — fractional refill carries
        over instead of being burned, so sub-token refills between calls
        still accumulate to the configured rate."""
        with self._lock:
            elapsed = max(0.0, now_s - self._last)
            self._last = now_s
            self._tokens = min(self.burst, self._tokens + elapsed * self.rate)
            take = min(n, int(self._tokens))
            self._tokens -= take
            return take
