"""Token-bucket rate limiting.

The reference rate-limits per-pid event processing at 100 events/s with a
burst of 1000 (aggregator/data.go:339-353, golang.org/x/time/rate). This is
a vectorized variant: one call admits/charges a whole batch.
"""

from __future__ import annotations

import threading

import numpy as np


class TokenBucket:
    def __init__(self, rate_per_s: float, burst: float, now_s: float = 0.0):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._tokens = float(burst)  # guarded-by: self._lock
        self._last = float(now_s)  # guarded-by: self._lock
        self._lock = threading.Lock()

    def admit(self, n: int, now_s: float) -> int:
        """Admit up to n units at time now_s; returns how many were admitted
        (the rest should be dropped, mirroring rate.Limiter.Allow).

        Only whole admitted units are charged — fractional refill carries
        over instead of being burned, so sub-token refills between calls
        still accumulate to the configured rate."""
        with self._lock:
            elapsed = max(0.0, now_s - self._last)
            self._last = now_s
            self._tokens = min(self.burst, self._tokens + elapsed * self.rate)
            take = min(n, int(self._tokens))
            self._tokens -= take
            return take


def admit_batch(buckets: list[TokenBucket], counts, now_s: float) -> np.ndarray:
    """Vectorized ``TokenBucket.admit`` across many buckets: admits
    ``counts[i]`` units on ``buckets[i]`` at ``now_s`` in one array pass.

    Per-bucket results and post-call bucket state are bit-identical to
    calling ``buckets[i].admit(counts[i], now_s)`` one by one — the same
    max/min/int-truncation chain over IEEE doubles, in the same order
    (``int()`` truncates toward zero; tokens are non-negative so
    ``astype(int64)`` matches).

    The per-bucket locks are taken only to snapshot and write back state:
    callers serialize whole admissions themselves (the aggregator holds
    ``_l7_lock`` across the batch), the bucket locks just fence concurrent
    readers like the gc staleness sweep.
    """
    k = len(buckets)
    tokens = np.empty(k, dtype=np.float64)
    last = np.empty(k, dtype=np.float64)
    rate = np.empty(k, dtype=np.float64)
    burst = np.empty(k, dtype=np.float64)
    for i, b in enumerate(buckets):
        with b._lock:
            tokens[i] = b._tokens
            last[i] = b._last
        rate[i] = b.rate
        burst[i] = b.burst
    elapsed = np.maximum(0.0, now_s - last)
    tokens = np.minimum(burst, tokens + elapsed * rate)
    take = np.minimum(
        np.asarray(counts, dtype=np.int64), tokens.astype(np.int64)
    )
    tokens -= take
    for i, b in enumerate(buckets):
        with b._lock:
            b._tokens = float(tokens[i])
            b._last = now_s
    return take
