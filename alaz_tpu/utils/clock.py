"""Clocks.

The reference anchors kernel monotonic time to wall time once at startup
(FirstKernelTime/FirstUserspaceTime, l7.go:327-328,707-710) and converts
with ``convertKernelTimeToUserspaceTime``. We model the same anchor pair,
plus a virtual clock so replay runs are deterministic and faster than real
time.
"""

from __future__ import annotations

import threading
import time


class Clock:
    """Kernel(ns, monotonic) <-> wall(ns, epoch) anchored conversion."""

    def __init__(self, kernel_anchor_ns: int | None = None, wall_anchor_ns: int | None = None):
        self.kernel_anchor_ns = (
            kernel_anchor_ns if kernel_anchor_ns is not None else time.monotonic_ns()
        )
        self.wall_anchor_ns = wall_anchor_ns if wall_anchor_ns is not None else time.time_ns()

    def kernel_to_wall_ns(self, kernel_ns):
        return kernel_ns - self.kernel_anchor_ns + self.wall_anchor_ns

    def wall_to_kernel_ns(self, wall_ns):
        return wall_ns - self.wall_anchor_ns + self.kernel_anchor_ns

    def now_ns(self) -> int:
        return time.time_ns()

    def monotonic_ns(self) -> int:
        return time.monotonic_ns()


class WallClock(Clock):
    pass


class VirtualClock(Clock):
    """Deterministic, manually-advanced clock for replay/tests."""

    def __init__(self, start_ns: int = 1_700_000_000_000_000_000):
        super().__init__(kernel_anchor_ns=0, wall_anchor_ns=start_ns)
        self._now = start_ns
        self._lock = threading.Lock()

    def now_ns(self) -> int:
        return self._now

    def monotonic_ns(self) -> int:
        return self._now - self.wall_anchor_ns

    def advance(self, ns: int) -> int:
        with self._lock:
            self._now += int(ns)
            return self._now
