"""Bounded batch queues — the channel plane.

The reference moves single events through bounded Go channels and prefers
dropping to blocking at the kernel boundary (ebpf/l7_req/l7.go:764-770,
dropped-count logging l7.go:681-687). Here the queue element is a columnar
*batch* and the capacity is counted in **events**, not batches, so config
maps one-to-one to the reference's channel sizes (collector.go:79-81).

``put_nowait_drop`` implements drop-not-block with a running drop counter;
``put`` blocks (used between internal stages where backpressure is safe).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Optional


class QueueClosed(Exception):
    pass


class BatchQueue:
    """Bounded MPMC queue of (batch, aux) items; capacity in events.

    ``ledger``/``drop_cause`` (optional) route mouth drops into the
    unified :class:`~alaz_tpu.utils.ledger.DropLedger` so every lost row
    carries exactly one attribution (ISSUE 6): the queue keeps its local
    ``dropped`` gauge AND reports to the shared ledger."""

    def __init__(
        self,
        capacity_events: int,
        name: str = "queue",
        ledger=None,
        drop_cause: str = "dropped",
    ):
        self.name = name
        self.capacity = int(capacity_events)
        self._ledger = ledger
        self._drop_cause = drop_cause
        self._items: collections.deque = collections.deque()  # guarded-by: self._lock
        self._events = 0  # guarded-by: self._lock
        self._dropped = 0  # guarded-by: self._lock
        self._put_total = 0  # guarded-by: self._lock
        self._unfinished = 0  # enqueued batches not yet task_done()'d  # guarded-by: self._lock
        self._closed = False  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def pending_events(self) -> int:
        return self._events  # alazlint: disable=ALZ010 -- racy int read is a metrics gauge; GIL-atomic, momentarily stale at worst

    @property
    def dropped(self) -> int:
        """Total events dropped at the mouth of the queue (l7.go:764-770)."""
        return self._dropped  # alazlint: disable=ALZ010 -- racy gauge read, see pending_events

    @property
    def put_total(self) -> int:
        return self._put_total  # alazlint: disable=ALZ010 -- racy gauge read, see pending_events

    def _size_of(self, batch: Any) -> int:
        try:
            return len(batch)
        except TypeError:
            return 1

    def put_nowait_drop(self, batch: Any) -> bool:
        """Enqueue unless full; on full, count the events as dropped and
        return False. Never blocks — the kernel-boundary contract."""
        n = self._size_of(batch)
        with self._lock:
            if self._closed:
                raise QueueClosed(self.name)
            if self._events + n > self.capacity:
                self._dropped += n
                if self._ledger is not None:
                    # ledger.add is lock-leaf: the queue→ledger edge has
                    # no reverse path (alazsan DAG)
                    self._ledger.add(self._drop_cause, n, reason=self.name)
                return False
            self._items.append(batch)
            self._events += n
            self._put_total += n
            self._unfinished += 1
            self._not_empty.notify()
            return True

    def put(self, batch: Any, timeout: Optional[float] = None) -> bool:
        """Blocking enqueue for interior stages. ``timeout`` is a real
        DEADLINE, not a per-wakeup budget: under producer contention a
        loser's wait used to restart at the full timeout every time a
        competitor stole the freed capacity, making the shed bound
        (sharded _put_or_shed) no bound at all."""
        n = self._size_of(batch)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._not_full:
            while not self._closed and self._events + n > self.capacity and self._events > 0:
                if deadline is None:
                    self._not_full.wait()  # alazlint: disable=ALZ042 -- the timeout=None branch is the caller's explicit opt-in to block (interior stages where backpressure is safe); every ingest/flush/close-reachable call site passes a deadline, which ALZ042 checks AT those sites
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._not_full.wait(remaining)
            if self._closed:
                raise QueueClosed(self.name)
            self._items.append(batch)
            self._events += n
            self._put_total += n
            self._unfinished += 1
            self._not_empty.notify()
            return True

    def get(self, timeout: Optional[float] = None) -> Any:
        """Blocking dequeue; returns None on timeout or when closed+drained."""
        with self._not_empty:
            while not self._items:
                if self._closed:
                    return None
                if not self._not_empty.wait(timeout):
                    return None
            batch = self._items.popleft()
            self._events -= self._size_of(batch)
            self._not_full.notify()
            return batch

    def task_done(self) -> None:
        """Mark one previously-gotten batch as fully processed. A consumer
        that calls this after each ``get`` lets ``unfinished`` distinguish
        "queue empty" from "queue empty but a worker is mid-batch"."""
        with self._lock:
            if self._unfinished > 0:
                self._unfinished -= 1

    @property
    def unfinished(self) -> int:
        """Batches enqueued but not yet marked done (includes in-flight)."""
        return self._unfinished  # alazlint: disable=ALZ010 -- racy gauge read; drain() polls it in a timeout loop, see pending_events

    def drain(self) -> list:
        """Grab everything currently queued (for batch-oriented consumers)."""
        with self._lock:
            items = list(self._items)
            self._items.clear()
            self._events = 0
            self._unfinished -= len(items)
            self._not_full.notify_all()
            return items

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed  # alazlint: disable=ALZ010 -- monotonic latch: False→True once, a stale False only delays the reader one poll

    def stats(self) -> dict:
        """Lag/drop gauges, the data.go:177-186 channel-lag log analog."""
        with self._lock:
            return {
                "name": self.name,
                "pending_events": self._events,
                "pending_batches": len(self._items),
                "capacity": self.capacity,
                "dropped": self._dropped,
                "put_total": self._put_total,
            }
