"""TPU environment metrics — the NVML collector analog for the legs the
chip runtime CAN expose (gpu/collector.go:95-182 exports power, clocks,
fan, temperature via NVML; TPUs have no NVML, but libtpu ships a runtime
metric service on localhost:8431 — the surface `tpu-info` scrapes).

A gRPC unary client (repo HTTP/2 + HPACK stack, sources/cri.py) calls
``tpu.monitoring.runtime.RuntimeMetricService/GetRuntimeMetric`` per
metric name and walks the protobuf response generically: each returned
measurement is (attributes, gauge value); the ``device-id`` attribute
fans the gauge out per chip. Default metric set covers tensorcore duty
cycle and HBM usage/total (the documented names); extra names —
temperature/power on platforms whose libtpu exposes them — ride
``ALAZ_TPU_ENV_METRICS=name[,name...]`` and export under sanitized
gauge names, so new libtpu surfaces need zero code here.

Wire shapes follow tpu_metric_service.proto as implemented by the
public tpu-info tool: MetricRequest{metric_name=1};
MetricResponse{metric=1 TPUMetric{name=1, metrics=2 repeated
Metric{attribute=1 Attribute{key=1, value=2 AttrValue{int_attr=1,
str_attr=2}}, gauge=2 Gauge{as_double=1, as_int=2}}}}. The parser is
deliberately permissive (unknown fields skipped) so minor proto
revisions degrade to missing gauges, not crashes.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Dict, List, Optional, Tuple

from alaz_tpu.logging import get_logger
from alaz_tpu.sources.cri import GrpcError, GrpcTcpClient, pb_fields, pb_len, pb_str

log = get_logger("alaz_tpu.tpu_env")

DEFAULT_ADDR = "localhost:8431"
SERVICE = "/tpu.monitoring.runtime.RuntimeMetricService/GetRuntimeMetric"

METRIC_DUTY_CYCLE = "tpu.runtime.tensorcore.dutycycle.percent"
METRIC_HBM_USED = "tpu.runtime.hbm.memory.usage.bytes"
METRIC_HBM_TOTAL = "tpu.runtime.hbm.memory.total.bytes"

DEFAULT_METRICS = (METRIC_DUTY_CYCLE, METRIC_HBM_USED, METRIC_HBM_TOTAL)

# metric name -> short gauge suffix for the default set; extras sanitize
_GAUGE_NAMES = {
    METRIC_DUTY_CYCLE: "tensorcore_duty_cycle_pct",
    METRIC_HBM_USED: "runtime_hbm_used_bytes",
    METRIC_HBM_TOTAL: "runtime_hbm_total_bytes",
}


def gauge_suffix(metric_name: str) -> str:
    """tpu.runtime.env.temperature.celsius → env_temperature_celsius."""
    if metric_name in _GAUGE_NAMES:
        return _GAUGE_NAMES[metric_name]
    s = metric_name
    if s.startswith("tpu.runtime."):
        s = s[len("tpu.runtime."):]
    return s.replace(".", "_").replace("-", "_")


def _parse_attr(data: bytes) -> Tuple[str, Optional[object]]:
    """Attribute{key=1 str, value=2 AttrValue{int_attr=1, str_attr=2}}."""
    key, value = "", None
    for f, wt, v in pb_fields(data):
        if f == 1 and wt == 2:
            key = bytes(v).decode("utf-8", "replace")
        elif f == 2 and wt == 2:
            for f2, wt2, v2 in pb_fields(bytes(v)):
                if f2 == 1 and wt2 == 0:
                    value = int(v2)
                elif f2 == 2 and wt2 == 2:
                    value = bytes(v2).decode("utf-8", "replace")
    return key, value


def _parse_gauge(data: bytes) -> Optional[float]:
    """Gauge{as_double=1 (fixed64), as_int=2 (varint)}."""
    for f, wt, v in pb_fields(data):
        if f == 1 and wt == 1:
            return struct.unpack("<d", int(v).to_bytes(8, "little"))[0]
        if f == 2 and wt == 0:
            return float(int(v))
    return None


def parse_metric_response(body: bytes) -> List[Tuple[Dict[str, object], float]]:
    """MetricResponse → [(attributes, value)] measurement records."""
    records: List[Tuple[Dict[str, object], float]] = []
    for f, wt, v in pb_fields(body):
        if f != 1 or wt != 2:
            continue
        for f2, wt2, v2 in pb_fields(bytes(v)):  # TPUMetric
            if f2 != 2 or wt2 != 2:
                continue
            attrs: Dict[str, object] = {}
            value: Optional[float] = None
            for f3, wt3, v3 in pb_fields(bytes(v2)):  # Metric
                if f3 == 1 and wt3 == 2:
                    k, av = _parse_attr(bytes(v3))
                    if k:
                        attrs[k] = av
                elif f3 == 2 and wt3 == 2:
                    value = _parse_gauge(bytes(v3))
            if value is not None:
                records.append((attrs, value))
    return records


def build_metric_request(metric_name: str) -> bytes:
    return pb_str(1, metric_name)


class TpuEnvCollector:
    """Samples the libtpu metric service, caching one sweep per
    ``min_interval_s`` so a Prometheus scrape of N gauges costs one RPC
    round, not N (the NVML collector batches the same way)."""

    def __init__(
        self,
        addr: str | None = None,
        metric_names: tuple | None = None,
        timeout_s: float = 2.0,
        min_interval_s: float = 5.0,
    ):
        addr = addr or os.environ.get("ALAZ_TPU_ENV_ADDR", DEFAULT_ADDR)
        host, _, port_s = addr.rpartition(":")
        self.host, self.port = host or "localhost", int(port_s)
        extra = [
            m.strip()
            for m in os.environ.get("ALAZ_TPU_ENV_METRICS", "").split(",")
            if m.strip()
        ]
        self.metric_names = tuple(metric_names or DEFAULT_METRICS) + tuple(extra)
        self.timeout_s = timeout_s
        self.min_interval_s = min_interval_s
        self._cache: Dict[str, Dict[int, float]] = {}
        self._last_sweep = 0.0

    def sample(self) -> Dict[str, Dict[int, float]]:
        """{metric_name: {device_id: value}} for every configured metric
        the service answers; one fresh connection per sweep (the service
        restarts with the runtime — a pooled conn would go stale)."""
        out: Dict[str, Dict[int, float]] = {}
        client = GrpcTcpClient(self.host, self.port, timeout_s=self.timeout_s)
        try:
            for name in self.metric_names:
                try:
                    body = client.call(SERVICE, build_metric_request(name))
                except GrpcError as exc:
                    log.debug(f"metric {name}: {exc}")
                    continue
                per_dev: Dict[int, float] = {}
                for idx, (attrs, value) in enumerate(parse_metric_response(body)):
                    dev = attrs.get("device-id", attrs.get("device_id"))
                    try:
                        key = int(str(dev))
                    except ValueError:
                        # non-numeric ("pci:0000:05") or MISSING id: fall
                        # back to a NEGATIVE enumeration key — distinct
                        # per record but outside the real device-id
                        # range, so it can never clobber a parsed id in
                        # the same response (a missing id maps through
                        # int(str(None)) → ValueError → here)
                        key = -(idx + 1)
                    per_dev[key] = value
                if per_dev:
                    out[name] = per_dev
        finally:
            client.close()
        return out

    def _sweep_cached(self) -> Dict[str, Dict[int, float]]:
        now = time.monotonic()
        if now - self._last_sweep >= self.min_interval_s:
            self._last_sweep = now
            try:
                self._cache = self.sample()
            except (OSError, GrpcError) as exc:
                log.debug(f"tpu env sweep failed: {exc}")
                self._cache = {}
        return self._cache

    def register(self, metrics) -> bool:
        """Probe once; when the service answers, register one gauge per
        (metric, device) seen. Returns False (and registers nothing) when
        the service is absent — CPU hosts, tests."""
        try:
            first = self.sample()
        except (OSError, GrpcError) as exc:
            log.debug(f"tpu env metric service unavailable: {exc}")
            return False
        if not first:
            return False
        self._cache, self._last_sweep = first, time.monotonic()
        for name, per_dev in first.items():
            for dev in per_dev:
                def fn(n=name, d=dev):
                    return self._sweep_cached().get(n, {}).get(d, float("nan"))

                metrics.gauge(f"device{dev}.{gauge_suffix(name)}", fn)
        log.info(
            f"tpu env gauges: {len(first)} metrics x "
            f"{max(len(v) for v in first.values())} devices from "
            f"{self.host}:{self.port}"
        )
        return True
