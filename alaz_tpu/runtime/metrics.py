"""Per-stage counters and gauges.

The reference logs channel lag every 2 minutes (data.go:177-186), keeps
dropped-event counters (l7.go:681-687), and exports node metrics through
an embedded Prometheus exporter (backend.go:1038-1105). This registry is
the analog: counters/gauges with a Prometheus-text rendering and a
snapshot dict for the health/metrics push path.
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
from typing import Callable, Dict

from alaz_tpu.obs.histogram import Histogram


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    __slots__ = ("name", "_fn", "_value", "_on_error")

    def __init__(self, name: str, fn: Callable[[], float] | None = None):
        self.name = name
        self._fn = fn
        self._value = 0.0
        # wired by the registry: a raising callback used to render `nan`
        # into the Prometheus text SILENTLY — now every failed read bumps
        # metrics.gauge_errors and the exposition skips the NaN sample
        # (ISSUE 9 satellite; scrapers reject NaN-bearing series anyway)
        self._on_error: Callable[[], None] | None = None

    def set(self, v: float) -> None:
        self._value = float(v)

    def _count_error(self) -> None:
        if self._on_error is not None:
            try:
                self._on_error()
            except Exception:
                pass

    @property
    def value(self) -> float:
        # NaN is an error signal however it arrives — a raising
        # callback, a callback computing 0/0, or a direct set(nan) —
        # and every read of one bumps metrics.gauge_errors, so the
        # sample's disappearance from snapshot/exposition is never silent
        if self._fn is not None:
            try:
                v = float(self._fn())
            except Exception:
                self._count_error()
                return float("nan")
        else:
            v = self._value
        if math.isnan(v):
            self._count_error()
        return v


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, Counter] = {}  # guarded-by: self._lock
        self._gauges: Dict[str, Gauge] = {}  # guarded-by: self._lock
        self._infos: Dict[str, Dict[str, str]] = {}
        self._histograms: Dict[str, Histogram] = {}  # guarded-by: self._lock
        # sparse histograms (ISSUE 11, the per-bucket labeled series):
        # registered lazily per shape bucket, OMITTED from snapshot and
        # exposition while their count is zero — the same discipline the
        # gauge-error path applies to NaN samples: a series that has
        # nothing to say is absent, never an empty/nan render
        self._sparse: set = set()  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.started_at = time.time()
        # registered through the public surface so the golden registry
        # carries the name like any other metric (ALZ044's scanner
        # recognizes self-registrations inside this class)
        self._gauge_errors = self.counter("metrics.gauge_errors")

    def info(self, name: str, **labels: str) -> None:
        """Static labeled info metric (the gpu_info/gpu_driver pattern,
        gpu/collector.go:95-100: a gauge fixed at 1 carrying labels)."""
        with self._lock:
            self._infos[name] = dict(labels)

    def infos(self) -> Dict[str, Dict[str, str]]:
        with self._lock:
            return {k: dict(v) for k, v in self._infos.items()}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = Counter(name)
                self._counters[name] = c
            return c

    def gauge(self, name: str, fn: Callable[[], float] | None = None) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = Gauge(name, fn)
                g._on_error = self._gauge_errors.inc
                self._gauges[name] = g
            elif fn is not None:
                g._fn = fn
            return g

    def histogram(self, name: str, sparse: bool = False, bounds=None) -> Histogram:
        """Lock-striped log-bucket latency histogram (obs/histogram.py):
        p50/p95/p99 land in the snapshot, the full cumulative-bucket
        exposition in the Prometheus text. ``sparse=True`` (the
        per-bucket labeled series — ``latency.score_s.<bucket>``,
        ``device.occupancy.<bucket>``) omits the series everywhere while
        it has zero observations; fixed-name histograms stay rendered so
        dashboards can key on their presence. ``bounds`` overrides the
        geometric latency ladder at FIRST registration (linear ratios
        like occupancy misread on a 2x ladder); later lookups return the
        existing instance unchanged."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = Histogram(name, bounds=bounds)
                self._histograms[name] = h
            if sparse:
                self._sparse.add(name)
            return h

    def histograms(self, include_empty_sparse: bool = True) -> Dict[str, Histogram]:
        with self._lock:
            out = dict(self._histograms)
            sparse = set(self._sparse)
        if not include_empty_sparse:
            # total_count takes the stripe locks — outside the registry lock
            for n in sparse:
                if n in out and out[n].total_count == 0:
                    del out[n]
        return out

    def snapshot(self, histograms: bool = True) -> dict:
        with self._lock:
            out = {n: c.value for n, c in self._counters.items()}
            for n, g in self._gauges.items():
                v = g.value
                if isinstance(v, float) and math.isnan(v):
                    # already counted into metrics.gauge_errors by the
                    # value read (raising OR NaN-computing callbacks,
                    # and set(nan)): skip the sample — a bare NaN token
                    # in the health-push JSON would make a strict RFC
                    # 8259 consumer reject the whole payload
                    continue
                out[n] = v
            hists = list(self._histograms.items()) if histograms else ()
            sparse = set(self._sparse)
            out["uptime_s"] = time.time() - self.started_at
        # histogram percentile walks happen outside the registry lock
        # (they take the stripe locks; the registry lock stays cheap)
        for n, h in hists:
            snap = h.snapshot()
            if snap["count"] == 0 and n in sparse:
                # empty per-bucket series: absent, not zero-rendered
                continue
            out[f"{n}.count"] = snap["count"]
            out[f"{n}.p50"] = snap["p50"]
            out[f"{n}.p95"] = snap["p95"]
            out[f"{n}.p99"] = snap["p99"]
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (the :8182/inner/metrics analog).
        Histograms render as real histogram series (cumulative buckets +
        sum + count); NaN gauge samples are SKIPPED, not emitted — a
        raising gauge callback already counted into metrics.gauge_errors
        when its value was read."""
        lines = []
        # snapshot() already skips NaN gauge samples (shared with the
        # health-push JSON path, which must stay strict-RFC-parseable)
        for name, value in sorted(self.snapshot(histograms=False).items()):
            metric = "alaz_tpu_" + name.replace(".", "_").replace("-", "_")
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {value}")
        # empty sparse (per-bucket) series stay out of the scrape — the
        # fixed-name histograms render even at zero so dashboards can
        # key on their presence
        for name, h in sorted(self.histograms(include_empty_sparse=False).items()):
            metric = "alaz_tpu_" + name.replace(".", "_").replace("-", "_")
            lines.extend(h.render_prometheus(metric))
        def esc(v) -> str:
            # exposition format: backslash, double-quote and newline must
            # be escaped inside label values or the scrape line is invalid
            return (
                str(v)
                .replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        for name, labels in sorted(self.infos().items()):
            metric = "alaz_tpu_" + name.replace(".", "_").replace("-", "_")
            label_str = ",".join(
                f'{k}="{esc(v)}"' for k, v in sorted(labels.items())
            )
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric}{{{label_str}}} 1")
        return "\n".join(lines) + "\n"


def ledger_gauges(metrics: Metrics, ledger) -> None:
    """Surface the unified drop ledger (utils/ledger.py, ISSUE 6):
    one gauge per loss cause plus the grand total, so a degraded-mode
    incident reads as WHICH failure mode is eating rows (queue drops vs
    lateness vs quarantined frames vs deliberate shedding) instead of a
    single opaque drop counter."""
    for cause in ledger.CAUSES:
        metrics.gauge(f"ledger.{cause}", lambda c=cause: ledger.count(c))
    metrics.gauge("ledger.total", lambda: ledger.total)


def host_gauges(metrics: Metrics) -> None:
    """Node metrics — the embedded node_exporter scrape analog
    (backend.go:1038-1105): process, memory, load, cpu, network, disk and
    fd gauges from /proc, pushed to the backend via the metrics-scrape
    leg and with the health payload."""

    def rss_bytes() -> float:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return float(line.split()[1]) * 1024
        except OSError:
            pass
        return 0.0

    def meminfo(field: str) -> float:
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith(field + ":"):
                        return float(line.split()[1]) * 1024
        except OSError:
            pass
        return 0.0

    def loadavg(idx: int) -> float:
        try:
            return float(open("/proc/loadavg").read().split()[idx])
        except OSError:
            return 0.0

    def stat_field(prefix: str, idx: int, scale: float = 1.0) -> float:
        """One numeric column of a /proc/stat line (cpu jiffies → seconds
        via USER_HZ=100, the node_exporter cpu collector fields)."""
        try:
            with open("/proc/stat") as f:
                for line in f:
                    if line.startswith(prefix + " ") or line.startswith(prefix + "  "):
                        return float(line.split()[idx]) * scale
        except OSError:
            pass
        return 0.0

    def net_bytes(col: int) -> float:
        """Sum of rx (col 1) / tx (col 9) bytes over non-loopback
        interfaces (/proc/net/dev; the netdev collector)."""
        total = 0.0
        try:
            with open("/proc/net/dev") as f:
                for line in f.readlines()[2:]:
                    name, _, rest = line.partition(":")
                    if name.strip() == "lo":
                        continue
                    cols = rest.split()
                    if len(cols) > col:
                        total += float(cols[col])
        except OSError:
            return 0.0
        return total

    def disk(field: str) -> float:
        try:
            st = os.statvfs("/")
        except OSError:
            return 0.0
        if field == "total":
            return float(st.f_blocks * st.f_frsize)
        return float((st.f_blocks - st.f_bfree) * st.f_frsize)

    def open_fds() -> float:
        try:
            return float(len(os.listdir("/proc/self/fd")))
        except OSError:
            return 0.0

    def boot_uptime() -> float:
        try:
            return float(open("/proc/uptime").read().split()[0])
        except OSError:
            return 0.0

    def vmstat(field: str) -> float:
        """/proc/vmstat counters (the node_exporter vmstat collector)."""
        try:
            with open("/proc/vmstat") as f:
                for line in f:
                    if line.startswith(field + " "):
                        return float(line.split()[1])
        except OSError:
            pass
        return 0.0

    # partitions, not whole devices: sda1 / vdb2 / nvme0n1p3 / mmcblk0p1.
    # A bare trailing-digit check would also drop whole NVMe/eMMC devices
    # (nvme0n1, mmcblk0) — the common case on modern nodes.
    part_re = re.compile(
        r"^(?:(?:h|s|v|xv)d[a-z]+\d+|nvme\d+n\d+p\d+|mmcblk\d+p\d+)$"
    )

    def diskstats(col: int) -> float:
        """Sum of one /proc/diskstats column over whole devices (the
        diskstats collector). col 3=reads, 7=writes, 5/9=sectors,
        12=io_time_ms."""
        total = 0.0
        try:
            with open("/proc/diskstats") as f:
                for line in f:
                    cols = line.split()
                    if len(cols) <= col:
                        continue
                    name = cols[2]
                    if name.startswith(("loop", "ram", "dm-", "sr", "fd")):
                        continue
                    if part_re.match(name):
                        continue
                    total += float(cols[col])
        except OSError:
            return 0.0
        return total

    def sockstat(proto: str, field: str) -> float:
        """/proc/net/sockstat (the sockstat collector): TCP inuse/orphan/
        tw, UDP inuse — the reference joins on live socket state, so
        kernel socket-table pressure is first-order here."""
        try:
            with open("/proc/net/sockstat") as f:
                for line in f:
                    if line.startswith(proto + ":"):
                        parts = line.split()
                        for i, tok in enumerate(parts):
                            if tok == field and i + 1 < len(parts):
                                return float(parts[i + 1])
        except OSError:
            pass
        return 0.0

    def file_nr(idx: int) -> float:
        """/proc/sys/fs/file-nr: allocated (0) and max (2) file handles
        system-wide (the filefd collector)."""
        try:
            return float(open("/proc/sys/fs/file-nr").read().split()[idx])
        except OSError:
            return 0.0

    def psi(resource: str) -> float:
        """PSI avg10 'some' pressure percentage (the pressure
        collector); 0 where the kernel lacks CONFIG_PSI."""
        try:
            with open(f"/proc/pressure/{resource}") as f:
                for line in f:
                    if line.startswith("some"):
                        for tok in line.split():
                            if tok.startswith("avg10="):
                                return float(tok[6:])
        except OSError:
            pass
        return 0.0

    metrics.gauge("host.process_rss_bytes", rss_bytes)
    metrics.gauge("host.mem_available_bytes", lambda: meminfo("MemAvailable"))
    metrics.gauge("host.mem_total_bytes", lambda: meminfo("MemTotal"))
    metrics.gauge("host.mem_cached_bytes", lambda: meminfo("Cached"))
    metrics.gauge("host.mem_buffers_bytes", lambda: meminfo("Buffers"))
    metrics.gauge("host.swap_total_bytes", lambda: meminfo("SwapTotal"))
    metrics.gauge("host.swap_free_bytes", lambda: meminfo("SwapFree"))
    metrics.gauge("host.load1", lambda: loadavg(0))
    metrics.gauge("host.load5", lambda: loadavg(1))
    metrics.gauge("host.load15", lambda: loadavg(2))
    metrics.gauge("host.cpu_user_s", lambda: stat_field("cpu", 1, 0.01))
    metrics.gauge("host.cpu_system_s", lambda: stat_field("cpu", 3, 0.01))
    metrics.gauge("host.cpu_idle_s", lambda: stat_field("cpu", 4, 0.01))
    metrics.gauge("host.cpu_iowait_s", lambda: stat_field("cpu", 5, 0.01))
    metrics.gauge("host.cpu_steal_s", lambda: stat_field("cpu", 8, 0.01))
    metrics.gauge("host.context_switches", lambda: stat_field("ctxt", 1))
    metrics.gauge("host.forks_total", lambda: stat_field("processes", 1))
    metrics.gauge("host.procs_running", lambda: stat_field("procs_running", 1))
    metrics.gauge("host.procs_blocked", lambda: stat_field("procs_blocked", 1))
    metrics.gauge("host.net_rx_bytes", lambda: net_bytes(0))
    metrics.gauge("host.net_tx_bytes", lambda: net_bytes(8))
    metrics.gauge("host.net_rx_errors", lambda: net_bytes(2))
    metrics.gauge("host.net_rx_dropped", lambda: net_bytes(3))
    metrics.gauge("host.net_tx_errors", lambda: net_bytes(10))
    metrics.gauge("host.net_tx_dropped", lambda: net_bytes(11))
    metrics.gauge("host.disk_used_bytes", lambda: disk("used"))
    metrics.gauge("host.disk_total_bytes", lambda: disk("total"))
    metrics.gauge("host.disk_reads_completed", lambda: diskstats(3))
    metrics.gauge("host.disk_writes_completed", lambda: diskstats(7))
    metrics.gauge("host.disk_read_sectors", lambda: diskstats(5))
    metrics.gauge("host.disk_written_sectors", lambda: diskstats(9))
    metrics.gauge("host.disk_io_time_ms", lambda: diskstats(12))
    metrics.gauge("host.pgfault", lambda: vmstat("pgfault"))
    metrics.gauge("host.pgmajfault", lambda: vmstat("pgmajfault"))
    metrics.gauge("host.sockets_tcp_inuse", lambda: sockstat("TCP", "inuse"))
    metrics.gauge("host.sockets_tcp_orphan", lambda: sockstat("TCP", "orphan"))
    metrics.gauge("host.sockets_tcp_tw", lambda: sockstat("TCP", "tw"))
    metrics.gauge("host.sockets_udp_inuse", lambda: sockstat("UDP", "inuse"))
    metrics.gauge("host.filefd_allocated", lambda: file_nr(0))
    metrics.gauge("host.filefd_maximum", lambda: file_nr(2))
    metrics.gauge("host.pressure_cpu_avg10", lambda: psi("cpu"))
    metrics.gauge("host.pressure_memory_avg10", lambda: psi("memory"))
    metrics.gauge("host.pressure_io_avg10", lambda: psi("io"))
    metrics.gauge("host.open_fds", open_fds)
    metrics.gauge("host.boot_uptime_s", boot_uptime)


# memory_stats keys exported per device when the runtime provides them —
# the TPU-side analog of the NVML total/used/free/bar1 memory gauges
_DEVICE_MEM_KEYS = (
    ("bytes_in_use", "hbm_bytes_in_use"),
    ("peak_bytes_in_use", "hbm_peak_bytes_in_use"),
    ("bytes_limit", "hbm_bytes_limit"),
    ("bytes_reservable_limit", "hbm_bytes_reservable_limit"),
    ("largest_free_block_bytes", "hbm_largest_free_block_bytes"),
    ("largest_alloc_size", "hbm_largest_alloc_bytes"),
    ("num_allocs", "num_allocs"),
    ("pool_bytes", "pool_bytes"),
)


def device_gauges(metrics: Metrics) -> None:
    """Accelerator gauges (the gpu/ NVML collector analog, SURVEY §2.2
    G22, ~19 gauges): per-device memory-stat gauges, an HBM-utilization
    percentage (the mem_utz analog), device identity info (the
    gpu_info/gpu_driver analog), and — where the host's libtpu runtime
    metric service answers — environment legs (tensorcore duty cycle,
    runtime HBM, temperature/power on platforms that expose them) via
    runtime/tpu_env.py, completing the power/clock/temperature side of
    the NVML analog. The in-process compute-side fallback is the scorer
    duty-cycle gauge the service registers."""
    try:
        from alaz_tpu.runtime.tpu_env import TpuEnvCollector

        TpuEnvCollector().register(metrics)
    except Exception:  # no libtpu metric service on this host
        pass
    try:
        import jax

        for i, dev in enumerate(jax.local_devices()):
            for stat_key, gauge_name in _DEVICE_MEM_KEYS:
                def mem_fn(d=dev, k=stat_key):
                    stats = d.memory_stats() or {}
                    return stats.get(k, 0)

                metrics.gauge(f"device{i}.{gauge_name}", mem_fn)

            def utz_fn(d=dev):
                stats = d.memory_stats() or {}
                limit = stats.get("bytes_limit", 0)
                return 100.0 * stats.get("bytes_in_use", 0) / limit if limit else 0.0

            metrics.gauge(f"device{i}.hbm_utilization_pct", utz_fn)
            metrics.info(
                f"device{i}.info",
                kind=getattr(dev, "device_kind", "unknown"),
                platform=getattr(dev, "platform", "unknown"),
                id=str(getattr(dev, "id", i)),
            )
        metrics.gauge("device.count", lambda: len(jax.local_devices()))
        metrics.info(
            "device.runtime",
            backend=jax.default_backend(),
            jax_version=jax.__version__,
        )
    except Exception:  # no accelerator runtime present
        pass
