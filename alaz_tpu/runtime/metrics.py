"""Per-stage counters and gauges.

The reference logs channel lag every 2 minutes (data.go:177-186), keeps
dropped-event counters (l7.go:681-687), and exports node metrics through
an embedded Prometheus exporter (backend.go:1038-1105). This registry is
the analog: counters/gauges with a Prometheus-text rendering and a
snapshot dict for the health/metrics push path.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    __slots__ = ("name", "_fn", "_value")

    def __init__(self, name: str, fn: Callable[[], float] | None = None):
        self.name = name
        self._fn = fn
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        return self._value


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._lock = threading.Lock()
        self.started_at = time.time()

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = Counter(name)
                self._counters[name] = c
            return c

    def gauge(self, name: str, fn: Callable[[], float] | None = None) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = Gauge(name, fn)
                self._gauges[name] = g
            elif fn is not None:
                g._fn = fn
            return g

    def snapshot(self) -> dict:
        with self._lock:
            out = {n: c.value for n, c in self._counters.items()}
            out.update({n: g.value for n, g in self._gauges.items()})
            out["uptime_s"] = time.time() - self.started_at
            return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (the :8182/inner/metrics analog)."""
        lines = []
        for name, value in sorted(self.snapshot().items()):
            metric = "alaz_tpu_" + name.replace(".", "_").replace("-", "_")
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {value}")
        return "\n".join(lines) + "\n"


def host_gauges(metrics: Metrics) -> None:
    """Node metrics (the embedded node_exporter scrape analog,
    backend.go:1038-1105): process RSS, host memory, load average from
    /proc — pushed with the health payload like the reference pushes its
    scrape."""

    def rss_bytes() -> float:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return float(line.split()[1]) * 1024
        except OSError:
            pass
        return 0.0

    def meminfo(field: str) -> float:
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith(field + ":"):
                        return float(line.split()[1]) * 1024
        except OSError:
            pass
        return 0.0

    def load1() -> float:
        try:
            return float(open("/proc/loadavg").read().split()[0])
        except OSError:
            return 0.0

    metrics.gauge("host.process_rss_bytes", rss_bytes)
    metrics.gauge("host.mem_available_bytes", lambda: meminfo("MemAvailable"))
    metrics.gauge("host.load1", load1)


def device_gauges(metrics: Metrics) -> None:
    """Register accelerator gauges (the gpu/ NVML collector analog,
    SURVEY §2.2 G22): per-device HBM usage from the JAX runtime."""
    try:
        import jax

        for i, dev in enumerate(jax.local_devices()):
            def mem_fn(d=dev):
                stats = d.memory_stats() or {}
                return stats.get("bytes_in_use", 0)

            metrics.gauge(f"device{i}.hbm_bytes_in_use", mem_fn)
        metrics.gauge("device.count", lambda: len(jax.local_devices()))
    except Exception:  # no accelerator runtime present
        pass
