"""Health-check loop with backend-commanded stop/resume.

The reference PUTs a healthcheck every 10s and obeys a "payment required"
protocol: on HTTP 402 the agent stops its collectors, and resumes when the
backend starts answering 200 again (backend.go:950-1036, main.go:149-187).
Here the commands pause/resume the scoring service via callbacks; the
transport is the same pluggable callable the datastore uses.
"""

from __future__ import annotations

import enum
import threading
from typing import Callable, Optional

from alaz_tpu.datastore.backend import Transport
from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.health")

EP_HEALTHCHECK = "/healthcheck/"


class HealthState(str, enum.Enum):
    RUNNING = "running"
    STOPPED = "stopped"  # backend-commanded (the payment-required state)


class HealthChecker:
    def __init__(
        self,
        transport: Transport,
        interval_s: float = 10.0,
        on_stop: Optional[Callable[[], None]] = None,
        on_resume: Optional[Callable[[], None]] = None,
        metrics_snapshot: Optional[Callable[[], dict]] = None,
        degraded_snapshot: Optional[Callable[[], dict]] = None,
    ):
        self.transport = transport
        self.interval_s = interval_s
        self.on_stop = on_stop
        self.on_resume = on_resume
        self.metrics_snapshot = metrics_snapshot
        # self-healing visibility (ISSUE 6): drop-ledger breakdown,
        # worker restarts, breaker state, last-wave age — wire it to
        # Service.degraded_snapshot so a stalled merge thread or an open
        # circuit shows up in every health PUT instead of staying silent
        self.degraded_snapshot = degraded_snapshot
        self.state = HealthState.RUNNING
        self.checks = 0
        self.failures = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check_once(self) -> HealthState:
        payload = {"state": self.state.value}
        if self.metrics_snapshot is not None:
            payload["metrics"] = self.metrics_snapshot()
        if self.degraded_snapshot is not None:
            try:
                payload["degraded"] = self.degraded_snapshot()
            except Exception as exc:
                log.warning(f"degraded snapshot failed: {exc}")
        try:
            status = self.transport(EP_HEALTHCHECK, payload)
        except Exception as exc:
            log.warning(f"healthcheck transport error: {exc}")
            self.failures += 1
            return self.state
        self.checks += 1
        if status == 402 and self.state == HealthState.RUNNING:
            # payment-required: stop collectors until told otherwise
            log.warning("healthcheck: backend commanded STOP (402)")
            self.state = HealthState.STOPPED
            if self.on_stop is not None:
                self.on_stop()
        elif status < 400 and self.state == HealthState.STOPPED:
            log.warning("healthcheck: backend resumed (2xx), restarting")
            self.state = HealthState.RUNNING
            if self.on_resume is not None:
                self.on_resume()
        return self.state

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(self.interval_s):
                self.check_once()

        self._thread = threading.Thread(target=run, name="alaz-health", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
