"""Runtime wiring: the main.go analog.

``Service`` assembles the full streaming pipeline — event queues →
aggregator workers → windowed graph store → GNN scorer → score sink —
with health checking (stop/resume protocol), per-stage metrics, and
graceful shutdown.
"""

from alaz_tpu.runtime.metrics import Metrics, Counter, Gauge
from alaz_tpu.runtime.health import HealthChecker, HealthState
from alaz_tpu.runtime.service import Service, ScoreBatch, ScoreRecord

__all__ = [
    "Metrics",
    "Counter",
    "Gauge",
    "HealthChecker",
    "HealthState",
    "Service",
    "ScoreRecord",
    "ScoreBatch",
]
