"""Per-tenant host-plane partitions (ISSUE 14 tentpole).

The production shape of "heavy traffic from millions of users" is
hundreds of agent fleets multiplexed onto ONE scoring backend. The unit
of isolation is the :class:`TenantPartition`: everything a tenant's rows
touch between the ingest socket and the shared window queue —

- an **Interner namespace** of its own (tenant A's pod uids can never
  collide with, or leak into, tenant B's node table),
- a **DropLedger** of its own, so ``pushed == emitted + ledger.total``
  is a PER-TENANT conservation invariant (the isolation gate's exact
  equation; one shared ledger would let tenant A's sheds hide tenant
  B's losses),
- its own bounded **source queues** (l7/tcp/proc/k8s) — one tenant's
  backlog fills its own queues and sheds its own rows; it cannot
  head-of-line block another fleet's stream,
- its own **windowed pipeline** with private watermarks: the serial
  ``Aggregator`` + ``WindowedGraphStore`` pair, or a full
  ``ShardedIngest`` pool per tenant when ``ingest_workers > 1`` — a
  malformed stream or hot key perturbs only its own windows,
- its own **SpanTracer**: spans are keyed by window_start_ms, and two
  tenants legitimately close the same wall-clock window — per-tenant
  tracers keep their lifecycles apart while the stage histograms merge
  into the one fleet-wide ``latency.*`` ladder.

What partitions do NOT own is the device plane: every partition's
``on_batch`` feeds the service's ONE window queue, where the scorer's
micro-batch group path packs same-bucket close waves from many tenants
into the shared bucketed staging arenas (continuous cross-tenant
batching — the device never waits on any single tenant's window
cadence). Tenant attribution rides the emitted batch (``batch.tenant``)
so score sketches, drift state and top-K attribution stay per-tenant
downstream.

``tenants == 1`` constructs exactly the objects the pre-tenancy Service
constructed, wired identically — the K=1 parity contract
(tests/test_tenancy.py proves bit-identical windows against the raw
pipelines).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from alaz_tpu.config import RuntimeConfig
from alaz_tpu.events.intern import Interner
from alaz_tpu.graph.builder import WindowedGraphStore
from alaz_tpu.graph.snapshot import GraphBatch
from alaz_tpu.logging import get_logger
from alaz_tpu.obs.recorder import FlightRecorder
from alaz_tpu.obs.spans import SpanTracer
from alaz_tpu.utils.ledger import DropLedger
from alaz_tpu.utils.queues import BatchQueue

log = get_logger("alaz_tpu.tenancy")


class TenantPartition:
    """One tenant's host plane: interner namespace, drop ledger, source
    queues, aggregation pipeline and watermarks (module docstring).

    Construction mirrors the pre-tenancy Service wiring exactly when the
    caller passes its own interner/ledger/tracer (partition 0 does);
    later partitions get fresh namespaces. ``on_batch`` is the service's
    window enqueue, already bound to this partition's tenant id.
    """

    def __init__(
        self,
        tenant: int,
        config: RuntimeConfig,
        *,
        on_batch: Callable[[GraphBatch], None],
        interner: Optional[Interner] = None,
        ledger: Optional[DropLedger] = None,
        tracer: Optional[SpanTracer] = None,
        recorder: Optional[FlightRecorder] = None,
        export_backend=None,
        use_native_ingest: bool = False,
        scoring: bool = False,
        metrics=None,
    ):
        self.tenant = int(tenant)
        self.config = config
        self.interner = interner if interner is not None else Interner()
        self.ledger = ledger if ledger is not None else DropLedger()
        if recorder is not None and self.ledger.recorder is None:
            self.ledger.recorder = recorder
        self.recorder = recorder
        if tracer is None:
            # fresh per-tenant span plane: stage histograms merge into
            # the shared fleet ladder via the metrics registry; the
            # live-span maps stay apart (window ids collide across
            # tenants by design — same wall clock, different fleets)
            tcfg = getattr(config, "trace", None)
            tracer = SpanTracer(
                metrics=metrics,
                recorder=recorder,
                enabled=tcfg.enabled if tcfg is not None else True,
                max_live=tcfg.max_live if tcfg is not None else 4096,
                complete_at_emit=not scoring,
            )
        self.tracer = tracer

        suffix = f"-t{self.tenant}" if self.tenant else ""
        q = config.queues
        self.l7_queue = BatchQueue(q.l7_events, f"l7{suffix}", ledger=self.ledger)
        self.tcp_queue = BatchQueue(q.tcp_events, f"tcp{suffix}", ledger=self.ledger)
        self.proc_queue = BatchQueue(
            q.proc_events, f"proc{suffix}", ledger=self.ledger
        )
        # the k8s queue is CONTROL plane, not row plane: a dropped
        # resource message is not a lost data row, and ledgering it
        # would break the per-tenant conservation equation (pushed ==
        # emitted + ledger.total counts L7 rows) with phantom entries —
        # the queue's own dropped gauge keeps the loss visible
        self.k8s_queue = BatchQueue(q.kube_events, f"k8s{suffix}")

        renumber = getattr(config, "renumber_nodes", False)
        ingest_workers = max(1, int(getattr(config, "ingest_workers", 1)))
        ingest_backend = str(
            getattr(config, "ingest_backend", "thread") or "thread"
        ).lower()
        if ingest_backend not in ("thread", "process"):
            raise ValueError(
                f"ingest_backend must be 'thread' or 'process', got "
                f"{ingest_backend!r} (INGEST_BACKEND)"
            )
        degree_cap = max(0, int(getattr(config, "degree_cap", 0)))
        sample_seed = int(getattr(config, "sample_seed", 0))

        self.graph_store = None
        self.sharded = None
        self.fault_hook = None
        if use_native_ingest:
            from alaz_tpu.graph import native as native_mod

            if native_mod.available():
                if ingest_workers > 1:
                    log.warning(
                        "ingest_workers > 1 ignored with use_native_ingest: "
                        "the C++ window accumulator is its own ingest plane"
                    )
                # degree_cap rides the C++ close pass itself now
                # (alz_close_window_feats selects bottom-k priorities per
                # hot dst, bit-identical to degree_cap_select) — cut rows
                # land in the shared ledger under sampled/degree_cap, same
                # as the GraphBuilder paths
                self.graph_store = native_mod.NativeWindowedStore(
                    window_s=config.window_s,
                    on_batch=on_batch,
                    renumber=renumber,
                    degree_cap=degree_cap,
                    sample_seed=sample_seed,
                    ledger=self.ledger,
                )
            else:
                log.warning(
                    "native ingest requested but library unavailable; "
                    "using numpy store"
                )
        if self.graph_store is None and (
            ingest_workers > 1 or ingest_backend == "process"
        ):
            # sharded multi-worker ingest: the pipeline IS both the
            # aggregator (ingestion surface) and the windowed store —
            # one object plays both roles. Each tenant gets its OWN
            # pool: shard workers, queues/rings and close waves are
            # never shared across fleets. Backend per config
            # (ISSUE 15): "thread" = aggregator/sharded.py over the
            # shared interner; "process" = alaz_tpu/shm spawn workers
            # over shared-memory rings with id-exchange at merge (the
            # out-of-GIL path; applies even at ingest_workers == 1 so
            # ingest leaves the serving process's GIL).
            from alaz_tpu.aggregator.sharded import ShardedIngest

            # soak mode (CHAOS_ENABLED=1): per-partition injector so
            # every tenant's pool proves its self-healing independently
            # (tenant-offset seed: partitions draw independent streams)
            ccfg = getattr(config, "chaos", None)
            if ccfg is not None and ccfg.enabled:
                from alaz_tpu.chaos.injectors import WorkerChaos

                self.fault_hook = WorkerChaos(
                    seed=ccfg.seed + self.tenant,
                    crash_prob=ccfg.worker_crash_prob,
                    stall_prob=ccfg.worker_stall_prob,
                    stall_s=ccfg.worker_stall_s,
                    max_crashes=ccfg.worker_max_crashes,
                )
                log.warning(
                    "chaos soak enabled: worker-seam fault injection live"
                )
            if ingest_backend == "process":
                from alaz_tpu.shm.process_pool import ProcessShardedIngest

                if export_backend is not None:
                    # worker REQUEST rows carry process-LOCAL interner
                    # ids — an export tee would resolve them against the
                    # wrong table and ship another fleet's names. Refuse
                    # loudly; the thread backend keeps the tee.
                    raise ValueError(
                        "ingest_backend=process cannot drive the export "
                        "backend tee (worker rows carry process-local "
                        "interner ids); use INGEST_BACKEND=thread with "
                        "the export backend, or export from scores"
                    )
                self.sharded = ProcessShardedIngest(
                    ingest_workers,
                    interner=self.interner,
                    config=config,
                    window_s=config.window_s,
                    on_batch=on_batch,
                    renumber=renumber,
                    ledger=self.ledger,
                    shed_block_s=config.shed_block_s,
                    fault_hook=self.fault_hook,
                    degree_cap=degree_cap,
                    sample_seed=sample_seed,
                    tracer=self.tracer,
                    recorder=recorder,
                )
            else:
                self.sharded = ShardedIngest(
                    ingest_workers,
                    interner=self.interner,
                    config=config,
                    window_s=config.window_s,
                    on_batch=on_batch,
                    renumber=renumber,
                    tee=export_backend,
                    ledger=self.ledger,
                    shed_block_s=config.shed_block_s,
                    fault_hook=self.fault_hook,
                    degree_cap=degree_cap,
                    sample_seed=sample_seed,
                    tracer=self.tracer,
                    recorder=recorder,
                )
            self.graph_store = self.sharded
        if self.graph_store is None:
            self.graph_store = WindowedGraphStore(
                self.interner,
                window_s=config.window_s,
                on_batch=on_batch,
                renumber=renumber,
                ledger=self.ledger,
                degree_cap=degree_cap,
                sample_seed=sample_seed,
                tracer=self.tracer,
            )
        if self.sharded is not None:
            self.datastore = None  # worker sinks fan out inside the pipeline
            self.aggregator = self.sharded
        else:
            from alaz_tpu.aggregator.engine import Aggregator
            from alaz_tpu.runtime.service import FanoutDataStore

            sinks: List = [self.graph_store]
            if export_backend is not None:
                sinks.append(export_backend)
            self.datastore = FanoutDataStore(sinks)
            self.aggregator = Aggregator(
                self.datastore,
                interner=self.interner,
                config=config,
                # semantic (filtered) drops join the tenant ledger so
                # per-tenant conservation needs no side-channel term
                ledger=self.ledger,
                recorder=recorder,
            )

        # windows this partition emitted (written only by the partition's
        # closing thread — the l7 worker for serial stores, the merge
        # thread for sharded pools)
        self.windows_closed = 0  # lockless-ok: single-writer counter (the partition's closing thread); racy reads are stats gauges
        # edges.out convergence baseline for the sharded path: each
        # partition's l7 worker syncs ITS delta into the fleet counter
        self.edges_out_synced = 0  # role-private: touched only by this partition's l7 worker thread
        # idle-flush bookkeeping (housekeeping thread only)
        self.idle_flushed_for: Optional[float] = None  # role-private: housekeeping thread only
        # per-tenant gauge registration latch: first-window, idempotent,
        # single-writer (the partition's closing thread)
        self._gauges_done = False  # lockless-ok: single-writer latch (closing thread); Metrics.gauge is itself idempotent under its own lock

    # -- observability --------------------------------------------------------

    @property
    def queues(self) -> tuple:
        return (self.l7_queue, self.tcp_queue, self.proc_queue, self.k8s_queue)

    def register_tenant_gauges(self, metrics) -> None:
        """Register this tenant's ``ledger.*.t<k>`` series — called at
        the tenant's FIRST window, never at wiring time, so an idle
        tenant is absent from the scrape instead of rendering zeros
        (the sparse-series discipline, ISSUE 11)."""
        if self._gauges_done or metrics is None:
            return
        self._gauges_done = True
        ledger = self.ledger
        t = self.tenant
        for cause in ledger.CAUSES:
            metrics.gauge(f"ledger.{cause}.t{t}", lambda c=cause: ledger.count(c))
        metrics.gauge(f"ledger.total.t{t}", lambda: ledger.total)
        metrics.gauge(
            f"ingest.windows_closed.t{t}", lambda: self.windows_closed
        )

    def snapshot(self) -> dict:
        """One tenant's /stats entry: queue lag, ledger breakdown,
        aggregator stats, window count."""
        out = {
            "queues": {q.name: q.stats() for q in self.queues},
            "ledger": self.ledger.snapshot(),
            "windows_closed": self.windows_closed,
            "aggregator": self.aggregator.stats.as_dict(),
            "interned_strings": len(self.interner),
        }
        if self.sharded is not None:
            out["worker_restarts"] = self.sharded.worker_restarts
            out["shard_backlog"] = self.sharded.unfinished
        return out

    # -- lifecycle ------------------------------------------------------------

    def stop(self) -> None:
        if self.sharded is not None:
            self.sharded.stop()


def validate_tenants(config: RuntimeConfig, model_state, use_native: bool) -> int:
    """Resolve and validate the partition count for a Service build.

    Raises on combinations that would silently corrupt a tenant's data:
    the C++ native ring is a single-tenant plane, and the temporal
    model's node memory is slot-indexed across windows — K fleets
    interleaving through one memory would cross-contaminate state."""
    from alaz_tpu.events.schema import MAX_TENANTS

    tenants = max(1, int(getattr(config, "tenants", 1)))
    if tenants > MAX_TENANTS:
        raise ValueError(
            f"tenants={tenants} exceeds the wire header's MAX_TENANTS "
            f"({MAX_TENANTS}); the frame tenant id is one byte"
        )
    if tenants > 1 and use_native:
        raise ValueError(
            "use_native_ingest is incompatible with tenants > 1: the C++ "
            "window accumulator is a single-tenant plane"
        )
    if tenants > 1 and model_state is not None and config.model.model == "tgn":
        raise ValueError(
            "model=tgn is incompatible with tenants > 1: the temporal "
            "memory is slot-indexed across windows and would interleave "
            "tenants' node state; score each fleet on its own backend or "
            "pick a window-independent model"
        )
    return tenants
