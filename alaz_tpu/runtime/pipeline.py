"""Host→device pipeline: double-buffered window feeding (SURVEY §2.3 P3).

The reference pipelines stages with channels; on TPU the analog is
overlapping the host→device transfer of window N+1 with the scoring of
window N. ``DevicePrefetcher`` wraps an iterator of GraphBatches: it
issues ``jax.device_put`` for the next batch while the caller computes on
the current one (JAX transfers are async, so the overlap costs one
in-flight buffer of HBM).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional

from alaz_tpu.graph.snapshot import GraphBatch


class DevicePrefetcher:
    def __init__(self, batches: Iterable[GraphBatch], device=None):
        self._it = iter(batches)
        self._device = device
        self._staged: Optional[tuple[GraphBatch, dict]] = None

    def _stage(self) -> Optional[tuple[GraphBatch, dict]]:
        import jax
        import jax.numpy as jnp

        try:
            batch = next(self._it)
        except StopIteration:
            return None
        arrays = {k: jnp.asarray(v) for k, v in batch.device_arrays().items()}
        if self._device is not None:
            arrays = jax.device_put(arrays, self._device)
        return batch, arrays

    def __iter__(self) -> Iterator[tuple[GraphBatch, dict]]:
        self._staged = self._stage()
        while self._staged is not None:
            current = self._staged
            # start the next transfer before yielding (compute overlaps it)
            self._staged = self._stage()
            yield current
