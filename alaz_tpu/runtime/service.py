"""The streaming scoring service — the main.go:28-188 wiring analog.

Pipeline (every arrow a bounded queue; drop-not-block at the source edge):

    sources → [l7 | tcp | proc | k8s queues] → aggregator workers
            → fanout datastore (graph store [+ export backend])
            → window queue → scorer thread (jit'd GNN, one program per
              shape bucket) → score sink (edge annotations back through
              the dto path — the BASELINE.json return leg)

Pause/resume hooks match the health checker's stop/resume protocol.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time as time_module
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import numpy as np

from alaz_tpu.config import RuntimeConfig
from alaz_tpu.datastore.interface import BaseDataStore, DataStore
from alaz_tpu.events.intern import Interner
from alaz_tpu.graph.builder import WindowedGraphStore, src_locality_gauges
from alaz_tpu.graph.snapshot import GraphBatch
from alaz_tpu.logging import get_logger
from alaz_tpu.obs.device import CompileEventPlane, DeviceTelemetry, bucket_key
from alaz_tpu.obs.recorder import FlightRecorder
from alaz_tpu.obs.scores import ScorePlane
from alaz_tpu.obs.spans import SpanTracer
from alaz_tpu.runtime.metrics import Metrics, device_gauges, host_gauges, ledger_gauges
from alaz_tpu.runtime.tenancy import TenantPartition, validate_tenants
from alaz_tpu.utils.ledger import DropLedger
from alaz_tpu.utils.queues import BatchQueue

log = get_logger("alaz_tpu.service")


@dataclass
class ScoreRecord:
    """One anomaly-score edge annotation (dto.go leg) — the *view* type;
    the hot path moves ScoreBatch columns and only materializes records
    when a consumer iterates."""

    window_start_ms: int
    from_uid: str
    to_uid: str
    protocol: str
    score: float


@dataclass
class ScoreBatch:
    """Columnar anomaly scores for one window (above-threshold edges only).
    uid columns hold interned ids; string resolution is deferred to the
    consumer (the backend amortizes it per unique node). Iterating yields
    ScoreRecords for tests/debug sinks — the export leg never iterates."""

    window_start_ms: int
    from_uid: np.ndarray  # interned node uid ids [K]
    to_uid: np.ndarray  # [K]
    protocol: np.ndarray  # wire protocol codes [K]
    score: np.ndarray  # sigmoid scores [K], float32
    interner: Interner

    def __len__(self) -> int:
        return int(self.score.shape[0])

    def __iter__(self):
        from alaz_tpu.events.schema import _PROTOCOL_NAMES as proto

        lookup = self.interner.lookup
        names: dict[int, str] = {}
        for i in range(len(self)):
            f, t = int(self.from_uid[i]), int(self.to_uid[i])
            for u in (f, t):
                if u not in names:
                    names[u] = lookup(u)
            yield ScoreRecord(
                window_start_ms=self.window_start_ms,
                from_uid=names[f],
                to_uid=names[t],
                protocol=proto[int(self.protocol[i])],
                score=float(self.score[i]),
            )


class StagingArenas:
    """Reusable host staging buffers for the vmapped group-score path.

    One ``[W, ...]`` arena per (bucket-shape, W) key, so steady-state
    group scoring allocates nothing on the host — the per-group
    ``np.stack`` (a fresh multi-MB allocation per dispatch at the large
    buckets) becomes ``np.copyto`` into a warm buffer. Arenas are
    **double-buffered** per key: the scorer stages group k+1 into the
    other buffer while group k's transfer/compute may still be reading
    the first (jax may alias host memory on CPU backends, and device
    transfers are async), and it always blocks on group k's result
    before a buffer comes around again — two buffers are exactly enough.
    """

    def __init__(self) -> None:
        # today a single scorer thread owns the arenas, but the swap is a
        # read-modify-write: two concurrent fills for one key would hand
        # out the SAME buffer (silent window corruption, the exact class
        # of bug alazlint's guarded-by rule exists for) — so the swap is
        # locked; once per group dispatch, noise next to the copies
        self._lock = threading.Lock()
        self._pool: dict[tuple, list] = {}  # guarded-by: self._lock
        self._next: dict[tuple, int] = {}  # guarded-by: self._lock
        self.fills = 0  # guarded-by: self._lock
        self.reuses = 0  # perf smoke: steady state must be allocation-free  # guarded-by: self._lock

    def fill(self, key: tuple, cols: List[dict]) -> dict:
        """Copy ``cols`` (one device_arrays dict per window) into the
        next arena for ``key`` and return it."""
        k = (key, len(cols))
        with self._lock:
            arenas = self._pool.setdefault(k, [None, None])
            i = self._next.get(k, 0)
            self._next[k] = 1 - i
            arena = arenas[i]
            if arena is None:
                arena = {
                    name: np.empty((len(cols),) + a.shape, a.dtype)
                    for name, a in cols[0].items()
                }
                arenas[i] = arena
            else:
                self.reuses += 1
            self.fills += 1
        # the copies run OUTSIDE the lock: the double-buffer discipline
        # (caller finishes group k before buffer k comes around again)
        # makes the returned arena exclusively this caller's to fill
        for w, c in enumerate(cols):
            for name, a in c.items():
                np.copyto(arena[name][w], a)
        return arena


@functools.lru_cache(maxsize=None)
def _batched_score_fn(cfg):
    """Jitted vmapped score fn, cached per ModelConfig: every Service
    with the same config shares one trace cache, so re-construction never
    re-traces (ALZ006 / the retrace budget). The inner fn is NAMED so the
    compile log (sanitize.retrace.CompileWatcher) can attribute compiles
    to this entry point."""
    import jax

    from alaz_tpu.models.registry import get_model

    _, apply = get_model(cfg.model)

    def batched_score_apply(params, graph):
        return apply(params, graph, cfg)

    return jax.jit(jax.vmap(batched_score_apply, in_axes=(None, 0)))


class FanoutDataStore(BaseDataStore):
    """Tee persisted data to several sinks (graph store + export backend)."""

    def __init__(self, sinks: List[DataStore]):
        self.sinks = sinks

    def persist_requests(self, batch: np.ndarray) -> None:
        for s in self.sinks:
            s.persist_requests(batch)

    def persist_kafka_events(self, batch: np.ndarray) -> None:
        for s in self.sinks:
            s.persist_kafka_events(batch)

    def persist_alive_connections(self, batch: np.ndarray) -> None:
        for s in self.sinks:
            s.persist_alive_connections(batch)

    def persist_resource(self, rtype, event, obj) -> None:
        for s in self.sinks:
            s.persist_resource(rtype, event, obj)


class Service:
    def __init__(
        self,
        config: Optional[RuntimeConfig] = None,
        interner: Optional[Interner] = None,
        export_backend: Optional[DataStore] = None,
        score_sink: Optional[Callable[[ScoreBatch], None]] = None,
        model_state: Any = None,  # params; None = scoring disabled
        score_threshold: float = 0.5,  # only annotate edges scoring above
        use_native_ingest: bool = False,  # C++ window accumulator when built
        score_fn: Optional[Callable] = None,  # host scorer override (see below)
        score_many_fn: Optional[Callable] = None,  # its vmapped-group twin
    ):
        self.score_threshold = score_threshold
        self.use_native_ingest = use_native_ingest
        self.config = config if config is not None else RuntimeConfig()
        self.interner = interner if interner is not None else Interner()
        # host scorer override (ISSUE 14): ``score_fn(params, graph) ->
        # {"edge_logits": ...}`` replaces the jit'd model — the tenancy
        # replay harness and ``bench.py --tenants`` drive the WHOLE
        # service plane (queues → partitions → window queue → scorer →
        # per-tenant score planes) with a deterministic numpy scorer, so
        # isolation gates measure the plane, not XLA compile jitter.
        # When set, graphs stay numpy (no device transfer, no compile
        # plane) and ``score_many_fn(params, stacked)`` — if given —
        # serves the micro-batch group path over the stacked arenas.
        # OWNERSHIP: ``stacked`` is a REUSED double-buffered staging
        # arena — score_many_fn must return arrays it owns (any
        # arithmetic copies; a bare view would be clobbered by the next
        # group's arena fill before the result is read).
        self._host_score = score_fn is not None
        self.tenants = validate_tenants(self.config, model_state, use_native_ingest)
        self.score_observer: Optional[Callable] = None  # (batch, tenant, latency_s)  # lockless-ok: attach-once harness hook published before windows flow; the scorer null-checks an atomic reference read
        self.metrics = Metrics()
        device_gauges(self.metrics)
        host_gauges(self.metrics)
        # observability plane (ISSUE 9, alaz_tpu/obs): a bounded ring of
        # structured runtime events (window spans, worker restarts,
        # breaker flips, every ledger decision) + the per-window span
        # tracer whose stage durations feed the latency.* histograms.
        # Tracing is ON by default (TraceConfig / TRACE_ENABLED=0 to
        # kill); the bench's trace_overhead_pct A/B bounds its cost.
        tcfg = getattr(self.config, "trace", None)
        if tcfg is None:
            from alaz_tpu.config import TraceConfig

            tcfg = TraceConfig()
        self.recorder = FlightRecorder(
            capacity=tcfg.recorder_capacity,
            metrics=self.metrics,
            dump_on_crash=tcfg.recorder_dump_on_crash,
        )
        # unified loss accounting (ISSUE 6): every row this service
        # loses — queue-mouth drop, late straggler, quarantined frame,
        # deliberate shed — lands in exactly one ledger cause (and, via
        # the recorder hook, in the flight-recorder trail)
        self.ledger = DropLedger()
        self.ledger.recorder = self.recorder
        ledger_gauges(self.metrics, self.ledger)
        # rows refused at the door for an UNKNOWN tenant id (ISSUE 14)
        # get their own ledger: they belong to no partition, and folding
        # them into tenant 0's books (self.ledger aliases partition 0)
        # would break that tenant's exact conservation equation with
        # rows it never saw. Reported apart in degraded_snapshot.
        self.refused_ledger = DropLedger()
        self.refused_ledger.recorder = self.recorder
        # warn-once latch per refused tenant id (the _warned_no_native
        # pattern): a hostile/misconfigured agent streaming thousands of
        # mis-tagged frames per second must cost a counter bump, not an
        # unbounded log flood. Bounded: wire ids fit a byte; API callers
        # past the cap stay silent (the counter carries the signal).
        # the check-then-act (membership probe + cap + add) is locked:
        # alazrace's v1.1 lockset walk counts the `.add(...)` as a
        # structural write, and the old lockless-ok sanction cannot
        # bless an unlocked container mutation (ALZ053)
        self._warn_lock = threading.Lock()
        self._warned_tenants: set = set()  # guarded-by: self._warn_lock
        # spans complete at emit when no scorer runs behind the store;
        # with a model they stay open through stage/score/export
        self.tracer = SpanTracer(
            metrics=self.metrics,
            recorder=self.recorder,
            enabled=tcfg.enabled,
            max_live=tcfg.max_live,
            complete_at_emit=model_state is None,
        )
        # device-side telemetry (ISSUE 11, obs/device.py): per-bucket
        # score latency + occupancy at staging time, the stage
        # arena/transfer decomposition (+ byte ledger), pad-waste — the
        # numbers the Pallas/mixed-precision/multi-tenant work will be
        # judged by. DEVICE_TRACE_ENABLED=0 kills it independently.
        self.device = DeviceTelemetry(
            metrics=self.metrics,
            recorder=self.recorder,
            enabled=tcfg.enabled and tcfg.device_enabled,
        )
        # always-on compile event plane (ISSUE 11): sanitize's
        # CompileWatcher promoted to production — a steady-state retrace
        # shows up in compile.* on /metrics and in crash dumps, not only
        # under `make sanitize`. Only a scoring service compiles device
        # programs, so the hookup rides model_state.
        self.compile_plane: Optional[CompileEventPlane] = None
        # same gate as DeviceTelemetry: TRACE_ENABLED=0 is the master
        # obs kill switch and must silence the compile capture too. A
        # host-score service compiles nothing — no capture to run.
        if (
            model_state is not None
            and not self._host_score
            and tcfg.enabled
            and tcfg.device_enabled
        ):
            self.compile_plane = CompileEventPlane(
                metrics=self.metrics, recorder=self.recorder
            ).start()
        # score-plane observability (ISSUE 13, obs/scores.py): per-model
        # distribution sketch, drift detection, top-K attribution —
        # rides model_state like the compile plane (a non-scoring
        # service has no scores to watch) and registers NOTHING when
        # disabled (absent-not-zero). Serial + ShardedIngest paths share
        # one accounting: both feed through record_window.
        #
        # Tenancy (ISSUE 14): with one tenant the plane is the eager
        # singleton it always was. With K > 1, sketches/drift/top-K must
        # stay PER-TENANT (one fleet's incident must not page — or
        # mask — another's), so planes are created lazily at each
        # tenant's first scored window under a ``.t<k>`` metric suffix:
        # an idle tenant is absent from the scrape, never a zero render.
        self._scores_enabled = (
            model_state is not None and tcfg.enabled and tcfg.score_enabled
        )
        self._trace_cfg = tcfg
        # per-tenant plane map: inserts happen on the scorer thread only
        # but under a lock (dict resize is not GIL-atomic against the
        # read side); readers (/scores handlers, snapshots) take a
        # dict() copy without the lock — the blessed locked-writes +
        # lockless-reads shape
        self._planes_lock = threading.Lock()
        self._score_planes: dict = {}  # tenant -> ScorePlane  # lockless-ok: locked writes (scorer thread under _planes_lock) + lockless dict-copy reads
        self.scores: Optional[ScorePlane] = None
        if self.tenants == 1:
            self.scores = ScorePlane(
                metrics=self.metrics,
                recorder=self.recorder,
                enabled=self._scores_enabled,
                model=self.config.model.model,
                drift_windows=tcfg.score_drift_windows,
                top_k=tcfg.score_top_k,
                resolve=self.interner.lookup,
            )
            self._score_planes[0] = self.scores
        self._export_backend = export_backend
        if export_backend is not None and getattr(
            export_backend, "ledger", None
        ) is None:
            # wire the export leg its OWN ledger (ISSUE 12 satellite):
            # breaker sheds attribute as the closed `shed` cause. A
            # SEPARATE instance, not self.ledger — the export tee sees
            # rows the graph path also emits, so folding its sheds into
            # the pipeline ledger would double-count against
            # pushed == emitted + ledger.total (the exact equation the
            # chaos gates check); degraded_snapshot surfaces it apart.
            export_backend.ledger = DropLedger()

        # the window queue is interior backpressure, not a source edge —
        # a drop there is the pipeline choosing to shed. NOT ledger-wired
        # at the queue mouth: its items are [GraphBatch] lists (size 1),
        # and the ledger's contract is ROWS — _enqueue_window attributes
        # the batch's true aggregated row count on drop instead. ONE
        # queue for all tenants: this is where cross-tenant batching
        # happens — close waves from every partition interleave here and
        # the scorer packs same-bucket windows into shared arenas.
        self.window_queue = BatchQueue(10_000_000, "windows")

        renumber = getattr(self.config, "renumber_nodes", False)
        if renumber and self.config.model.model == "tgn":
            # per-window renumbering scrambles node SLOTS between windows;
            # the temporal model's memory is slot-indexed across windows
            raise ValueError(
                "renumber_nodes is incompatible with model=tgn "
                "(cross-window slot-indexed memory); disable one of the two"
            )
        # per-tenant host-plane partitions (ISSUE 14, runtime/tenancy.py):
        # partition 0 owns the service-level interner/ledger/tracer (the
        # K=1 wiring is bit-identical to the pre-tenancy service); later
        # partitions get fresh namespaces. Every partition's on_batch
        # lands in the ONE window queue, tenant-stamped.
        if export_backend is not None and self.tenants > 1:
            # the export tee resolves interned uids against the ONE
            # interner the backend was built with (partition 0's):
            # teeing other fleets' rows through it would resolve their
            # uids in the WRONG namespace and export tenant A's traffic
            # under tenant B's service names. Until the per-tenant
            # export leg lands (ROADMAP follow-on), only the primary
            # tenant exports — loudly, not silently.
            log.warning(
                "export backend attached with tenants > 1: only tenant "
                "0 (the primary) exports — the backend resolves uids in "
                "one interner namespace; per-tenant export is a roadmap "
                "follow-on"
            )
        self.partitions: List[TenantPartition] = []
        for t in range(self.tenants):
            self.partitions.append(
                TenantPartition(
                    t,
                    self.config,
                    on_batch=functools.partial(self._enqueue_window, tenant=t),
                    interner=self.interner if t == 0 else None,
                    ledger=self.ledger if t == 0 else None,
                    tracer=self.tracer if t == 0 else None,
                    recorder=self.recorder,
                    export_backend=export_backend if t == 0 else None,
                    use_native_ingest=use_native_ingest and t == 0,
                    scoring=model_state is not None,
                    metrics=self.metrics,
                )
            )
        p0 = self.partitions[0]
        # partition-0 aliases: the single-tenant surface every existing
        # consumer (gauges below, /stats, tests, the ingest socket's
        # native-store probe) keys on. With K > 1 the unsuffixed series
        # describe tenant 0 — the primary/legacy tenant — and the
        # ``.t<k>`` series carry the per-tenant breakdown.
        self.l7_queue = p0.l7_queue
        self.tcp_queue = p0.tcp_queue
        self.proc_queue = p0.proc_queue
        self.k8s_queue = p0.k8s_queue
        self.graph_store = p0.graph_store
        self.sharded = p0.sharded
        self.aggregator = p0.aggregator
        self.datastore = p0.datastore
        if self.tenants > 1:
            # trace.live: each partition's SpanTracer registered the
            # gauge in turn (last write wins) — rebind it to the fleet
            # sum so the scrape reads live spans across ALL tenants
            parts = list(self.partitions)
            self.metrics.gauge(
                "trace.live", lambda: sum(p.tracer.live_count for p in parts)
            )

        self.score_sink = score_sink
        if self.score_sink is None and export_backend is not None and hasattr(export_backend, "persist_scores"):
            # scores flow back to the backend's /anomalies/ stream by default
            self.score_sink = export_backend.persist_scores
        self.model_state = model_state
        self._score_fn = None
        self._tgn_memory = None  # temporal model node memory (tgn only)
        if model_state is not None and score_fn is not None:
            # host scorer override: no registry import, no jit, no jax —
            # the scorer loop runs the callable over numpy graphs
            self._score_fn = score_fn
        elif model_state is not None:
            if self.config.model.model == "tgn":
                from alaz_tpu.models import tgn

                # pre-size memory to the largest configured bucket so a
                # growing fleet never pays a serving-time recompile for a
                # new (bucket, memory-shape) pair (tgn.step still
                # zero-extends as a fallback if the bucket outgrows it)
                self._tgn_memory = tgn.init_memory(
                    self.config.model, max_nodes=self.config.model.tgn_max_nodes
                )
                # cached per ModelConfig: repeated Service construction
                # shares one jitted step and its compile cache (ALZ006)
                jitted_step = tgn.make_step_fn(self.config.model)

                def tgn_score(params, graph):
                    out, self._tgn_memory = jitted_step(params, graph, self._tgn_memory)
                    return out

                self._score_fn = tgn_score
            else:
                from alaz_tpu.train.trainstep import make_score_fn

                self._score_fn = make_score_fn(self.config.model)
        # backlog micro-batching (config.score_batch_windows): vmapped
        # twin of the score fn for window-independent models. TGN is
        # excluded — its memory threads sequentially through windows.
        self._score_many_fn = None
        self._stage_arenas = StagingArenas()
        self._batch_windows = max(1, int(self.config.score_batch_windows))
        if (
            self._score_fn is not None
            and self._batch_windows > 1
            and self.config.model.model != "tgn"
        ):
            if self._host_score:
                # group scoring only when the override supplies its
                # stacked twin; otherwise windows score serially
                self._score_many_fn = score_many_fn
            else:
                self._score_many_fn = _batched_score_fn(self.config.model)
        # cross-tenant batching accounting (ISSUE 14): dispatches vs
        # windows is the group-occupancy number `bench.py --tenants`
        # publishes (K fleets on one backend should fill groups that K
        # serial backends would dispatch one window at a time). Scorer
        # thread only.
        self.score_dispatches = 0  # role-private: scorer thread only
        self.multi_tenant_groups = 0  # role-private: scorer thread only

        self.housekeeping_interval_s = 120.0  # reference ticker cadence
        self.scored_batches = 0  # lockless-ok: single-writer GIL-atomic counter (scorer thread); racy reads are stats gauges
        self.scored_edges = 0  # lockless-ok: single-writer GIL-atomic counter (scorer thread); racy reads are stats gauges
        self._paused = threading.Event()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

        self.metrics.gauge("l7.pending", lambda: self.l7_queue.pending_events)
        self.metrics.gauge("l7.dropped", lambda: self.l7_queue.dropped)
        self.metrics.gauge("tcp.pending", lambda: self.tcp_queue.pending_events)
        self.metrics.gauge("windows.pending", lambda: len(self.window_queue))
        self.metrics.gauge("windows.late_dropped", lambda: self.graph_store.late_dropped)
        # native path only: backpressure (ring-full) drops and node/edge
        # table-capacity drops, each distinct from lateness
        self.metrics.gauge(
            "ingest.ring_dropped", lambda: getattr(self.graph_store, "ring_dropped", 0)
        )
        self.metrics.gauge(
            "ingest.acc_dropped", lambda: getattr(self.graph_store, "acc_dropped", 0)
        )
        # sharded path only: pool width, in-flight shard backlog and the
        # merge-stage share of the pipeline (ARCHITECTURE §3f)
        if self.sharded is not None:
            self.metrics.gauge("ingest.workers", lambda: self.sharded.n)
            self.metrics.gauge(
                "ingest.shard_unfinished", lambda: self.sharded.unfinished
            )
            self.metrics.gauge("ingest.merge_s", lambda: self.sharded.merge_s)
            # self-healing plane (ISSUE 6): restarts say workers are
            # dying; a climbing last-wave age says the merge thread is
            # stalled — the failure that used to be perfectly silent
            self.metrics.gauge(
                "ingest.worker_restarts", lambda: self.sharded.worker_restarts
            )
            self.metrics.gauge(
                "ingest.last_wave_age_s", lambda: self.sharded.last_wave_age_s
            )
            # degree-cap activity (ISSUE 7): nonzero means a hot key is
            # live RIGHT NOW and the sampler is what's absorbing it —
            # rows cut ride the ledger.sampled gauge, this one counts
            # aggregated edges so fan-in magnitude is readable directly
            self.metrics.gauge(
                "ingest.sampled_edges",
                lambda: self.sharded.builder.sampled_edges,
            )
            # process backend only (ISSUE 15, alaz_tpu/shm): shared-
            # memory ring occupancy — slots committed but not yet
            # consumed, summed across workers per direction. A climbing
            # request-side number is a worker falling behind; a climbing
            # response side is the merge thread falling behind.
            if hasattr(self.sharded, "shm_req_pending"):
                # lock-free cursor reads per scrape (never the per-ring
                # put_lock the scatter path contends on)
                self.metrics.gauge(
                    "ingest.shm_req_pending_slots",
                    lambda: self.sharded.shm_req_pending(),
                )
                self.metrics.gauge(
                    "ingest.shm_resp_pending_slots",
                    lambda: self.sharded.shm_resp_pending(),
                )
        elif isinstance(self.graph_store, WindowedGraphStore):
            self.metrics.gauge(
                "ingest.sampled_edges",
                lambda: self.graph_store.builder.sampled_edges,
            )
        if export_backend is not None and hasattr(export_backend, "breaker"):
            # 0 closed / 1 half-open / 2 open — numeric for dashboards
            self.metrics.gauge(
                "backend.breaker_state",
                lambda: {"closed": 0.0, "half-open": 1.0, "open": 2.0}[
                    export_backend.breaker.state
                ],
            )
            # breaker flips land in the flight-recorder trail (ISSUE 9)
            export_backend.breaker.recorder = self.recorder
        # the TPU analog of the NVML gpu_utz gauge: fraction of wall time
        # the scorer spends in device compute (includes host→device feed)
        self._scorer_busy_s = 0.0
        self.metrics.gauge(
            "scorer.duty_cycle_pct",
            lambda: 100.0
            * self._scorer_busy_s
            / max(time_module.time() - self.metrics.started_at, 1e-9),
        )
        # metrics scrape-and-push leg (backend.go:340-392,1038-1105)
        if export_backend is not None and hasattr(export_backend, "attach_metrics"):
            export_backend.attach_metrics(self.metrics.render_prometheus)

    # -- ingestion surface (what sources call) ------------------------------

    def _tenant_known(self, tenant: int, rows: int) -> bool:
        """True iff this service has a partition for ``tenant``. A
        mis-tagged or hostile frame is refused at the door (accounted
        below) — routing it into another tenant's stream would corrupt
        that tenant's windows, which is the exact failure tenancy
        exists to prevent."""
        if 0 <= tenant < self.tenants:
            return True
        self._refuse_unknown_tenant(tenant, rows)
        return False

    def _refuse_unknown_tenant(self, tenant: int, rows: int) -> None:
        """Account rows refused for an unknown tenant id: attributed to
        the service's REFUSED ledger (the rows belong to no partition —
        inventing one per hostile byte would be an allocation DoS, and
        folding them into any tenant's books would corrupt that
        tenant's exact conservation equation)."""
        if rows:
            self.refused_ledger.add("filtered", rows, reason="unknown_tenant")
        # one unit, always: the counter counts refusal EVENTS (frames /
        # submits — row-less k8s refusals included); lost ROWS ride the
        # refused ledger, so the two series never mix units
        self.metrics.counter("ingest.unknown_tenant").inc()
        with self._warn_lock:  # warn-once latch is check-then-act
            first_refusal = (
                tenant not in self._warned_tenants
                and len(self._warned_tenants) < 300
            )
            if first_refusal:
                self._warned_tenants.add(tenant)
        if first_refusal:
            log.warning(
                f"refused frame for unknown tenant {tenant} "
                f"(service runs {self.tenants}); further refusals for this "
                "id count silently into ingest.unknown_tenant"
            )

    def submit_l7(self, batch: np.ndarray, tenant: int = 0) -> bool:
        if self._paused.is_set():
            return False
        if not self._tenant_known(tenant, int(batch.shape[0])):
            return False
        ok = self.partitions[tenant].l7_queue.put_nowait_drop(batch)
        self.metrics.counter("l7.in").inc(batch.shape[0])
        return ok

    def submit_tcp(self, batch: np.ndarray, tenant: int = 0) -> bool:
        if self._paused.is_set():
            return False
        if not self._tenant_known(tenant, int(batch.shape[0])):
            return False
        return self.partitions[tenant].tcp_queue.put_nowait_drop(batch)

    def submit_proc(self, batch: np.ndarray, tenant: int = 0) -> bool:
        if self._paused.is_set():
            return False
        if not self._tenant_known(tenant, int(batch.shape[0])):
            return False
        return self.partitions[tenant].proc_queue.put_nowait_drop(batch)

    def submit_k8s(self, msg, tenant: int = 0) -> bool:
        if self._paused.is_set():
            return False
        if not self._tenant_known(tenant, 0):
            return False
        return self.partitions[tenant].k8s_queue.put_nowait_drop([msg])

    # -- workers -------------------------------------------------------------

    def _enqueue_window(self, batch: GraphBatch, tenant: int = 0) -> None:
        part = self.partitions[tenant]
        # tenant attribution rides the batch through the SHARED window
        # queue (ISSUE 14): record_window routes sketches/drift/top-K to
        # the right per-tenant plane, and the close→score latency stamp
        # is what the per-tenant p99 gate measures
        batch.tenant = tenant
        batch.closed_monotonic = time_module.monotonic()
        part.windows_closed += 1
        if self.tenants > 1:
            # first-window gauge registration: per-tenant ledger series
            # appear when the tenant first produces, never before
            part.register_tenant_gauges(self.metrics)
        if not self.window_queue.put_nowait_drop([batch]):
            # ledger in ROWS, not batches (GraphBatch.aggregated_rows —
            # the one conservation row measure). The shed attributes to
            # the EMITTING tenant's ledger — per-tenant conservation is
            # the isolation gate's invariant.
            part.ledger.add("shed", batch.aggregated_rows(), reason="windows")
            # a shed window never reaches the scorer: drop its live span
            # (an eviction tick, not a leak) instead of leaving it open
            part.tracer.discard(batch.window_start_ms)
        self.metrics.counter("windows.closed").inc()
        # the banded src-gather's cost models on live traffic: lets an
        # operator read off whether SRC_GATHER=banded would pay here.
        # The decisive gauge is the straggler fraction (<0.125, the
        # kernel's fix-up budget → banded pays; →1.0 → keep the XLA
        # gather); the [min,max] band width rides along for context.
        # Multi-tenant: K closing threads would race these shared
        # set-style gauges into whichever-tenant-closed-last noise, so
        # only the PRIMARY tenant's windows feed them (the series keeps
        # one deterministic meaning; per-tenant locality is a follow-on)
        if tenant == 0:
            band_w, strag = src_locality_gauges(
                batch.edge_src[: batch.n_edges], n_nodes=batch.n_nodes
            )
            self.metrics.gauge("windows.src_band_windows").set(band_w)
            self.metrics.gauge("windows.src_straggler_fraction").set(strag)

    def _consume(self, queue: BatchQueue, fn: Callable[[Any], None]) -> None:
        """Worker loop: every successfully-gotten batch is matched with a
        task_done (drain() hangs otherwise)."""
        while not self._stop.is_set():
            batch = queue.get(timeout=0.1)
            if batch is None:
                continue
            try:
                fn(batch)
            finally:
                queue.task_done()

    def _l7_worker(self, part: TenantPartition) -> None:
        def handle(batch):
            out = part.aggregator.process_l7(batch)
            if out is not None:
                self.metrics.counter("edges.out").inc(int(out.shape[0]))
            elif part.sharded is not None:
                # sharded pipeline processes async and returns None —
                # converge the counter onto the pipeline's authoritative
                # emitted total so edges.out dashboards keep reading the
                # truth (lag: at most the in-flight shard backlog). Per
                # partition: only THIS partition's l7 worker syncs its
                # delta (tracked on the partition), so K workers never
                # race a shared read-inc pair.
                delta = part.sharded.stats.edges_out - part.edges_out_synced
                if delta > 0:
                    part.edges_out_synced += delta
                    self.metrics.counter("edges.out").inc(delta)

        self._consume(part.l7_queue, handle)

    def _tcp_worker(self, part: TenantPartition) -> None:
        self._consume(part.tcp_queue, part.aggregator.process_tcp)

    def _proc_worker(self, part: TenantPartition) -> None:
        self._consume(part.proc_queue, part.aggregator.process_proc)

    def _k8s_worker(self, part: TenantPartition) -> None:
        def handle(msgs):
            for m in msgs:
                part.aggregator.process_k8s(m)

        self._consume(part.k8s_queue, handle)

    def _housekeeping_worker(self) -> None:
        """Periodic gc: socket lines, h2 stream reaping, DNS purge — the
        reference's 2-minute ticker loops (data.go:177-219,1688)."""
        while not self._stop.wait(self.housekeeping_interval_s):
            try:
                for part in self.partitions:
                    part.aggregator.gc()
                # timer-driven retry flush: requeued events must not wait
                # for the next L7 batch to arrive (input lulls)
                self._flush_retries_counted()
                # zombie reaper: processes that died without an EXIT event
                # (data.go:192-219; probes <proc_root>/<pid> existence, NOT
                # kill(pid,0) — see engine.reap_zombies). Valid ONLY when
                # tracked pids belong to this node — replayed/remote pids
                # would all look dead and lose their join state.
                if self.config.local_pids:
                    for part in self.partitions:
                        part.aggregator.reap_zombies()
                # traffic-lull liveness: with no newer event the watermark
                # never advances, so the last window would sit open
                # forever. Ingest idleness (not event time — replay clocks
                # are synthetic) triggers the flush, PER TENANT: one
                # fleet going quiet must flush its last window even while
                # another fleet streams on. The grace knob trades
                # staleness against upstream delivery stalls: rows that
                # arrive after their window was idle-flushed drop as late.
                grace_s = max(self.config.idle_flush_grace_s, 2 * self.config.window_s)
                for part in self.partitions:
                    last = getattr(part.graph_store, "last_persist_monotonic", None)
                    if (
                        last is not None
                        and last != part.idle_flushed_for
                        and time_module.monotonic() - last > grace_s
                    ):
                        part.graph_store.flush()
                        # one flush per idle period: until a new persist
                        # moves the timestamp there is nothing more to
                        # drain, so don't re-take the store lock per tick
                        part.idle_flushed_for = last
                # channel-lag log (data.go:177-186 cadence)
                lag = {
                    q.name: q.stats()
                    for q in (self.l7_queue, self.tcp_queue, self.window_queue)
                }
                log.info(f"queue lag: {lag}")
            except Exception as exc:
                log.warning(f"housekeeping failed: {exc}")

    def _scorer_worker(self) -> None:
        if self._host_score:
            jnp = None  # host scorer: numpy end to end, jax never imports
        else:
            import jax.numpy as jnp

            from alaz_tpu.models.registry import get_model  # noqa: F401 (jit cache warm)

        # double buffering (SURVEY §2.3 P3): window N+1's host→device
        # transfer is staged (JAX transfers are async) before window N is
        # scored, so the feed overlaps the compute. FIFO order is kept —
        # the temporal model's memory threading depends on it. The same
        # discipline covers the vmapped GROUP path: a group is staged
        # (arena stack + transfer + async dispatch) and only finished
        # (blocked on) after the next work is staged, so host stacking of
        # group k+1 overlaps device compute of group k.
        # staged: ("one", batch, device arrays) | ("group", batches, out)
        staged: Optional[tuple] = None

        def owed(entry: Optional[tuple]) -> int:
            """Windows a staged entry still owes task_done for."""
            if entry is None:
                return 0
            return 1 if entry[0] == "one" else len(entry[1])

        def record_window(batch, logits) -> None:
            """Per-window accounting + export — the ONE definition both
            the serial and batched paths share (their score parity is a
            tested invariant; two copies of this block could drift).
            Computes the sigmoid ONCE for the score plane and the export
            leg, times the export-ack leg and COMPLETES the window's
            span — the last lifecycle stage, so completion lives here
            and only here. Tenancy (ISSUE 14): the batch's tenant stamp
            routes sketches/drift/top-K to the tenant's OWN plane and
            feeds the per-tenant close→score latency series — the
            isolation gate's p99."""
            t = int(getattr(batch, "tenant", 0))
            part = self.partitions[t]
            self.scored_batches += 1
            self.scored_edges += batch.n_edges
            self.metrics.counter("scored.edges").inc(batch.n_edges)
            plane = self._scores_for(t)
            scores = None
            if plane.enabled or self.score_sink is not None:
                n = batch.n_edges
                scores = (1.0 / (1.0 + np.exp(-logits[:n]))).astype(np.float32)
            # score plane (ISSUE 13): sketch + drift compare + top-K
            # attribution, one vectorized pass per window — BOTH scorer
            # paths (serial and vmapped group) land here, so the plane's
            # accounting is identical under serial and sharded ingest
            if scores is not None and plane.enabled:
                plane.observe_window(batch, scores)
            closed = getattr(batch, "closed_monotonic", None)
            if closed is not None:
                # close→score latency, attributed per tenant (sparse —
                # the series appears with the tenant's first window)
                lat = time_module.monotonic() - closed
                self.metrics.histogram(
                    f"latency.close_to_score_s.t{t}", sparse=True
                ).observe(lat)
                if self.score_observer is not None:
                    # harness hook (replay/tenants.py): exact per-window
                    # latencies — histogram rungs are factor-2 banded,
                    # too coarse for a ±10% isolation gate
                    try:
                        self.score_observer(batch, t, lat)
                    except Exception as exc:  # alazlint: disable=ALZ043 -- telemetry hook, not a row holder: the window's rows continue to the export leg below; a raising observer costs its own sample only
                        log.warning(f"score observer failed: {exc!r}")
            te0 = time_module.perf_counter()
            if self.score_sink is not None:
                annotated = self._annotate(batch, scores, part.interner)
                if len(annotated):
                    self.score_sink(annotated)
            part.tracer.observe(
                batch.window_start_ms, "export",
                time_module.perf_counter() - te0,
            )
            part.tracer.complete(batch.window_start_ms)

        def score_one(batch, graph) -> None:
            """Score one window; always settles its task_done."""
            try:
                t0 = time_module.perf_counter()
                self.score_dispatches += 1
                with self._bucket_ctx(batch):
                    out = self._score_fn(self.model_state, graph)
                    logits = np.asarray(out["edge_logits"])
                if "attn_clamp_saturation" in out:
                    # GAT logit-clamp saturation (models/gat.py layer_fn):
                    # nonzero means trained logits are hitting ±30 and the
                    # softmax is flattening — the fixed-clamp assumption
                    # needs revisiting if this climbs
                    self.metrics.gauge("model.attn_clamp_saturation").set(
                        float(out["attn_clamp_saturation"])
                    )
                dt = time_module.perf_counter() - t0
                self._scorer_busy_s += dt
                self._tracer_for(batch).observe(batch.window_start_ms, "score", dt)
                # device plane: the same duration, attributed per bucket
                self.device.observe_score(batch, dt)
                record_window(batch, logits)
            finally:
                self.window_queue.task_done()

        def stage_group(batches) -> tuple:
            """Stage same-bucket windows for ONE vmapped dispatch: stack
            into a reused host arena (StagingArenas — no per-group
            allocation), start the host→device transfer and dispatch the
            vmapped score fn WITHOUT blocking on its result — the caller
            holds the returned staged entry and finishes it after the
            next work is staged, so the device computes this group while
            the host stacks the next one. Only ever fed an
            already-queued backlog, so it adds no latency over scoring
            serially — it removes per-dispatch overhead (ARCHITECTURE
            §3e). Partial groups are PADDED to the next power of two,
            CLAMPED to batch_windows (duplicating the last window, its
            logits discarded): compiled shapes per bucket are the powers
            of two up to the cap plus the cap itself when it isn't one
            (W=6 → {2,4,6}) — never a serving-time recompile per backlog
            size (the TGN memory pre-sizing policy) — while padding
            waste stays under 2×. On failure it settles every window's
            task_done itself (the accounting guarantee the serial path's
            try/except gives a single window)."""
            try:
                t0 = time_module.perf_counter()
                self.score_dispatches += 1
                # cross-tenant batching (ISSUE 14): the group was packed
                # purely by bucket shape — windows from different fleets
                # share one arena fill and one dispatch
                if len({int(getattr(b, "tenant", 0)) for b in batches}) > 1:
                    self.multi_tenant_groups += 1
                # layout selection (ISSUE 20): the scorer's ModelConfig
                # decides the pytree — under "blocked" every window ships
                # its (already close-time-computed) extents, and the
                # arenas pick the column up generically from cols[0]
                cols = [
                    b.device_arrays(self.config.model.edge_layout)
                    for b in batches
                ]
                target = 1
                while target < len(cols):
                    target *= 2
                # never exceed the operator's cap: batch_windows may be
                # sized to device memory at the largest bucket, and a
                # non-power-of-two cap must not round up past itself
                target = min(target, self._batch_windows)
                if len(cols) < target:
                    cols = cols + [cols[-1]] * (target - len(cols))
                arena = self._stage_arenas.fill(
                    (batches[0].n_pad, batches[0].e_pad), cols
                )
                t_arena = time_module.perf_counter()
                with self._bucket_ctx(batches[0]):
                    if self._host_score:
                        # host scorer: the arena IS the stacked input —
                        # no device transfer exists to dispatch
                        stacked = arena
                    else:
                        stacked = {k: jnp.asarray(v) for k, v in arena.items()}
                    t_xfer = time_module.perf_counter()
                    stage_s = t_xfer - t0
                    out = self._score_many_fn(self.model_state, stacked)
                self._scorer_busy_s += time_module.perf_counter() - t0
                # the whole group staged in one arena fill + transfer:
                # each member's span carries the shared staging time
                # (critical-path semantics — observe keeps the max)
                for b in batches:
                    self._tracer_for(b).observe(b.window_start_ms, "stage", stage_s)
                    # occupancy per REAL window — the group's
                    # power-of-two padding re-ships the last member's
                    # columns, but that's a dispatch artifact (its
                    # logits are discarded), not a staged window
                    self.device.observe_staged(b)
                # one dispatch: arena fill vs transfer split + the bytes
                # the whole stacked group shipped
                self.device.observe_transfer(
                    sum(v.nbytes for v in arena.values()),
                    t_arena - t0,
                    t_xfer - t_arena,
                )
                return ("group", batches, out)
            except BaseException:
                for _ in batches:
                    self.window_queue.task_done()
                raise

        def finish_group(batches, out) -> None:
            """Block on a staged group's logits, record every window;
            always settles the group's task_dones."""
            try:
                t0 = time_module.perf_counter()
                logits = np.asarray(out["edge_logits"])
                if "attn_clamp_saturation" in out:
                    self.metrics.gauge("model.attn_clamp_saturation").set(
                        float(np.max(np.asarray(out["attn_clamp_saturation"])))
                    )
                dt = time_module.perf_counter() - t0
                self._scorer_busy_s += dt
                for i, batch in enumerate(batches):
                    # shared device time for the vmapped group — each
                    # window's `score` stage carries the group dispatch
                    self._tracer_for(batch).observe(batch.window_start_ms, "score", dt)
                    self.device.observe_score(batch, dt)
                    record_window(batch, logits[i])
            finally:
                for _ in batches:
                    self.window_queue.task_done()

        def finish(entry: tuple) -> None:
            """Finish any staged entry (serial window or vmapped group).
            Settles the entry's own accounting in all cases."""
            if entry[0] == "one":
                score_one(entry[1], entry[2])
            else:
                finish_group(entry[1], entry[2])

        # carry: a popped window whose bucket broke a micro-batch group;
        # it owes a task_done until scored or the worker dies
        carry: Optional[GraphBatch] = None
        try:
            while not self._stop.is_set():
                if carry is not None:
                    batch, carry = carry, None
                else:
                    item = self.window_queue.get(timeout=0.05)
                    if item is None:
                        if staged is not None:  # idle: don't hold work
                            prev, staged = staged, None
                            finish(prev)
                        continue
                    (batch,) = item
                if self._score_fn is None or self.model_state is None:
                    # scoring disabled ⟺ no model_state ⟺ the tracer
                    # completes spans at emit, on the CLOSING thread —
                    # which may still be between on_batch and emit for
                    # this very window. Do NOT discard here: the drive
                    # test caught that racing it destroys the span
                    # before emit can complete it.
                    self.window_queue.task_done()
                    continue
                # backlog micro-batching (config.score_batch_windows):
                # drain ALREADY-QUEUED same-bucket windows — a current
                # scorer finds none (group of 1) and keeps the serial
                # path's double-buffered staging; a backlog collapses
                # into one vmapped dispatch
                group = [batch]
                if self._score_many_fn is not None:
                    key = (batch.n_pad, batch.e_pad)
                    while len(group) < self._batch_windows:
                        nxt = self.window_queue.get(timeout=0)
                        if nxt is None:
                            break
                        (b2,) = nxt
                        if (b2.n_pad, b2.e_pad) != key:
                            carry = b2  # scored next iteration
                            break
                        group.append(b2)
                if len(group) > 1:
                    # stage the group (its dispatch runs on device while
                    # we drain the older staged work), THEN finish the
                    # older entry — sink/record order stays FIFO because
                    # finishing happens in stage order. stage_group
                    # settles the group's accounting itself on failure;
                    # if finishing the older entry raises instead, the
                    # worker's finally settles the newly staged group.
                    new = stage_group(group)
                    prev, staged = staged, new
                    if prev is not None:
                        finish(prev)
                    continue
                try:
                    t0 = time_module.perf_counter()
                    # host prep (lazy node_deg fill etc.) vs transfer
                    # dispatch: the serial path's arena analog is the
                    # device_arrays() call — same decomposition the
                    # group path gets from its arena fill
                    cols = batch.device_arrays(self.config.model.edge_layout)
                    t_arena = time_module.perf_counter()
                    with self._bucket_ctx(batch):
                        if self._host_score:
                            graph = cols  # numpy stays numpy, no transfer
                        else:
                            graph = {k: jnp.asarray(v) for k, v in cols.items()}
                    t_xfer = time_module.perf_counter()
                    dt = t_xfer - t0
                    self._scorer_busy_s += dt
                    self._tracer_for(batch).observe(batch.window_start_ms, "stage", dt)
                    self.device.observe_staged(batch)
                    self.device.observe_transfer(
                        sum(v.nbytes for v in cols.values()),
                        t_arena - t0,
                        t_xfer - t_arena,
                    )
                except Exception:
                    # the popped window still owes its accounting
                    self.window_queue.task_done()
                    raise
                prev, staged = staged, ("one", batch, graph)
                if prev is not None:
                    finish(prev)  # finishes N; N+1's transfer in flight
            if staged is not None:
                prev, staged = staged, None
                finish(prev)
        finally:
            # worker dying (or stopping) with work still staged or
            # carried: settle its accounting so drain() doesn't burn its
            # timeout
            for _ in range(owed(staged)):
                self.window_queue.task_done()
            if carry is not None:
                self.window_queue.task_done()

    def _tracer_for(self, batch: GraphBatch):
        """The span tracer owning this batch's window: its emitting
        partition's (window ids collide across tenants — same wall
        clock, different fleets — so spans must stay partitioned)."""
        return self.partitions[int(getattr(batch, "tenant", 0))].tracer

    def _scores_for(self, tenant: int) -> ScorePlane:
        """The tenant's score plane, created lazily at its first scored
        window (scorer thread only — the single writer of the plane
        map). Per-tenant planes register under a ``.t<k>`` suffix so an
        idle tenant never renders zeros; K=1 keeps the eager unsuffixed
        singleton, bit-identical to the pre-tenancy plane."""
        plane = self._score_planes.get(tenant)
        if plane is None:
            tcfg = self._trace_cfg
            plane = ScorePlane(
                metrics=self.metrics,
                recorder=self.recorder,
                enabled=self._scores_enabled,
                model=self.config.model.model,
                metric_suffix=f".t{tenant}",
                drift_windows=tcfg.score_drift_windows,
                top_k=tcfg.score_top_k,
                resolve=self.partitions[tenant].interner.lookup,
            )
            with self._planes_lock:
                self._score_planes[tenant] = plane
        return plane

    def tenant_scores(self, tenant: int) -> Optional[ScorePlane]:
        """Read-side accessor: the tenant's plane if it has scored at
        least one window (None before — absent, not empty)."""
        return self._score_planes.get(tenant)

    def score_planes(self) -> dict:
        """Read-side copy of the per-tenant plane map ({tenant id →
        ScorePlane}) — the /scores surface for K > 1; tenants that
        have not scored are absent."""
        return dict(self._score_planes)

    def _bucket_ctx(self, batch: GraphBatch):
        """Compile-attribution context (ISSUE 11): XLA compiles fired
        while staging/scoring ``batch`` — synchronously, on this
        thread — tag with its shape bucket in the recorder trail."""
        if self.compile_plane is None:
            return contextlib.nullcontext()
        return self.compile_plane.bucket(bucket_key(batch))

    def _annotate(
        self,
        batch: GraphBatch,
        scores: np.ndarray,
        interner: Optional[Interner] = None,
    ) -> ScoreBatch:
        """Columnar edge annotation: no per-edge Python objects on the
        return leg — the annotate path must sustain bench-rate edge
        throughput (the export backend resolves strings per unique node
        at serialization time). ``scores`` are the window's [0,1] edge
        scores, computed ONCE in record_window and shared with the
        score plane. ``interner`` is the EMITTING tenant's namespace —
        resolving one fleet's uids against another's table would
        annotate the wrong services."""
        keep = np.flatnonzero(scores >= self.score_threshold)
        uids = batch.node_uids
        return ScoreBatch(
            window_start_ms=batch.window_start_ms,
            from_uid=uids[batch.edge_src[keep]],
            to_uid=uids[batch.edge_dst[keep]],
            protocol=batch.edge_type[keep],
            score=scores[keep],
            interner=interner if interner is not None else self.interner,
        )

    def degraded_snapshot(self) -> dict:
        """One dict answering "what is this node losing and why": the
        per-cause drop ledger, worker restarts, merge-wave age and the
        export circuit state. Wire it to HealthChecker(degraded_snapshot=)
        so every health PUT carries it — the observable that turns
        "windows stopped arriving" from a mystery into a diagnosis."""
        out: dict = {"ledger": self.ledger.snapshot()}
        if self.scores is not None and self.scores.enabled:
            # drift state rides the health payload (ISSUE 13): a node
            # whose score distribution moved says so in every PUT, next
            # to what it is losing
            s = self.scores.snapshot()
            out["scores"] = {
                "drift_state": s["drift"]["state"],
                "psi": s["drift"]["psi"],
                "drift_events": s["drift"]["events"],
                "rebaselines": s["drift"]["rebaselines"],
                "windows": s["windows"],
            }
        if self.refused_ledger.total:
            # frames refused for unknown tenant ids — kept OUT of every
            # tenant's conservation books, surfaced on their own
            out["refused"] = self.refused_ledger.snapshot()
        if self.tenants > 1:
            # per-tenant breakdown (ISSUE 14): which FLEET is losing
            # rows / drifting — the isolation diagnosis, in every PUT
            out["tenants"] = self.tenants_snapshot(full=False)
        if self.sharded is not None:
            out["worker_restarts"] = self.sharded.worker_restarts
            out["last_wave_age_s"] = round(self.sharded.last_wave_age_s, 3)
            out["shard_backlog"] = self.sharded.unfinished
            if hasattr(self.sharded, "ring_stats"):
                # process backend (ISSUE 15): per-worker ring occupancy
                # and respawn generations — which shard is behind, and
                # whether its process has been dying
                out["shm_rings"] = self.sharded.ring_stats()
        be = self._export_backend
        if be is not None and getattr(be, "ledger", None) is not None:
            # the export leg's OWN ledger (breaker sheds) — reported
            # beside, never summed into, the pipeline ledger above
            out["export_ledger"] = be.ledger.snapshot()
        if be is not None and hasattr(be, "breaker"):
            out["breaker"] = {
                "state": be.breaker.state,
                "opens": be.breaker.opens,
                "shorted": be.breaker.shorted,
            }
        return out

    def tenants_snapshot(self, full: bool = True) -> dict:
        """Per-tenant breakdown (ISSUE 14): ledger, windows, queue lag
        (``full``) and — for tenants that have scored — drift state.
        Keys are tenant ids as strings (JSON-stable)."""
        out: dict = {}
        planes = dict(self._score_planes)  # GIL-atomic copy; scorer writes
        for part in self.partitions:
            if full:
                entry = part.snapshot()
            else:
                entry = {
                    "ledger": part.ledger.snapshot(),
                    "windows_closed": part.windows_closed,
                }
            plane = planes.get(part.tenant)
            if plane is not None and plane.enabled:
                s = plane.snapshot()
                entry["scores"] = {
                    "drift_state": s["drift"]["state"],
                    "psi": s["drift"]["psi"],
                    "drift_events": s["drift"]["events"],
                    "rebaselines": s["drift"]["rebaselines"],
                    "windows": s["windows"],
                }
            out[str(part.tenant)] = entry
        return out

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        # one consumer set per tenant partition (isolation: tenant A's
        # queue backlog stalls only tenant A's workers), ONE scorer and
        # ONE housekeeping thread for the fleet
        workers = []
        for part in self.partitions:
            sfx = f"-t{part.tenant}" if part.tenant else ""
            workers += [
                (f"alaz-l7{sfx}", self._l7_worker, (part,)),
                (f"alaz-tcp{sfx}", self._tcp_worker, (part,)),
                (f"alaz-proc{sfx}", self._proc_worker, (part,)),
                (f"alaz-k8s{sfx}", self._k8s_worker, (part,)),
            ]
        workers += [
            ("alaz-scorer", self._scorer_worker, ()),
            ("alaz-housekeeping", self._housekeeping_worker, ()),
        ]
        for name, fn, args in workers:
            t = threading.Thread(target=fn, args=args, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        log.info("service started")

    def pause(self) -> None:
        """Backend-commanded stop (the payment-required protocol)."""
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def drain(self, timeout_s: float = 10.0) -> None:
        """Wait until every submitted batch is fully processed, including
        batches a worker has popped but not finished (``unfinished`` counts
        those; plain queue-emptiness would race ``flush_windows``)."""
        import time

        deadline = time.monotonic() + timeout_s
        queues = [self.window_queue]
        for part in self.partitions:
            queues.extend(part.queues)
        while time.monotonic() < deadline:
            if all(q.unfinished == 0 for q in queues):
                # the sharded pipelines have their own in-flight queues
                # behind the partition queues; they must drain too
                if any(
                    getattr(p.aggregator, "unfinished", 0)
                    for p in self.partitions
                ):
                    time.sleep(0.02)
                    continue
                if all(
                    p.aggregator.pending_retries == 0 for p in self.partitions
                ):
                    return
                # flush due retries so the final window sees them; not-due
                # entries come due within a few 20ms backoff periods
                self._flush_retries_counted()
            time.sleep(0.02)

    def _flush_retries_counted(self) -> None:
        import time

        for part in self.partitions:
            out = part.aggregator.flush_retries(time.time_ns())
            if out is not None and out.shape[0]:
                self.metrics.counter("edges.out").inc(int(out.shape[0]))

    def flush_windows(self) -> None:
        for part in self.partitions:
            part.graph_store.flush()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        for part in self.partitions:
            part.stop()
        if self.compile_plane is not None:
            # detach the jax-logger capture and restore log_compiles
            self.compile_plane.stop()
        log.info(f"service stopped; metrics={self.metrics.snapshot()}")
