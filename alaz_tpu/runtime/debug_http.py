"""Debug/observability HTTP server — the pprof-on-:8181 +
node-exporter-on-:8182 analog (main.go:25,160; backend.go:1038-1105).

Endpoints:
- ``/metrics``          Prometheus text (service counters/gauges + devices
                        + ``latency.*`` stage histograms, ISSUE 9; the
                        per-bucket ``latency.score_s.*`` /
                        ``device.occupancy.*`` series and ``compile.*``
                        counters, ISSUE 11)
- ``/healthz``          liveness
- ``/stats``            JSON snapshot (queue lag, aggregator stats,
                        per-stage latency percentiles, recorder counters,
                        and the per-bucket device breakdown:
                        score percentiles, occupancy, pad waste,
                        stage arena/transfer split, compile events)
- ``/scores``           score-plane snapshot (ISSUE 13): per-model
                        distribution sketch percentiles, last-window
                        summary, drift state/PSI/rebaselines; 404 when
                        the plane is disabled (absent-not-zero)
- ``/scores/top?windows=N``  top-K anomaly attribution ledger: the K
                        highest-scoring nodes of the last N windows with
                        feature z-scores + top contributing in-edges;
                        bounded by the ledger ring however large N
- ``/recorder``         flight-recorder dump (alaz_tpu/obs): the last-N
                        structured runtime events, oldest→newest
- ``/stack``            all-thread stack dump (goroutine-profile analog)
- ``/profile?seconds=N``  on-demand bounded ``jax.profiler.trace`` deep
                        dive (ISSUE 11): single-flight (409 on overlap),
                        clamped to ``PROFILE_MAX_SECONDS``, CPU-safe;
                        the trace dir comes back in the JSON response
- ``/profiler/start``   begin an unbounded JAX profiler trace
                        (``/profiler/stop`` ends; the manual twin of
                        ``/profile`` for attach-and-watch sessions)
"""

from __future__ import annotations

import io
import json
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.debug")


class DebugServer:
    def __init__(self, service, host: str = "127.0.0.1", port: int = 8181):
        self.service = service
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._trace_dir: Optional[str] = None
        # /profile single-flight guard: jax's profiler is process-global
        # (start_trace raises on nesting), so overlapping requests must
        # 409, not crash the handler thread mid-trace
        self._profile_mu = threading.Lock()
        self._profiling = False  # guarded-by: self._profile_mu

    def start(self) -> int:
        svc = self.service
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, body: str, ctype: str = "text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, "ok")
                elif self.path == "/metrics":
                    self._send(200, svc.metrics.render_prometheus())
                elif self.path == "/stats":
                    stats = {
                        "queues": {
                            q.name: q.stats()
                            for q in (svc.l7_queue, svc.tcp_queue, svc.proc_queue, svc.k8s_queue)
                        },
                        "aggregator": svc.aggregator.stats.as_dict(),
                        "scored_batches": svc.scored_batches,
                        "scored_edges": svc.scored_edges,
                    }
                    tracer = getattr(svc, "tracer", None)
                    if tracer is not None:
                        # per-stage latency percentiles (ISSUE 9): the
                        # "where did window W spend its 0.6s" answer
                        stats["stage_latency"] = tracer.stage_snapshot()
                        stats["spans"] = {
                            "live": tracer.live_count,
                            "completed": tracer.completed,
                            "evicted": tracer.evicted,
                        }
                    device = getattr(svc, "device", None)
                    if device is not None and hasattr(device, "snapshot"):
                        # per-bucket breakdown (ISSUE 11) next to
                        # stage_latency: score percentiles, occupancy,
                        # pad waste, arena/transfer split
                        stats["device"] = device.snapshot()
                    plane = getattr(svc, "compile_plane", None)
                    if plane is not None:
                        stats["compile"] = plane.snapshot()
                    score_plane = getattr(svc, "scores", None)
                    if score_plane is not None and score_plane.enabled:
                        # drift + distribution summary next to the
                        # device breakdown (ISSUE 13); the full ledger
                        # stays on /scores/top
                        stats["scores"] = score_plane.snapshot()
                    recorder = getattr(svc, "recorder", None)
                    if recorder is not None:
                        stats["recorder"] = {
                            "recorded": recorder.recorded,
                            "overwritten": recorder.overwritten,
                            "capacity": recorder.capacity,
                        }
                    if getattr(svc, "tenants", 1) > 1:
                        # per-tenant breakdown (ISSUE 14): queue lag,
                        # ledger, windows and drift state per fleet —
                        # the isolation diagnosis surface
                        stats["tenants"] = svc.tenants_snapshot()
                    self._send(200, json.dumps(stats, indent=2), "application/json")
                elif self.path == "/scores":
                    plane = getattr(svc, "scores", None)
                    if getattr(svc, "tenants", 1) > 1:
                        # multi-tenant service: per-tenant planes (ISSUE
                        # 14), keyed by tenant id; a tenant absent from
                        # the dict has not scored a window yet
                        if not getattr(svc, "_scores_enabled", False):
                            self._send(404, "score plane disabled")
                        else:
                            self._send(
                                200,
                                json.dumps(
                                    {
                                        "tenants": {
                                            str(t): p.snapshot()
                                            for t, p in sorted(
                                                svc.score_planes().items()
                                            )
                                            if p.enabled
                                        }
                                    },
                                    indent=2,
                                ),
                                "application/json",
                            )
                    elif plane is None or not plane.enabled:
                        # absent-not-zero (ISSUE 13): a disabled plane
                        # has no surface, it does not serve empty JSON
                        self._send(404, "score plane disabled")
                    else:
                        self._send(
                            200,
                            json.dumps(plane.snapshot(), indent=2),
                            "application/json",
                        )
                elif self.path == "/scores/top" or self.path.startswith(
                    "/scores/top?"
                ):
                    from urllib.parse import parse_qs, urlparse

                    # one parse for every query param this endpoint reads
                    qs = parse_qs(urlparse(self.path).query)
                    plane = getattr(svc, "scores", None)
                    if getattr(svc, "tenants", 1) > 1:
                        # ?tenant=T selects the fleet's ledger (default
                        # 0 — the primary tenant); 404 until that
                        # tenant has scored a window (absent-not-zero)
                        try:
                            tid = int(qs.get("tenant", ["0"])[0])
                        except ValueError:
                            self._send(
                                400,
                                '{"error": "tenant must be an integer"}',
                                "application/json",
                            )
                            return
                        plane = svc.tenant_scores(tid)
                    if plane is None or not plane.enabled:
                        self._send(404, "score plane disabled")
                        return
                    raw = qs.get("windows", ["1"])[0]
                    # malformed params 400 BEFORE any side effect (the
                    # /profile discipline); the ledger ring bounds the
                    # response however large the ask
                    try:
                        windows = int(raw)
                    except ValueError:
                        self._send(
                            400,
                            '{"error": "windows must be an integer"}',
                            "application/json",
                        )
                        return
                    if windows < 0:
                        self._send(
                            400,
                            '{"error": "windows must be >= 0"}',
                            "application/json",
                        )
                        return
                    self._send(
                        200,
                        json.dumps(plane.top_snapshot(windows), indent=2),
                        "application/json",
                    )
                elif self.path == "/recorder":
                    recorder = getattr(svc, "recorder", None)
                    if recorder is None:
                        self._send(404, "no flight recorder attached")
                    else:
                        self._send(
                            200,
                            json.dumps(recorder.dump(), indent=2),
                            "application/json",
                        )
                elif self.path == "/stack":
                    buf = io.StringIO()
                    frames = getattr(threading, "_current_frames", lambda: {})()
                    import sys

                    for tid, frame in sys._current_frames().items():
                        buf.write(f"--- thread {tid} ---\n")
                        traceback.print_stack(frame, file=buf)
                    self._send(200, buf.getvalue())
                elif self.path == "/profiler/start":
                    self._send(200, outer._profiler_start())
                elif self.path == "/profiler/stop":
                    self._send(200, outer._profiler_stop())
                elif self.path == "/profile" or self.path.startswith("/profile?"):
                    import math
                    from urllib.parse import parse_qs, urlparse

                    qs = parse_qs(urlparse(self.path).query)
                    try:
                        seconds = float(qs.get("seconds", ["1.0"])[0])
                    except ValueError:
                        seconds = float("nan")
                    # nan slips through float() AND the min/max clamp
                    # (NaN comparisons are all False, so min/max keep
                    # it) — reject anything non-finite up front
                    if not math.isfinite(seconds):
                        self._send(400, '{"error": "seconds must be a finite number"}',
                                   "application/json")
                        return
                    code, body = outer._profile(seconds)
                    self._send(code, body, "application/json")
                else:
                    self._send(404, "not found")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port  # resolves port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever, name="alaz-debug-http", daemon=True)
        self._thread.start()
        log.info(f"debug http on {self.host}:{self.port}")
        return self.port

    def _profile(self, seconds: float) -> tuple:
        """On-demand bounded deep dive (ISSUE 11): one
        ``jax.profiler.trace`` of ``seconds`` (clamped to the
        ``PROFILE_MAX_SECONDS`` bound — the endpoint must never wedge a
        debug thread or fill a disk indefinitely), single-flight against
        itself AND the manual ``/profiler/start`` session. Returns
        ``(http status, json body)``."""
        import json as json_mod
        import tempfile
        import time

        try:
            import jax
        except ImportError:
            return 501, json_mod.dumps({"error": "jax unavailable on this image"})
        cfg = getattr(self.service, "config", None)
        max_s = float(getattr(getattr(cfg, "trace", None), "profile_max_s", 30.0))
        requested = seconds
        seconds = min(max(seconds, 0.05), max_s)
        with self._profile_mu:
            if self._profiling or self._trace_dir is not None:
                return 409, json_mod.dumps(
                    {"error": "a profiler trace is already running; "
                              "retry when it completes"}
                )
            self._profiling = True
        try:
            # retention: a polled endpoint must not grow /tmp without
            # bound — PROFILE_MAX_SECONDS bounds one request, this
            # bounds the fleet of them. Oldest dirs beyond the newest
            # few are pruned before each new trace. Pid-scoped prefix:
            # the single-flight lock is per-process, so pruning must
            # never touch a sibling process's still-being-written trace.
            self._prune_profile_dirs(keep=4)
            out_dir = tempfile.mkdtemp(prefix=self._profile_prefix())
            recorder = getattr(self.service, "recorder", None)
            if recorder is not None:
                # deep dives leave a trail: an operator reading the
                # flight recorder sees WHEN the profiler perturbed things
                recorder.record("profile", seconds=seconds, trace_dir=out_dir)
            t0 = time.perf_counter()
            with jax.profiler.trace(out_dir):
                time.sleep(seconds)
            wall = time.perf_counter() - t0
            return 200, json_mod.dumps(
                {
                    "trace_dir": out_dir,
                    "seconds": seconds,
                    "requested_seconds": requested,
                    "wall_s": round(wall, 3),
                }
            )
        except Exception as exc:  # noqa: BLE001 - surface, don't kill the server
            return 500, json_mod.dumps({"error": repr(exc)})
        finally:
            with self._profile_mu:
                self._profiling = False

    @staticmethod
    def _profile_prefix() -> str:
        """Pid-scoped /profile trace-dir prefix: pruning is guarded by
        a per-process lock, so it may only ever see THIS process's
        dirs — a sibling service's in-flight trace is untouchable."""
        import os

        return f"alaz-profile-{os.getpid()}-"

    @classmethod
    def _prune_profile_dirs(cls, keep: int) -> None:
        """Delete all but the ``keep`` newest completed /profile trace
        dirs of THIS process (incl. empty dirs a failed trace left)."""
        import glob
        import os
        import shutil
        import tempfile

        dirs = glob.glob(
            os.path.join(tempfile.gettempdir(), cls._profile_prefix() + "*")
        )
        dirs.sort(key=lambda d: os.path.getmtime(d) if os.path.exists(d) else 0)
        for d in dirs[: max(0, len(dirs) - keep)]:
            shutil.rmtree(d, ignore_errors=True)

    def _profiler_start(self) -> str:
        import os
        import tempfile

        import jax

        # reserve-then-start: the guard check and the _trace_dir claim
        # happen in ONE critical section (a check-then-act split let two
        # concurrent starts both pass and the loser's start_trace raise
        # uncaught through the handler); the profiler call itself runs
        # outside the lock, and a failure releases the reservation
        d = tempfile.mkdtemp(prefix="alaz-jax-trace-")
        with self._profile_mu:
            if self._trace_dir is not None:
                os.rmdir(d)
                return f"already tracing to {self._trace_dir}"
            if self._profiling:
                os.rmdir(d)
                return "a /profile deep dive is running; retry when it completes"
            self._trace_dir = d
        try:
            jax.profiler.start_trace(d)
        except Exception as exc:  # noqa: BLE001 - report, don't kill the handler
            with self._profile_mu:
                self._trace_dir = None
            return f"profiler start failed: {exc!r}"
        return f"tracing to {d}"

    def _profiler_stop(self) -> str:
        import jax

        # the claim is released only AFTER a successful stop: a failed
        # stop_trace leaves the process-global profiler RUNNING, so the
        # guard must keep saying "tracing" or no later request could
        # ever stop it (review finding — the old clear-then-stop wedged
        # the profiler until process restart). stop_trace under the
        # mutex also keeps a racing /profile or /profiler/start from
        # claiming the slot mid-stop.
        with self._profile_mu:
            if self._trace_dir is None:
                return "not tracing"
            try:
                jax.profiler.stop_trace()
            except Exception as exc:  # noqa: BLE001 - report, keep retryable
                return f"profiler stop failed (still tracing, retry): {exc!r}"
            out, self._trace_dir = self._trace_dir, None
        return f"trace written to {out}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
