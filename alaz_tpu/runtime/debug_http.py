"""Debug/observability HTTP server — the pprof-on-:8181 +
node-exporter-on-:8182 analog (main.go:25,160; backend.go:1038-1105).

Endpoints:
- ``/metrics``          Prometheus text (service counters/gauges + devices
                        + ``latency.*`` stage histograms, ISSUE 9)
- ``/healthz``          liveness
- ``/stats``            JSON snapshot (queue lag, aggregator stats,
                        per-stage latency percentiles, recorder counters)
- ``/recorder``         flight-recorder dump (alaz_tpu/obs): the last-N
                        structured runtime events, oldest→newest
- ``/stack``            all-thread stack dump (goroutine-profile analog)
- ``/profiler/start``   begin a JAX profiler trace (``/profiler/stop`` ends;
                        trace dir served back in the response)
"""

from __future__ import annotations

import io
import json
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.debug")


class DebugServer:
    def __init__(self, service, host: str = "127.0.0.1", port: int = 8181):
        self.service = service
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._trace_dir: Optional[str] = None

    def start(self) -> int:
        svc = self.service
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, body: str, ctype: str = "text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, "ok")
                elif self.path == "/metrics":
                    self._send(200, svc.metrics.render_prometheus())
                elif self.path == "/stats":
                    stats = {
                        "queues": {
                            q.name: q.stats()
                            for q in (svc.l7_queue, svc.tcp_queue, svc.proc_queue, svc.k8s_queue)
                        },
                        "aggregator": svc.aggregator.stats.as_dict(),
                        "scored_batches": svc.scored_batches,
                        "scored_edges": svc.scored_edges,
                    }
                    tracer = getattr(svc, "tracer", None)
                    if tracer is not None:
                        # per-stage latency percentiles (ISSUE 9): the
                        # "where did window W spend its 0.6s" answer
                        stats["stage_latency"] = tracer.stage_snapshot()
                        stats["spans"] = {
                            "live": tracer.live_count,
                            "completed": tracer.completed,
                            "evicted": tracer.evicted,
                        }
                    recorder = getattr(svc, "recorder", None)
                    if recorder is not None:
                        stats["recorder"] = {
                            "recorded": recorder.recorded,
                            "overwritten": recorder.overwritten,
                            "capacity": recorder.capacity,
                        }
                    self._send(200, json.dumps(stats, indent=2), "application/json")
                elif self.path == "/recorder":
                    recorder = getattr(svc, "recorder", None)
                    if recorder is None:
                        self._send(404, "no flight recorder attached")
                    else:
                        self._send(
                            200,
                            json.dumps(recorder.dump(), indent=2),
                            "application/json",
                        )
                elif self.path == "/stack":
                    buf = io.StringIO()
                    frames = getattr(threading, "_current_frames", lambda: {})()
                    import sys

                    for tid, frame in sys._current_frames().items():
                        buf.write(f"--- thread {tid} ---\n")
                        traceback.print_stack(frame, file=buf)
                    self._send(200, buf.getvalue())
                elif self.path == "/profiler/start":
                    self._send(200, outer._profiler_start())
                elif self.path == "/profiler/stop":
                    self._send(200, outer._profiler_stop())
                else:
                    self._send(404, "not found")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port  # resolves port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever, name="alaz-debug-http", daemon=True)
        self._thread.start()
        log.info(f"debug http on {self.host}:{self.port}")
        return self.port

    def _profiler_start(self) -> str:
        import tempfile

        import jax

        if self._trace_dir is not None:
            return f"already tracing to {self._trace_dir}"
        self._trace_dir = tempfile.mkdtemp(prefix="alaz-jax-trace-")
        jax.profiler.start_trace(self._trace_dir)
        return f"tracing to {self._trace_dir}"

    def _profiler_stop(self) -> str:
        import jax

        if self._trace_dir is None:
            return "not tracing"
        jax.profiler.stop_trace()
        out = self._trace_dir
        self._trace_dir = None
        return f"trace written to {out}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
