"""String interning: the boundary between string-land and array-land.

The reference carries strings (UIDs, pod names, paths, topics) through its
whole pipeline and pays for it in GC pressure — it mitigates with object
pools (datastore/backend.go:767-797). We instead intern every string to a
dense int32 id the moment it enters the system; everything downstream is
integer arrays, and ids become embedding-table rows on device for free.

Id 0 is always the empty string, so zero-initialized arrays mean "no value".
"""

from __future__ import annotations

import threading
from typing import Iterable, List

import numpy as np


class Interner:
    """Thread-safe append-only string <-> int32 table."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._to_id: dict[str, int] = {"": 0}
        self._strings: List[str] = [""]

    def __len__(self) -> int:
        return len(self._strings)

    def intern(self, s: str) -> int:
        sid = self._to_id.get(s)
        if sid is not None:
            return sid
        with self._lock:
            sid = self._to_id.get(s)
            if sid is None:
                sid = len(self._strings)
                self._strings.append(s)
                self._to_id[s] = sid
            return sid

    def intern_many(self, strings: Iterable[str]) -> np.ndarray:
        return np.fromiter((self.intern(s) for s in strings), dtype=np.int32)

    def lookup(self, sid: int) -> str:
        return self._strings[sid]

    def lookup_many(self, ids: np.ndarray) -> List[str]:
        strings = self._strings
        return [strings[i] for i in ids]

    def get(self, s: str) -> int | None:
        """Id if already interned, else None (no allocation)."""
        return self._to_id.get(s)

    def snapshot(self) -> List[str]:
        with self._lock:
            return list(self._strings)
