"""String interning: the boundary between string-land and array-land.

The reference carries strings (UIDs, pod names, paths, topics) through its
whole pipeline and pays for it in GC pressure — it mitigates with object
pools (datastore/backend.go:767-797). We instead intern every string to a
dense int32 id the moment it enters the system; everything downstream is
integer arrays, and ids become embedding-table rows on device for free.

Id 0 is always the empty string, so zero-initialized arrays mean "no value".

The batch APIs (``intern_many`` / ``lookup_many``) are the ingest hot
path: they resolve HITS over *unique* strings without touching the lock,
and take the lock a bounded number of times per batch — once for the
instrumentation counters, once more when there are misses
(O(unique-misses) work under it — one probe per miss, needed only
because another thread may have raced the unlocked resolve phase). The
pre-vectorization one-``intern()``-per-row forms are kept as
``_scalar_*`` references for the equivalence property tests.
"""

from __future__ import annotations

import threading
from operator import itemgetter
from typing import Iterable, List

import numpy as np


class Interner:
    """Thread-safe append-only string <-> int32 table."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._to_id: dict[str, int] = {"": 0}  # guarded-by: self._lock
        self._strings: List[str] = [""]  # guarded-by: self._lock
        # batch-path instrumentation: the perf smoke test asserts the
        # vectorized APIs carried the traffic (no silent per-row fallback)
        self.batch_calls = 0  # guarded-by: self._lock
        self.batch_strings = 0  # guarded-by: self._lock

    def __len__(self) -> int:
        return len(self._strings)  # alazlint: disable=ALZ010 -- racy size gauge; append-only table never shrinks

    def intern(self, s: str) -> int:
        sid = self._to_id.get(s)  # alazlint: disable=ALZ010 -- double-checked fast path: GIL-atomic dict probe, re-checked under the lock below on miss
        if sid is not None:
            return sid
        with self._lock:
            sid = self._to_id.get(s)
            if sid is None:
                sid = len(self._strings)
                self._strings.append(s)
                self._to_id[s] = sid
            return sid

    def intern_many(self, strings: Iterable[str]) -> np.ndarray:
        """Batch intern: one dict probe per unique string outside the
        lock, ONE lock acquisition total (counters fold into the same
        critical section as the miss resolution), one probe per unique
        MISS under it (the race re-check the scalar path pays per
        string). Single-acquisition matters under the sharded ingest
        pool: N workers intern concurrently against this one table, and
        a second counters-only acquisition per batch was measurable
        contention there for zero information."""
        if not isinstance(strings, (list, tuple)):
            strings = list(strings)
        n = len(strings)
        if n == 0:
            # counters still advance: the perf smoke test reads them to
            # prove the batch APIs carried the traffic (+= is a lost-
            # update race off-lock — the ISSUE 2 ALZ010 finding)
            with self._lock:
                self.batch_calls += 1
            return np.zeros(0, dtype=np.int32)
        to_id = self._to_id  # alazlint: disable=ALZ010 -- lock-free resolve phase: GIL-atomic probes of an append-only dict; misses are re-checked under the lock below
        resolved: dict[str, int | None] = {}
        for s in strings:
            if s not in resolved:
                resolved[s] = to_id.get(s)
        misses = [s for s, sid in resolved.items() if sid is None]
        with self._lock:
            self.batch_calls += 1
            self.batch_strings += n
            if misses:
                table = self._strings
                for s in misses:
                    sid = to_id.get(s)
                    if sid is None:
                        sid = len(table)
                        table.append(s)
                        to_id[s] = sid
                    resolved[s] = sid
        return np.fromiter((resolved[s] for s in strings), dtype=np.int32, count=n)

    def _scalar_intern_many(self, strings: Iterable[str]) -> np.ndarray:
        """Pre-vectorization reference (one ``intern`` per row, each with
        its own lock round-trip on miss) — kept for the equivalence tests."""
        return np.fromiter((self.intern(s) for s in strings), dtype=np.int32)

    def lookup(self, sid: int) -> str:
        return self._strings[sid]  # alazlint: disable=ALZ010 -- lock-free read of the append-only table: any published id indexes a row that existed at publication

    def lookup_many(self, ids: np.ndarray) -> List[str]:
        """Batch id → string. ``tolist()`` + ``itemgetter`` keep the loop
        in C — iterating numpy scalars pays a boxing per element."""
        idx = np.asarray(ids).tolist()
        if not idx:
            return []
        if len(idx) == 1:
            return [self._strings[idx[0]]]  # alazlint: disable=ALZ010 -- lock-free read, see lookup()
        return list(itemgetter(*idx)(self._strings))  # alazlint: disable=ALZ010 -- lock-free read, see lookup()

    def _scalar_lookup_many(self, ids: np.ndarray) -> List[str]:
        """Pre-vectorization reference — kept for the equivalence tests."""
        strings = self._strings  # alazlint: disable=ALZ010 -- lock-free read, see lookup()
        return [strings[i] for i in ids]

    def get(self, s: str) -> int | None:
        """Id if already interned, else None (no allocation)."""
        return self._to_id.get(s)  # alazlint: disable=ALZ010 -- GIL-atomic dict probe; a miss during a concurrent insert is indistinguishable from probing a moment earlier

    def snapshot(self) -> List[str]:
        with self._lock:
            return list(self._strings)

    def strings_since(self, start: int) -> List[str]:
        """Rows ``[start, len)`` of the string table — the interner
        DELTA a process-mode shard worker ships at merge so the parent
        can fold its locally-assigned ids into the shared table
        (alaz_tpu/shm id-exchange, ISSUE 15)."""
        with self._lock:
            return self._strings[start:]
