"""Kubernetes resource messages — the k8s/informer.go analog.

The reference's informers emit ``K8sResourceMessage{ResourceType, EventType,
Object}`` (k8s/informer.go:236-240) for 7 resource kinds, with pods fanned
out into per-container CONTAINER messages (k8s/pod.go:48-87). K8s metadata
is low-rate control plane, so unlike the data plane these stay as plain
Python dataclasses; the aggregator folds them into integer lookup tables.

Field sets mirror datastore/dto.go:3-94.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, List, Tuple


class EventType(str, enum.Enum):
    ADD = "Add"
    UPDATE = "Update"
    DELETE = "Delete"


class ResourceType(str, enum.Enum):
    POD = "Pod"
    SERVICE = "Service"
    REPLICASET = "ReplicaSet"
    DEPLOYMENT = "Deployment"
    ENDPOINTS = "Endpoints"
    CONTAINER = "Container"
    DAEMONSET = "DaemonSet"
    STATEFULSET = "StatefulSet"


@dataclass
class Pod:
    uid: str
    name: str = ""
    namespace: str = ""
    image: str = ""  # main container image
    ip: str = ""
    owner_type: str = ""  # "ReplicaSet" or ""
    owner_id: str = ""
    owner_name: str = ""


@dataclass
class Service:
    uid: str
    name: str = ""
    namespace: str = ""
    type: str = ""
    cluster_ip: str = ""
    cluster_ips: List[str] = field(default_factory=list)
    # (name, src, dest, protocol) — dto.go:21-26
    ports: List[Tuple[str, int, int, str]] = field(default_factory=list)


@dataclass
class ReplicaSet:
    uid: str
    name: str = ""
    namespace: str = ""
    owner_type: str = ""
    owner_id: str = ""
    owner_name: str = ""
    replicas: int = 0


@dataclass
class Deployment:
    uid: str
    name: str = ""
    namespace: str = ""
    replicas: int = 0


@dataclass
class DaemonSet:
    uid: str
    name: str = ""
    namespace: str = ""


@dataclass
class StatefulSet:
    uid: str
    name: str = ""
    namespace: str = ""


@dataclass
class AddressIP:
    type: str = ""  # "pod" or "external"
    id: str = ""
    name: str = ""
    namespace: str = ""
    ip: str = ""


@dataclass
class AddressPort:
    port: int = 0
    protocol: str = "TCP"
    name: str = ""


@dataclass
class Address:
    ips: List[AddressIP] = field(default_factory=list)
    ports: List[AddressPort] = field(default_factory=list)


@dataclass
class Endpoints:
    uid: str
    name: str = ""
    namespace: str = ""
    addresses: List[Address] = field(default_factory=list)


@dataclass
class Container:
    name: str
    namespace: str = ""
    pod_uid: str = ""
    image: str = ""
    ports: List[Tuple[int, str]] = field(default_factory=list)


@dataclass
class K8sResourceMessage:
    resource_type: ResourceType
    event_type: EventType
    object: Any
