"""IPv4 helpers.

The reference formats kernel-side __u32 addresses into dotted strings at the
perf-reader boundary (ebpf/tcp_state/tcp.go:209-254) and keys maps by those
strings. We keep addresses as uint32 end to end and only render strings at
the export boundary.
"""

from __future__ import annotations

import socket
import struct

import numpy as np


def ip_to_u32(ip: str) -> int:
    """Dotted-quad -> host-order uint32 (big-endian semantic order)."""
    return struct.unpack("!I", socket.inet_aton(ip))[0]


def u32_to_ip(v: int) -> str:
    return socket.inet_ntoa(struct.pack("!I", int(v)))


def ips_to_u32(ips) -> np.ndarray:
    return np.fromiter((ip_to_u32(ip) for ip in ips), dtype=np.uint32)
