"""Event dtypes and protocol/method enums.

Field sets mirror the reference's userspace event structs so behavior (and
tests) can be compared one-to-one:

- L7 event   : ebpf/l7_req/l7.go:396-421 (``L7Event``)
- TCP event  : ebpf/tcp_state/tcp.go (``TcpConnectEvent``, enum at 20-33)
- Proc event : ebpf/proc/proc.go (``ProcEvent``)

Enum values match the reference's BPF-side constants (l7.go:19-144) so a
recorded trace from either system replays into the other.

Payloads: the reference captures up to 1024 bytes per event (ebpf/c/l7.c:14).
A 1024-byte inline field would make the hot dtype 1KiB/event, so the columnar
schema stores a configurable prefix inline (``MAX_PAYLOAD_SIZE``, default
256 — enough for every parser in protocols/) and the true ``payload_size``.
Trace files that need full fidelity can carry a side array.
"""

from __future__ import annotations

import enum

import numpy as np

MAX_PAYLOAD_SIZE = 256

# ---------------------------------------------------------------------------
# Tenancy (ISSUE 14): the frame header carries a one-byte tenant id so N
# agent fleets can multiplex onto one scoring backend. The byte sits in
# what was header padding (sources/ingest_server.py FRAME_HEADER), so a
# legacy agent — which zero-fills the pad — IS a tenant-0 agent byte for
# byte: every recorded trace replays unchanged. The width is a wire
# contract (alazspec pins it in resources/specs/wire_layouts.json);
# RuntimeConfig.tenants must stay ≤ MAX_TENANTS.
# ---------------------------------------------------------------------------

TENANT_WIRE_BITS = 8
MAX_TENANTS = 1 << TENANT_WIRE_BITS


class L7Protocol(enum.IntEnum):
    """BPF_L7_PROTOCOL_* (l7.go:19-28)."""

    UNKNOWN = 0
    HTTP = 1
    AMQP = 2
    POSTGRES = 3
    HTTP2 = 4
    REDIS = 5
    KAFKA = 6
    MYSQL = 7
    MONGO = 8

    def wire_name(self) -> str:
        return _PROTOCOL_NAMES[int(self)]


_PROTOCOL_NAMES = [
    "UNKNOWN",
    "HTTP",
    "AMQP",
    "POSTGRES",
    "HTTP2",
    "REDIS",
    "KAFKA",
    "MYSQL",
    "MONGO",
]

PROTOCOL_BY_NAME = {n: L7Protocol(i) for i, n in enumerate(_PROTOCOL_NAMES)}


class HttpMethod(enum.IntEnum):
    """BPF_METHOD_* (l7.go:75-85)."""

    UNKNOWN = 0
    GET = 1
    POST = 2
    PUT = 3
    PATCH = 4
    DELETE = 5
    HEAD = 6
    CONNECT = 7
    OPTIONS = 8
    TRACE = 9


class Http2Method(enum.IntEnum):
    UNKNOWN = 0
    CLIENT_FRAME = 1
    SERVER_FRAME = 2


class AmqpMethod(enum.IntEnum):
    UNKNOWN = 0
    PUBLISH = 1
    DELIVER = 2


class PostgresMethod(enum.IntEnum):
    UNKNOWN = 0
    CLOSE_OR_TERMINATE = 1
    SIMPLE_QUERY = 2
    EXTENDED_QUERY = 3


class RedisMethod(enum.IntEnum):
    UNKNOWN = 0
    COMMAND = 1
    PUSHED_EVENT = 2
    PING = 3


class KafkaMethod(enum.IntEnum):
    UNKNOWN = 0
    PRODUCE_REQUEST = 1
    FETCH_RESPONSE = 2


class MySqlMethod(enum.IntEnum):
    UNKNOWN = 0
    TEXT_QUERY = 1
    PREPARE_STMT = 2
    EXEC_STMT = 3
    STMT_CLOSE = 4


class MongoMethod(enum.IntEnum):
    UNKNOWN = 0
    OP_MSG = 1
    OP_COMPRESSED = 2


_METHOD_ENUMS = {
    L7Protocol.HTTP: HttpMethod,
    L7Protocol.HTTP2: Http2Method,
    L7Protocol.AMQP: AmqpMethod,
    L7Protocol.POSTGRES: PostgresMethod,
    L7Protocol.REDIS: RedisMethod,
    L7Protocol.KAFKA: KafkaMethod,
    L7Protocol.MYSQL: MySqlMethod,
    L7Protocol.MONGO: MongoMethod,
}

# String forms as the reference datastore emits them (l7.go:204-325).
_METHOD_STRINGS = {
    (L7Protocol.HTTP, HttpMethod.GET): "GET",
    (L7Protocol.HTTP, HttpMethod.POST): "POST",
    (L7Protocol.HTTP, HttpMethod.PUT): "PUT",
    (L7Protocol.HTTP, HttpMethod.PATCH): "PATCH",
    (L7Protocol.HTTP, HttpMethod.DELETE): "DELETE",
    (L7Protocol.HTTP, HttpMethod.HEAD): "HEAD",
    (L7Protocol.HTTP, HttpMethod.CONNECT): "CONNECT",
    (L7Protocol.HTTP, HttpMethod.OPTIONS): "OPTIONS",
    (L7Protocol.HTTP, HttpMethod.TRACE): "TRACE",
    (L7Protocol.HTTP2, Http2Method.CLIENT_FRAME): "CLIENT_FRAME",
    (L7Protocol.HTTP2, Http2Method.SERVER_FRAME): "SERVER_FRAME",
    (L7Protocol.AMQP, AmqpMethod.PUBLISH): "PUBLISH",
    (L7Protocol.AMQP, AmqpMethod.DELIVER): "DELIVER",
    (L7Protocol.POSTGRES, PostgresMethod.CLOSE_OR_TERMINATE): "CLOSE_OR_TERMINATE",
    (L7Protocol.POSTGRES, PostgresMethod.SIMPLE_QUERY): "SIMPLE_QUERY",
    (L7Protocol.POSTGRES, PostgresMethod.EXTENDED_QUERY): "EXTENDED_QUERY",
    (L7Protocol.REDIS, RedisMethod.COMMAND): "COMMAND",
    (L7Protocol.REDIS, RedisMethod.PUSHED_EVENT): "PUSHED_EVENT",
    (L7Protocol.REDIS, RedisMethod.PING): "PING",
    (L7Protocol.KAFKA, KafkaMethod.PRODUCE_REQUEST): "PRODUCE_REQUEST",
    (L7Protocol.KAFKA, KafkaMethod.FETCH_RESPONSE): "FETCH_RESPONSE",
    (L7Protocol.MYSQL, MySqlMethod.TEXT_QUERY): "TEXT_QUERY",
    (L7Protocol.MYSQL, MySqlMethod.PREPARE_STMT): "PREPARE_STMT",
    (L7Protocol.MYSQL, MySqlMethod.EXEC_STMT): "EXEC_STMT",
    (L7Protocol.MYSQL, MySqlMethod.STMT_CLOSE): "STMT_CLOSE",
    (L7Protocol.MONGO, MongoMethod.OP_MSG): "OP_MSG",
    (L7Protocol.MONGO, MongoMethod.OP_COMPRESSED): "OP_COMPRESSED",
}


def method_to_string(protocol: int, method: int) -> str:
    """Userspace method string, per l7.go:204-325; '' for unknown."""
    return _METHOD_STRINGS.get((L7Protocol(protocol), _coerce(protocol, method)), "")


def _coerce(protocol: int, method: int):
    e = _METHOD_ENUMS.get(L7Protocol(protocol))
    if e is None:
        return method
    try:
        return e(method)
    except ValueError:
        return method


class TcpEventType(enum.IntEnum):
    """BPF_EVENT_TCP_* (tcp.go:20-24); value 0 unused, matching the iota+1."""

    UNKNOWN = 0
    ESTABLISHED = 1
    CONNECT_FAILED = 2
    LISTEN = 3
    LISTEN_CLOSED = 4
    CLOSED = 5


class ProcEventType(enum.IntEnum):
    """EVENT_PROC_EXEC / EVENT_PROC_EXIT (ebpf/proc/proc.go)."""

    UNKNOWN = 0
    EXEC = 1
    EXIT = 2


# ---------------------------------------------------------------------------
# Structured dtypes. Field order groups the hot join keys first.
# ---------------------------------------------------------------------------

L7_EVENT_DTYPE = np.dtype(
    [
        ("pid", np.uint32),
        ("fd", np.uint64),
        ("write_time_ns", np.uint64),  # start time of the write syscall
        ("duration_ns", np.uint64),
        ("protocol", np.uint8),  # L7Protocol
        ("method", np.uint8),  # per-protocol method enum
        ("tls", np.bool_),
        ("failed", np.bool_),
        ("status", np.uint32),
        ("payload_size", np.uint32),
        ("payload_read_complete", np.bool_),
        ("tid", np.uint32),
        ("seq", np.uint32),  # tcp seq (dist tracing; l7.go:410)
        ("kafka_api_version", np.int16),
        ("mysql_prep_stmt_id", np.uint32),
        ("saddr", np.uint32),  # V2 path: addrs straight off the event (data.go:1760)
        ("sport", np.uint16),
        ("daddr", np.uint32),
        ("dport", np.uint16),
        ("event_read_time_ns", np.uint64),
        ("payload", np.uint8, (MAX_PAYLOAD_SIZE,)),
    ]
)

TCP_EVENT_DTYPE = np.dtype(
    [
        ("pid", np.uint32),
        ("fd", np.uint64),
        ("timestamp_ns", np.uint64),
        ("type", np.uint8),  # TcpEventType
        ("saddr", np.uint32),
        ("sport", np.uint16),
        ("daddr", np.uint32),
        ("dport", np.uint16),
    ]
)

PROC_EVENT_DTYPE = np.dtype(
    [
        ("pid", np.uint32),
        ("type", np.uint8),  # ProcEventType
        ("timestamp_ns", np.uint64),
    ]
)


# The wire-visible structured dtypes: everything an out-of-process agent
# serializes byte-for-byte (sources/ingest_server.py frames). alazspec
# pins each one's layout in resources/specs/wire_layouts.json and fails
# tier-1 on drift — the Go-struct-vs-C-struct desync of the reference,
# caught statically (tools/alazspec, ISSUE 4).
WIRE_DTYPES = {
    "L7_EVENT_DTYPE": L7_EVENT_DTYPE,
    "TCP_EVENT_DTYPE": TCP_EVENT_DTYPE,
    "PROC_EVENT_DTYPE": PROC_EVENT_DTYPE,
}


def dtype_layout(dtype: np.dtype, name: str) -> str:
    """Canonical layout string for a structured dtype:
    ``"Name:<itemsize>;<field>:<offset>:<size>;..."`` — byte-compatible
    with the C side's ``alz_abi_record_layout()`` (native/ingest.cc), so
    struct↔dtype parity is one string comparison. Subarray fields (the
    payload prefix) report their total byte span."""
    parts = [f"{name}:{dtype.itemsize}"]
    for field in dtype.names or ():
        ft, off = dtype.fields[field][:2]
        parts.append(f"{field}:{off}:{ft.itemsize}")
    return ";".join(parts)


def make_l7_events(n: int) -> np.ndarray:
    return np.zeros(n, dtype=L7_EVENT_DTYPE)


def make_tcp_events(n: int) -> np.ndarray:
    return np.zeros(n, dtype=TCP_EVENT_DTYPE)


def make_proc_events(n: int) -> np.ndarray:
    return np.zeros(n, dtype=PROC_EVENT_DTYPE)


def set_payloads(events: np.ndarray, payload: bytes) -> None:
    """Set the same payload prefix on every row of an L7 event batch."""
    buf = np.frombuffer(payload[:MAX_PAYLOAD_SIZE], dtype=np.uint8)
    events["payload"][:, : buf.shape[0]] = buf
    events["payload_size"] = len(payload)
