"""Columnar event schemas and string interning.

The reference's ebpf consumers (ebpf/l7_req/l7.go, ebpf/tcp_state/tcp.go,
ebpf/proc/proc.go) turn perf-ring samples into one Go struct per event and
push them down channels one at a time. Here the unit of flow is a **batch**:
a numpy structured array of a fixed dtype per event kind. That choice is the
whole performance story of the host data plane — every downstream stage
(protocol parse, socket join, k8s attribution, graph batching) is a
vectorized operation over these arrays, and the device handoff is a view,
not a million tiny objects.
"""

from alaz_tpu.events.schema import (
    L7_EVENT_DTYPE,
    TCP_EVENT_DTYPE,
    PROC_EVENT_DTYPE,
    L7Protocol,
    HttpMethod,
    Http2Method,
    AmqpMethod,
    PostgresMethod,
    RedisMethod,
    KafkaMethod,
    MySqlMethod,
    MongoMethod,
    TcpEventType,
    ProcEventType,
    MAX_PAYLOAD_SIZE,
    make_l7_events,
    make_tcp_events,
    make_proc_events,
    method_to_string,
)
from alaz_tpu.events.intern import Interner
from alaz_tpu.events.net import ip_to_u32, u32_to_ip, ips_to_u32
from alaz_tpu.events.k8s import (
    EventType,
    ResourceType,
    K8sResourceMessage,
    Pod,
    Service,
    ReplicaSet,
    Deployment,
    DaemonSet,
    StatefulSet,
    Endpoints,
    Container,
)

__all__ = [
    "L7_EVENT_DTYPE",
    "TCP_EVENT_DTYPE",
    "PROC_EVENT_DTYPE",
    "L7Protocol",
    "HttpMethod",
    "Http2Method",
    "AmqpMethod",
    "PostgresMethod",
    "RedisMethod",
    "KafkaMethod",
    "MySqlMethod",
    "MongoMethod",
    "TcpEventType",
    "ProcEventType",
    "MAX_PAYLOAD_SIZE",
    "make_l7_events",
    "make_tcp_events",
    "make_proc_events",
    "method_to_string",
    "Interner",
    "ip_to_u32",
    "u32_to_ip",
    "ips_to_u32",
    "EventType",
    "ResourceType",
    "K8sResourceMessage",
    "Pod",
    "Service",
    "ReplicaSet",
    "Deployment",
    "DaemonSet",
    "StatefulSet",
    "Endpoints",
    "Container",
]
