"""Training/eval harness for the anomaly scorers."""

from alaz_tpu.train.objective import edge_bce_loss
from alaz_tpu.train.trainstep import TrainState, make_train_step, train_on_batches
from alaz_tpu.train.metrics import auroc

__all__ = ["edge_bce_loss", "TrainState", "make_train_step", "train_on_batches", "auroc"]
