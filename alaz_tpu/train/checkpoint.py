"""Checkpoint/resume via orbax — the subsystem SURVEY §5 flags as absent in
the reference ("the agent is stateless") but required here: model params,
optimizer state, step counter, and the TGN node memory all survive
preemption, and the scoring loop restarts from the last saved state.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np

from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.checkpoint")

# Bump when the model's parameter/feature contract changes incompatibly.
# v2: edge-type embeddings moved into edge-feature one-hot slots 7..15
# (type_emb removed; edge_proj rows 7..15 now carry learned type offsets)
# — restoring a v1 checkpoint would silently inject untrained weights.
# v3: edge_feat_znorm=True default appends EDGE_STAT_COLS z-scored
# columns, widening edge_head/edge_proj inputs from edge_feature_dim to
# edge_feat_dim_in — a v2 checkpoint would fail with a dot-dimension
# error only at jit trace time in serve. EDGE_FEAT_ZNORM=0 rebuilds the
# v2-width model, but the version gate still refuses the cross-load
# (params trained with one input representation score garbage under the
# other).
SCHEMA_VERSION = 3


def _manager(directory: str | Path, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        Path(directory).resolve(),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
    )


def feature_contract(model_cfg) -> dict:
    """The shape-determining facts a checkpoint's params are only valid
    under. SCHEMA_VERSION gates code-level contract changes; this gates
    CONFIG-level ones — every ModelConfig.from_env knob that changes a
    param shape (MODEL, HIDDEN_DIM, NUM_LAYERS, EDGE_FEAT_ZNORM) so a
    mismatched serve fails at restore with the fix named, not at jit
    trace with a dot-dimension error. Values must be ints (orbax state
    is numeric): the model name rides as a stable crc32."""
    import zlib

    return {
        "model_crc": zlib.crc32(model_cfg.model.encode()),
        "hidden_dim": int(model_cfg.hidden_dim),
        "num_layers": int(model_cfg.num_layers),
        "edge_feat_dim_in": int(model_cfg.edge_feat_dim_in),
        "edge_feat_znorm": bool(model_cfg.edge_feat_znorm),
    }


def save(
    directory: str | Path,
    step: int,
    params: Any,
    opt_state: Any = None,
    memory: Any = None,
    max_to_keep: int = 3,
    contract: dict | None = None,
) -> None:
    import orbax.checkpoint as ocp

    # 0-d arrays, not numpy scalars: orbax's StandardSave type-checks the
    # tree and rejects bare np.int64 scalars on current releases
    state = {
        "params": params,
        "schema_version": np.asarray(SCHEMA_VERSION, dtype=np.int64),
    }
    if contract:
        state["contract"] = {
            k: np.asarray(v, dtype=np.int64) for k, v in sorted(contract.items())
        }
    if opt_state is not None:
        state["opt_state"] = opt_state
    if memory is not None:
        state["memory"] = memory
    mgr = _manager(directory, max_to_keep)
    mgr.save(step, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    mgr.close()


def restore(
    directory: str | Path,
    step: Optional[int] = None,
    expect_contract: dict | None = None,
) -> tuple[int, dict]:
    """→ (step, state dict). Raises FileNotFoundError when no checkpoint.

    ``expect_contract`` (see :func:`feature_contract`) rejects a
    checkpoint whose saved input representation disagrees with the live
    config — the failure otherwise surfaces as a cryptic dot-dimension
    error at jit trace time in serve."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory)
    try:
        target = step if step is not None else mgr.latest_step()
        if target is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
        # explicit StandardRestore: current orbax managers refuse a bare
        # restore() for items they did not just save (no handler registry)
        state = mgr.restore(target, args=ocp.args.StandardRestore())
        state = jax.tree.map(np.asarray, state)
        found = int(state.pop("schema_version", 1))
        if found != SCHEMA_VERSION:
            raise ValueError(
                f"checkpoint {directory} has schema v{found}, this build "
                f"needs v{SCHEMA_VERSION} (the model feature contract "
                "changed — retrain or convert; restoring would silently "
                "degrade scores)"
            )
        saved_contract = {
            k: int(v) for k, v in (state.pop("contract", None) or {}).items()
        }
        if expect_contract is not None and saved_contract:
            want = {k: int(v) for k, v in sorted(expect_contract.items())}
            if saved_contract != want:
                raise ValueError(
                    f"checkpoint {directory} was trained under feature "
                    f"contract {saved_contract}, this process runs "
                    f"{want} (EDGE_FEAT_ZNORM or feature widths differ "
                    "— retrain, or set the env to match the checkpoint)"
                )
        return int(target), state
    finally:
        mgr.close()


def latest_step(directory: str | Path) -> Optional[int]:
    import orbax.checkpoint as ocp  # noqa: F401

    mgr = _manager(directory)
    try:
        return mgr.latest_step()
    finally:
        mgr.close()
