"""Checkpoint/resume via orbax — the subsystem SURVEY §5 flags as absent in
the reference ("the agent is stateless") but required here: model params,
optimizer state, step counter, and the TGN node memory all survive
preemption, and the scoring loop restarts from the last saved state.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np

from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.checkpoint")

# Bump when the model's parameter/feature contract changes incompatibly.
# v2: edge-type embeddings moved into edge-feature one-hot slots 7..15
# (type_emb removed; edge_proj rows 7..15 now carry learned type offsets)
# — restoring a v1 checkpoint would silently inject untrained weights.
SCHEMA_VERSION = 2


def _manager(directory: str | Path, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        Path(directory).resolve(),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
    )


def save(
    directory: str | Path,
    step: int,
    params: Any,
    opt_state: Any = None,
    memory: Any = None,
    max_to_keep: int = 3,
) -> None:
    import orbax.checkpoint as ocp

    state = {"params": params, "schema_version": np.int64(SCHEMA_VERSION)}
    if opt_state is not None:
        state["opt_state"] = opt_state
    if memory is not None:
        state["memory"] = memory
    mgr = _manager(directory, max_to_keep)
    mgr.save(step, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    mgr.close()


def restore(directory: str | Path, step: Optional[int] = None) -> tuple[int, dict]:
    """→ (step, state dict). Raises FileNotFoundError when no checkpoint."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory)
    try:
        target = step if step is not None else mgr.latest_step()
        if target is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
        state = mgr.restore(target)
        state = jax.tree.map(np.asarray, state)
        found = int(state.pop("schema_version", 1))
        if found != SCHEMA_VERSION:
            raise ValueError(
                f"checkpoint {directory} has schema v{found}, this build "
                f"needs v{SCHEMA_VERSION} (the model feature contract "
                "changed — retrain or convert; restoring would silently "
                "degrade scores)"
            )
        return int(target), state
    finally:
        mgr.close()


def latest_step(directory: str | Path) -> Optional[int]:
    import orbax.checkpoint as ocp  # noqa: F401

    mgr = _manager(directory)
    try:
        return mgr.latest_step()
    finally:
        mgr.close()
