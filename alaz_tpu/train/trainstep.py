"""Jitted train step + a small training loop over GraphBatches.

One compiled program per (model, shape-bucket); batches of the same bucket
reuse the cache. The optimizer is adamw via optax.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Iterable, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from alaz_tpu.config import ModelConfig
from alaz_tpu.graph.snapshot import GraphBatch
from alaz_tpu.models.registry import get_model
from alaz_tpu.train.objective import edge_bce_loss


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int = 0


@functools.lru_cache(maxsize=None)
def _adamw(lr: float, weight_decay: float = 1e-4) -> optax.GradientTransformation:
    """One optimizer object per (lr, weight_decay). optax transforms are
    pure (stateless init/update pairs), so sharing is safe — and the
    cached object is what lets the lru_cache on the step makers hit
    across calls: a fresh ``optax.adamw(...)`` per call is a fresh cache
    key, which re-traces the step from scratch (ALZ070)."""
    return optax.adamw(lr, weight_decay=weight_decay)


@functools.lru_cache(maxsize=None)
def make_train_step(cfg: ModelConfig, optimizer: optax.GradientTransformation, pos_weight: float = 10.0) -> Callable:
    _, apply = get_model(cfg.model)

    @jax.jit
    def train_step(params, opt_state, graph, edge_label):
        def loss_fn(p):
            out = apply(p, graph, cfg)
            return edge_bce_loss(
                out["edge_logits"], edge_label, graph["edge_mask"].astype(jnp.float32), pos_weight
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def train_on_batches(
    cfg: ModelConfig,
    batches: Iterable[GraphBatch],
    epochs: int = 5,
    lr: float = 3e-3,
    pos_weight: float = 10.0,
    seed: int = 0,
) -> tuple[TrainState, List[float]]:
    init, _ = get_model(cfg.model)
    params = init(jax.random.PRNGKey(seed), cfg)
    optimizer = _adamw(lr)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(cfg, optimizer, pos_weight)

    batch_list = list(batches)
    losses: List[float] = []
    n_steps = 0
    for _ in range(epochs):
        for b in batch_list:
            graph = {k: jnp.asarray(v) for k, v in b.device_arrays().items()}
            params, opt_state, loss = step_fn(params, opt_state, graph, jnp.asarray(b.edge_label))
            losses.append(float(loss))
            n_steps += 1
    return TrainState(params=params, opt_state=opt_state, step=n_steps), losses


def _pad_graph_field(name: str, v, n_t: int, e_t: int):
    """Zero/mask-pad one device-array field up to target buckets. Padding
    edges point at the last node slot (keeps the dst-sorted invariant)
    with mask 0, so they contribute nothing."""
    v = np.asarray(v)
    if name.startswith("node_"):
        pad = n_t - v.shape[0]
        widths = ((0, pad),) + ((0, 0),) * (v.ndim - 1)
        return np.pad(v, widths)
    pad = e_t - v.shape[0]
    if pad == 0:
        return v
    if name in ("edge_src", "edge_dst"):
        return np.pad(v, (0, pad), constant_values=n_t - 1)
    widths = ((0, pad),) + ((0, 0),) * (v.ndim - 1)
    return np.pad(v, widths)


@functools.lru_cache(maxsize=None)
def _make_unrolled_step(
    cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    pos_weight: float,
) -> Callable:
    """Jitted whole-unroll update for TGN, cached per (cfg, optimizer,
    pos_weight) so repeated unrolled training runs (the eval matrix
    sweeps models per seed; scenario suites re-train per scenario) share
    one trace cache. The window count and shape bucket ride the jit's
    own cache key through the pytree structure of ``prepped``."""
    from alaz_tpu.models import tgn

    @jax.jit
    def unrolled_step(params, opt_state, prepped, memory0):
        def loss_fn(p):
            total = 0.0
            for graphs, labels in prepped:
                mem = memory0
                seq_total = 0.0
                for g, lbl in zip(graphs, labels):
                    out, mem = tgn.step(p, g, mem, cfg)
                    seq_total = seq_total + edge_bce_loss(
                        out["edge_logits"],
                        lbl,
                        g["edge_mask"].astype(jnp.float32),
                        pos_weight,
                    )
                total = total + seq_total / len(graphs)
            return total / len(prepped)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return unrolled_step


def train_tgn_unrolled(
    cfg: ModelConfig,
    batches: Iterable[GraphBatch],
    epochs: int = 5,
    lr: float = 3e-3,
    pos_weight: float = 10.0,
    seed: int = 0,
    label_attr: str = "edge_label",
) -> tuple[TrainState, List[float]]:
    """Temporal training for TGN: unroll ``step`` across the window
    sequence with memory threaded through, so the GRU/memory parameters
    receive gradient (the memoryless registry ``apply`` trains only the
    snapshot encoder — its memory path stays at init). One jitted program
    over the whole unroll; all windows must share a shape bucket.
    ``label_attr="edge_label_next"`` trains the FORECAST objective
    (replay/scenario.py run_forecast_scenario) — learnable because the
    z-scored edge stats (models/common.py znorm_edge_feats) put the
    sub-threshold latency drift tens of σ above the fleet baseline.

    ``batches`` is one window sequence (List[GraphBatch]) or SEVERAL
    (List[List[GraphBatch]]), each unrolled from fresh memory with the
    loss averaged across sequences. Forecast training MUST use several
    fault draws: with a single plan the faulty edge set is constant
    across every window, so the model can memorize WHICH edges ramp
    instead of learning the drift signature — and at eval time that
    memorization is anti-predictive for fault sets it never saw."""
    from alaz_tpu.models import tgn

    seq_input = list(batches)
    assert seq_input, "no training windows"
    sequences: List[List[GraphBatch]] = (
        [list(s) for s in seq_input]
        if isinstance(seq_input[0], (list, tuple))
        else [seq_input]
    )
    params = tgn.init(jax.random.PRNGKey(seed), cfg)
    # a schedule `lr` is a fresh callable per call — _adamw just misses
    # its cache then, which is no worse than building adamw inline
    optimizer = _adamw(lr)
    opt_state = optimizer.init(params)
    # the unroll is one program, so every window is padded up to the
    # largest bucket present (Poisson traffic routinely straddles bucket
    # boundaries between windows)
    all_b = [b for s in sequences for b in s]
    n_t = max(b.n_pad for b in all_b)
    e_t = max(b.e_pad for b in all_b)
    max_nodes = max(cfg.tgn_max_nodes, n_t)

    def prep_seq(batch_list):
        graphs = [
            {
                k: jnp.asarray(_pad_graph_field(k, v, n_t, e_t))
                for k, v in b.device_arrays().items()
            }
            for b in batch_list
        ]
        labels = [
            jnp.asarray(np.pad(getattr(b, label_attr), (0, e_t - b.e_pad)))
            for b in batch_list
        ]
        return graphs, labels

    prepped = [prep_seq(s) for s in sequences]
    unrolled_step = _make_unrolled_step(cfg, optimizer, pos_weight)
    memory0 = tgn.init_memory(cfg, max_nodes)
    losses: List[float] = []
    for _ in range(epochs):
        params, opt_state, loss = unrolled_step(
            params, opt_state, prepped, memory0
        )
        losses.append(float(loss))
    return TrainState(params=params, opt_state=opt_state, step=len(losses)), losses


@functools.lru_cache(maxsize=None)
def make_score_fn(cfg: ModelConfig) -> Callable:
    """Jitted inference fn (one compile per shape bucket). Cached per
    ModelConfig — frozen dataclass, hashable — so repeated Service
    construction / repeated CLI scoring shares ONE trace cache instead of
    re-tracing per caller (ALZ006, the retrace budget). The inner fn is
    named so the compile log attributes compiles to this entry point."""
    _, apply = get_model(cfg.model)

    def score_apply(params, graph):
        return apply(params, graph, cfg)

    return jax.jit(score_apply)


def score_batch(cfg: ModelConfig, params, batch: GraphBatch, score_fn: Callable | None = None) -> dict:
    if score_fn is None:
        score_fn = make_score_fn(cfg)
    graph = {k: jnp.asarray(v) for k, v in batch.device_arrays().items()}
    out = score_fn(params, graph)
    return {k: jax.device_get(v) for k, v in out.items()}
