"""Losses. Fault detection is per-edge binary classification with heavy
class imbalance, so BCE with positive-class upweighting, masked to real
(non-padding) edges."""

from __future__ import annotations

import jax.numpy as jnp
import optax


def edge_bce_loss(
    edge_logits: jnp.ndarray,
    edge_label: jnp.ndarray,
    edge_mask: jnp.ndarray,
    pos_weight: float = 10.0,
) -> jnp.ndarray:
    per_edge = optax.sigmoid_binary_cross_entropy(edge_logits, edge_label)
    weight = jnp.where(edge_label > 0.5, pos_weight, 1.0) * edge_mask
    return jnp.sum(per_edge * weight) / jnp.maximum(jnp.sum(weight), 1.0)
