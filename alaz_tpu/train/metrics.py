"""Eval metrics. AUROC via the rank statistic (Mann-Whitney U), computed
host-side in numpy — the BASELINE.json quality gate is ≥0.9 AUROC on
injected-fault graphs."""

from __future__ import annotations

import numpy as np


def auroc(scores: np.ndarray, labels: np.ndarray, mask: np.ndarray | None = None) -> float:
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels) > 0.5
    if mask is not None:
        keep = np.asarray(mask, dtype=bool)
        scores, labels = scores[keep], labels[keep]
    n_pos = int(labels.sum())
    n_neg = labels.shape[0] - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="stable")
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, scores.shape[0] + 1)
    # midranks for ties
    sorted_scores = scores[order]
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            mid = (i + j + 2) / 2.0
            ranks[order[i : j + 1]] = mid
        i = j + 1
    pos_rank_sum = ranks[labels].sum()
    u = pos_rank_sum - n_pos * (n_pos + 1) / 2.0
    return float(u / (n_pos * n_neg))


def auroc_by_kind(
    scores: np.ndarray,
    kind_labels: np.ndarray,
    kind_names: tuple,
    mask: np.ndarray | None = None,
) -> dict:
    """Per-failure-class AUROC: each kind k scored one-vs-clean (edges of
    OTHER fault kinds excluded, so classes don't dilute each other).
    ``kind_labels``: 0 = clean, else 1 + index into ``kind_names``
    (replay.faults.label_batch_kinds). NaN for kinds absent from the
    eval set."""
    scores = np.asarray(scores, dtype=np.float64)
    kinds = np.asarray(kind_labels)
    keep = np.ones(scores.shape[0], bool) if mask is None else np.asarray(mask, bool)
    out = {}
    for i, name in enumerate(kind_names):
        sel = keep & ((kinds == 0) | (kinds == i + 1))
        out[name] = auroc(scores[sel], (kinds[sel] == i + 1).astype(np.float32))
    return out
