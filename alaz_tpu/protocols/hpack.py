"""HPACK (RFC 7541) header compression — decoder + encoder.

The reference pairs gRPC request/response HEADERS frames with per-connection
client/server HPACK decoders from golang.org/x/net (aggregator/data.go:93-103,
646-657). This is a from-scratch implementation: static table, dynamic table
with size eviction, integer/string primitives, and Huffman coding.

The Huffman code is built canonically from the per-symbol code lengths
(RFC 7541 Appendix B assigns codes in canonical (length, symbol) order), and
is validated against the RFC's Appendix C test vectors in
``tests/test_protocols.py``.
"""

from __future__ import annotations

from typing import List, Tuple

# Code lengths for symbols 0..256 (256 = EOS), RFC 7541 Appendix B.
# ASCII symbols (32..126) are what headers are made of; the canonical
# construction only needs lengths, and the appendix-C vectors pin them down.
_CODE_LENGTHS = [
    # 0-31 control
    13, 23, 28, 28, 28, 28, 28, 28, 28, 24, 30, 28, 28, 30, 28, 28,
    28, 28, 28, 28, 28, 28, 30, 28, 28, 28, 28, 28, 28, 28, 28, 28,
    #  ' '  !   "   #   $   %   &   '   (   )   *   +   ,   -   .   /
    6, 10, 10, 12, 13, 6, 8, 11, 10, 10, 8, 11, 8, 6, 6, 6,
    #  0  1  2  3  4  5  6  7  8  9  :  ;  <   =  >   ?
    5, 5, 5, 6, 6, 6, 6, 6, 6, 6, 7, 8, 15, 6, 12, 10,
    #  @   A  B  C  D  E  F  G  H  I  J  K  L  M  N  O
    13, 6, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7,
    #  P  Q  R  S  T  U  V  W  X  Y  Z  [   \   ]   ^   _
    7, 7, 7, 7, 7, 7, 7, 7, 8, 7, 8, 13, 19, 13, 14, 6,
    #  `   a  b  c  d  e  f  g  h  i  j  k  l  m  n  o
    15, 5, 6, 5, 6, 5, 6, 6, 6, 5, 7, 7, 6, 6, 6, 5,
    #  p  q  r  s  t  u  v  w  x  y  z  {   |   }   ~   DEL
    6, 7, 6, 5, 5, 6, 7, 7, 7, 7, 7, 15, 11, 14, 13, 28,
    # 128-159
    20, 22, 20, 20, 22, 22, 22, 23, 22, 23, 23, 23, 23, 23, 24, 23,
    24, 24, 22, 23, 24, 23, 23, 23, 23, 21, 22, 23, 22, 23, 23, 24,
    # 160-191
    22, 21, 20, 22, 22, 23, 23, 21, 23, 22, 22, 24, 21, 22, 23, 23,
    21, 21, 22, 21, 23, 22, 23, 23, 20, 22, 22, 22, 23, 22, 22, 23,
    # 192-223
    26, 26, 20, 19, 22, 23, 22, 25, 26, 26, 26, 27, 27, 26, 24, 25,
    19, 21, 26, 27, 27, 26, 27, 24, 21, 21, 26, 26, 28, 27, 27, 27,
    # 224-255
    20, 24, 20, 21, 22, 21, 21, 23, 22, 22, 25, 25, 24, 24, 26, 23,
    26, 27, 26, 26, 27, 27, 27, 27, 27, 28, 27, 27, 27, 27, 27, 26,
    # 256 EOS
    30,
]

assert len(_CODE_LENGTHS) == 257


def _build_canonical() -> tuple[list[tuple[int, int]], dict[tuple[int, int], int]]:
    """Canonical Huffman assignment over (length, symbol) order."""
    order = sorted(range(257), key=lambda s: (_CODE_LENGTHS[s], s))
    codes: list[tuple[int, int]] = [(0, 0)] * 257
    decode: dict[tuple[int, int], int] = {}
    code = 0
    prev_len = _CODE_LENGTHS[order[0]]
    for sym in order:
        ln = _CODE_LENGTHS[sym]
        code <<= ln - prev_len
        prev_len = ln
        codes[sym] = (code, ln)
        decode[(code, ln)] = sym
        code += 1
    return codes, decode


HUFFMAN_CODES, _HUFFMAN_DECODE = _build_canonical()
EOS_SYMBOL = 256


class HpackError(Exception):
    pass


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for b in data:
        code, ln = HUFFMAN_CODES[b]
        acc = (acc << ln) | code
        nbits += ln
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        pad = 8 - nbits
        acc = (acc << pad) | ((1 << pad) - 1)  # EOS-prefix padding (all ones)
        out.append(acc & 0xFF)
    return bytes(out)


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    acc = 0
    nbits = 0
    table = _HUFFMAN_DECODE
    for byte in data:
        acc = (acc << 8) | byte
        nbits += 8
        # greedily match shortest codes (min length is 5)
        while nbits >= 5:
            matched = False
            for ln in range(5, min(nbits, 30) + 1):
                code = (acc >> (nbits - ln)) & ((1 << ln) - 1)
                sym = table.get((code, ln))
                if sym is not None:
                    if sym == EOS_SYMBOL:
                        raise HpackError("EOS in huffman data")
                    out.append(sym)
                    nbits -= ln
                    acc &= (1 << nbits) - 1
                    matched = True
                    break
            if not matched:
                break
    # remaining bits must be an all-ones EOS prefix, < 8 bits
    if nbits >= 8:
        raise HpackError("huffman padding too long")
    if nbits and (acc & ((1 << nbits) - 1)) != (1 << nbits) - 1:
        raise HpackError("huffman padding not EOS prefix")
    return bytes(out)


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------


def encode_integer(value: int, prefix_bits: int, flags: int = 0) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([flags | value])
    out = bytearray([flags | limit])
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_integer(data: bytes, off: int, prefix_bits: int) -> tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    if off >= len(data):
        raise HpackError("integer truncated")
    value = data[off] & limit
    off += 1
    if value < limit:
        return value, off
    shift = 0
    while True:
        if off >= len(data):
            raise HpackError("integer truncated")
        b = data[off]
        off += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return value, off
        if shift > 63:
            raise HpackError("integer overflow")


def encode_string(s: bytes, huffman: bool = True) -> bytes:
    if huffman:
        enc = huffman_encode(s)
        if len(enc) < len(s):
            return encode_integer(len(enc), 7, 0x80) + enc
    return encode_integer(len(s), 7, 0x00) + s


def decode_string(data: bytes, off: int) -> tuple[bytes, int]:
    if off >= len(data):
        raise HpackError("string truncated")
    huff = bool(data[off] & 0x80)
    length, off = decode_integer(data, off, 7)
    raw = bytes(data[off : off + length])
    if len(raw) < length:
        raise HpackError("string truncated")
    off += length
    return (huffman_decode(raw) if huff else raw), off


# ---------------------------------------------------------------------------
# Tables
# ---------------------------------------------------------------------------

STATIC_TABLE: List[Tuple[bytes, bytes]] = [
    (b":authority", b""),
    (b":method", b"GET"),
    (b":method", b"POST"),
    (b":path", b"/"),
    (b":path", b"/index.html"),
    (b":scheme", b"http"),
    (b":scheme", b"https"),
    (b":status", b"200"),
    (b":status", b"204"),
    (b":status", b"206"),
    (b":status", b"304"),
    (b":status", b"400"),
    (b":status", b"404"),
    (b":status", b"500"),
    (b"accept-charset", b""),
    (b"accept-encoding", b"gzip, deflate"),
    (b"accept-language", b""),
    (b"accept-ranges", b""),
    (b"accept", b""),
    (b"access-control-allow-origin", b""),
    (b"age", b""),
    (b"allow", b""),
    (b"authorization", b""),
    (b"cache-control", b""),
    (b"content-disposition", b""),
    (b"content-encoding", b""),
    (b"content-language", b""),
    (b"content-length", b""),
    (b"content-location", b""),
    (b"content-range", b""),
    (b"content-type", b""),
    (b"cookie", b""),
    (b"date", b""),
    (b"etag", b""),
    (b"expect", b""),
    (b"expires", b""),
    (b"from", b""),
    (b"host", b""),
    (b"if-match", b""),
    (b"if-modified-since", b""),
    (b"if-none-match", b""),
    (b"if-range", b""),
    (b"if-unmodified-since", b""),
    (b"last-modified", b""),
    (b"link", b""),
    (b"location", b""),
    (b"max-forwards", b""),
    (b"proxy-authenticate", b""),
    (b"proxy-authorization", b""),
    (b"range", b""),
    (b"referer", b""),
    (b"refresh", b""),
    (b"retry-after", b""),
    (b"server", b""),
    (b"set-cookie", b""),
    (b"strict-transport-security", b""),
    (b"transfer-encoding", b""),
    (b"user-agent", b""),
    (b"vary", b""),
    (b"via", b""),
    (b"www-authenticate", b""),
]

_STATIC_LOOKUP = {}
for _i, (_n, _v) in enumerate(STATIC_TABLE):
    _STATIC_LOOKUP.setdefault((_n, _v), _i + 1)
    _STATIC_LOOKUP.setdefault(_n, _i + 1)


class _DynamicTable:
    def __init__(self, max_size: int = 4096):
        self.entries: list[tuple[bytes, bytes]] = []
        self.size = 0
        self.max_size = max_size

    @staticmethod
    def entry_size(name: bytes, value: bytes) -> int:
        return len(name) + len(value) + 32  # RFC 7541 §4.1

    def add(self, name: bytes, value: bytes) -> None:
        self.entries.insert(0, (name, value))
        self.size += self.entry_size(name, value)
        self._evict()

    def resize(self, max_size: int) -> None:
        self.max_size = max_size
        self._evict()

    def _evict(self) -> None:
        while self.size > self.max_size and self.entries:
            n, v = self.entries.pop()
            self.size -= self.entry_size(n, v)

    def get(self, index: int) -> tuple[bytes, bytes]:
        """1-based HPACK index across static + dynamic tables."""
        if 1 <= index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        di = index - len(STATIC_TABLE) - 1
        if 0 <= di < len(self.entries):
            return self.entries[di]
        raise HpackError(f"invalid index {index}")


class Decoder:
    """Stateful HPACK decoder — one per connection direction, exactly like
    the per-conn client/server decoders in data.go:93-103."""

    def __init__(self, max_table_size: int = 4096):
        self.table = _DynamicTable(max_table_size)

    def decode(self, block: bytes) -> list[tuple[str, str]]:
        headers: list[tuple[str, str]] = []
        off = 0
        while off < len(block):
            b = block[off]
            if b & 0x80:  # indexed
                index, off = decode_integer(block, off, 7)
                name, value = self.table.get(index)
            elif b & 0x40:  # literal with incremental indexing
                index, off = decode_integer(block, off, 6)
                name = self.table.get(index)[0] if index else None
                if name is None:
                    name, off = decode_string(block, off)
                value, off = decode_string(block, off)
                self.table.add(name, value)
            elif b & 0x20:  # dynamic table size update
                size, off = decode_integer(block, off, 5)
                self.table.resize(size)
                continue
            else:  # literal without indexing / never indexed (0x00 / 0x10)
                index, off = decode_integer(block, off, 4)
                name = self.table.get(index)[0] if index else None
                if name is None:
                    name, off = decode_string(block, off)
                value, off = decode_string(block, off)
            headers.append((name.decode("latin-1"), value.decode("latin-1")))
        return headers


class Encoder:
    """Minimal encoder (static-table aware, literal-with-indexing) — used by
    the simulator/tests to fabricate gRPC HEADERS blocks."""

    def __init__(self, max_table_size: int = 4096, huffman: bool = True):
        self.table = _DynamicTable(max_table_size)
        self.huffman = huffman
        self._dyn_lookup: dict[tuple[bytes, bytes], int] = {}

    def encode(self, headers: list[tuple[str, str]]) -> bytes:
        out = bytearray()
        for name_s, value_s in headers:
            name = name_s.encode("latin-1")
            value = value_s.encode("latin-1")
            idx = _STATIC_LOOKUP.get((name, value))
            if isinstance(idx, int) and STATIC_TABLE[idx - 1][1] == value:
                out += encode_integer(idx, 7, 0x80)
                continue
            # dynamic full match
            for di, (n, v) in enumerate(self.table.entries):
                if n == name and v == value:
                    out += encode_integer(len(STATIC_TABLE) + 1 + di, 7, 0x80)
                    break
            else:
                name_idx = _STATIC_LOOKUP.get(name, 0)
                if isinstance(name_idx, int) and name_idx:
                    out += encode_integer(name_idx, 6, 0x40)
                else:
                    out += encode_integer(0, 6, 0x40)
                    out += encode_string(name, self.huffman)
                out += encode_string(value, self.huffman)
                self.table.add(name, value)
        return bytes(out)
