"""SQL keyword gate shared by the Postgres and MySQL parsers.

The reference filters garbage payloads with a case-sensitive keyword regexp
(aggregator/data.go:120-127,1623-1626).
"""

from __future__ import annotations

import re

_KEYWORDS = [
    "SELECT",
    "INSERT INTO",
    "UPDATE",
    "DELETE FROM",
    "CREATE TABLE",
    "ALTER TABLE",
    "DROP TABLE",
    "TRUNCATE TABLE",
    "BEGIN",
    "COMMIT",
    "ROLLBACK",
    "SAVEPOINT",
    "CREATE INDEX",
    "DROP INDEX",
    "CREATE VIEW",
    "DROP VIEW",
    "GRANT",
    "REVOKE",
    "EXECUTE",
]

_RE = re.compile("|".join(_KEYWORDS))


def contains_sql_keywords(text: str) -> bool:
    return _RE.search(text) is not None
