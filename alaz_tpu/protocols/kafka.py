"""Kafka wire protocol: kernel-style header sanity + userspace decode.

Kernel side (ebpf/c/kafka.c:38-79): request header sanity (size == buffer,
api_key in 0..74), capture correlation_id/api_key/api_version; responses
matched by correlation_id. Payload decode is deferred to userspace — the
reference vendors a trimmed Sarama decoder (aggregator/kafka/, ~2.6k LoC,
SURVEY G14). This module is the from-scratch equivalent: ProduceRequest and
FetchResponse decode over both legacy message sets (magic 0/1) and record
batches (magic 2), with gzip decompression (the other codecs are gated on
optional libs, like the reference's decompress.go codec table).

Both non-flexible (produce v0-v8, fetch v0-v11) and flexible/compact
versions (KIP-482: produce v9+, fetch v12+ — compact strings/arrays,
unsigned-varint lengths, tagged fields; fetch v13+ topic ids) decode;
modern clients (Kafka ≥2.4) negotiate the flexible versions.
"""

from __future__ import annotations

import gzip
import io
import struct
from dataclasses import dataclass
from typing import List


API_KEY_PRODUCE = 0
API_KEY_FETCH = 1

PUBLISH = "PUBLISH"
CONSUME = "CONSUME"

# Kafka error code → symbolic name (the errors.go KError table analog;
# common subset — unknown codes format as 'KError-<n>').
KERROR = {
    -1: "UNKNOWN_SERVER_ERROR",
    0: "NONE",
    1: "OFFSET_OUT_OF_RANGE",
    2: "CORRUPT_MESSAGE",
    3: "UNKNOWN_TOPIC_OR_PARTITION",
    4: "INVALID_FETCH_SIZE",
    5: "LEADER_NOT_AVAILABLE",
    6: "NOT_LEADER_OR_FOLLOWER",
    7: "REQUEST_TIMED_OUT",
    8: "BROKER_NOT_AVAILABLE",
    9: "REPLICA_NOT_AVAILABLE",
    10: "MESSAGE_TOO_LARGE",
    11: "STALE_CONTROLLER_EPOCH",
    12: "OFFSET_METADATA_TOO_LARGE",
    13: "NETWORK_EXCEPTION",
    14: "COORDINATOR_LOAD_IN_PROGRESS",
    15: "COORDINATOR_NOT_AVAILABLE",
    16: "NOT_COORDINATOR",
    17: "INVALID_TOPIC_EXCEPTION",
    18: "RECORD_LIST_TOO_LARGE",
    19: "NOT_ENOUGH_REPLICAS",
    20: "NOT_ENOUGH_REPLICAS_AFTER_APPEND",
    21: "INVALID_REQUIRED_ACKS",
    22: "ILLEGAL_GENERATION",
    23: "INCONSISTENT_GROUP_PROTOCOL",
    24: "INVALID_GROUP_ID",
    25: "UNKNOWN_MEMBER_ID",
    26: "INVALID_SESSION_TIMEOUT",
    27: "REBALANCE_IN_PROGRESS",
    28: "INVALID_COMMIT_OFFSET_SIZE",
    29: "TOPIC_AUTHORIZATION_FAILED",
    30: "GROUP_AUTHORIZATION_FAILED",
    31: "CLUSTER_AUTHORIZATION_FAILED",
    32: "INVALID_TIMESTAMP",
    33: "UNSUPPORTED_SASL_MECHANISM",
    34: "ILLEGAL_SASL_STATE",
    35: "UNSUPPORTED_VERSION",
    36: "TOPIC_ALREADY_EXISTS",
    37: "INVALID_PARTITIONS",
    38: "INVALID_REPLICATION_FACTOR",
    39: "INVALID_REPLICA_ASSIGNMENT",
    40: "INVALID_CONFIG",
    41: "NOT_CONTROLLER",
    42: "INVALID_REQUEST",
    43: "UNSUPPORTED_FOR_MESSAGE_FORMAT",
    44: "POLICY_VIOLATION",
    45: "OUT_OF_ORDER_SEQUENCE_NUMBER",
    46: "DUPLICATE_SEQUENCE_NUMBER",
    47: "INVALID_PRODUCER_EPOCH",
    48: "INVALID_TXN_STATE",
    49: "INVALID_PRODUCER_ID_MAPPING",
    50: "INVALID_TRANSACTION_TIMEOUT",
    51: "CONCURRENT_TRANSACTIONS",
    52: "TRANSACTION_COORDINATOR_FENCED",
    53: "TRANSACTIONAL_ID_AUTHORIZATION_FAILED",
    54: "SECURITY_DISABLED",
    55: "OPERATION_NOT_ATTEMPTED",
    56: "KAFKA_STORAGE_ERROR",
    57: "LOG_DIR_NOT_FOUND",
    58: "SASL_AUTHENTICATION_FAILED",
    59: "UNKNOWN_PRODUCER_ID",
    60: "REASSIGNMENT_IN_PROGRESS",
}


def kerror_name(code: int) -> str:
    return KERROR.get(code, f"KError-{code}")


@dataclass
class KafkaMessage:
    """Decoded record → datastore.KafkaEvent fields (dto.go:122-142)."""

    topic: str
    partition: int
    key: str
    value: str
    type: str  # PUBLISH | CONSUME


def parse_request_header(buf: bytes) -> tuple[bool, int, int, int]:
    """(ok, correlation_id, api_key, api_version) — kafka.c:38-66."""
    if len(buf) < 12:
        return (False, 0, 0, 0)
    size, api_key, api_version, correlation_id = struct.unpack_from("!ihhi", buf, 0)
    if size + 4 != len(buf):
        return (False, 0, 0, 0)
    if correlation_id > 0 and 0 <= api_key <= 74:
        return (True, correlation_id, api_key, api_version)
    return (False, 0, 0, 0)


def is_response_header(buf: bytes, correlation_id: int) -> bool:
    """kafka.c:69-79: match by correlation id."""
    if len(buf) < 8:
        return False
    _size, corr = struct.unpack_from("!ii", buf, 0)
    return corr == correlation_id


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def remaining(self) -> int:
        return len(self.buf) - self.off

    def read(self, n: int) -> bytes:
        if self.off + n > len(self.buf):
            raise EOFError
        out = self.buf[self.off : self.off + n]
        self.off += n
        return out

    def skip(self, n: int) -> None:
        if self.off + n > len(self.buf):
            raise EOFError
        self.off += n

    def i8(self) -> int:
        return struct.unpack("!b", self.read(1))[0]

    def i16(self) -> int:
        return struct.unpack("!h", self.read(2))[0]

    def i32(self) -> int:
        return struct.unpack("!i", self.read(4))[0]

    def i64(self) -> int:
        return struct.unpack("!q", self.read(8))[0]

    def string(self) -> str | None:
        n = self.i16()
        if n < 0:
            return None
        return self.read(n).decode("utf-8", "replace")

    def bytes_(self) -> bytes | None:
        n = self.i32()
        if n < 0:
            return None
        return self.read(n)

    def varint(self) -> int:
        """Zigzag varint (record batch v2 encoding)."""
        value = 0
        shift = 0
        while True:
            b = self.read(1)[0]
            value |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
            if shift > 63:
                raise EOFError
        return (value >> 1) ^ -(value & 1)

    def varint_bytes(self) -> bytes | None:
        n = self.varint()
        if n < 0:
            return None
        return self.read(n)

    def bytes_lenient(self) -> bytes:
        """BYTES field tolerating truncation: events carry at most the
        capture window (MAX_PAYLOAD_SIZE), so a record set's declared
        length routinely exceeds what was captured — decode what's there."""
        n = self.i32()
        if n < 0:
            return b""
        take = min(n, self.remaining())
        return self.read(take)

    # -- flexible-version (KIP-482) primitives ---------------------------

    def uvarint(self) -> int:
        """Unsigned varint (compact lengths, tagged-field tags/sizes)."""
        value = 0
        shift = 0
        while True:
            b = self.read(1)[0]
            value |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
            if shift > 63:
                raise EOFError
        return value

    def compact_string(self) -> str | None:
        n = self.uvarint()
        if n == 0:
            return None
        return self.read(n - 1).decode("utf-8", "replace")

    def compact_bytes_lenient(self) -> bytes:
        """COMPACT_BYTES tolerating capture-window truncation."""
        n = self.uvarint()
        if n == 0:
            return b""
        take = min(n - 1, self.remaining())
        return self.read(take)

    def compact_array_len(self) -> int:
        """Compact array length: uvarint(count + 1); -1 means null."""
        return self.uvarint() - 1

    def tagged_fields(self) -> None:
        """Skip a tagged-field section: uvarint count, then per field
        uvarint tag + uvarint size + bytes."""
        n = self.uvarint()
        for _ in range(n):
            self.uvarint()  # tag
            size = self.uvarint()
            self.skip(size)

    def uuid_hex(self) -> str:
        """16-byte UUID (fetch v13+ topic ids) as canonical hex."""
        raw = self.read(16)
        h = raw.hex()
        return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


def _decompress(codec: int, data: bytes) -> bytes | None:
    """Codec table analog of decompress.go; returns None when the codec's
    lib isn't available (caller emits a placeholder)."""
    if codec == 0:
        return data
    if codec == 1:
        try:
            return gzip.GzipFile(fileobj=io.BytesIO(data)).read()
        except OSError:
            return None
    if codec == 2:  # snappy (pure-python decoder, xerial framing aware)
        try:
            from alaz_tpu.protocols.compression import snappy_decompress

            return snappy_decompress(data)
        except Exception:
            return None
    if codec == 3:  # lz4 (pure-python frame/block decoder)
        try:
            from alaz_tpu.protocols.compression import lz4_frame_decompress

            return lz4_frame_decompress(data)
        except Exception:
            return None
    if codec == 4:  # zstd (system libzstd via ctypes → wheel fallback;
        # never silently absent — decompress.go:87 decodes unconditionally)
        try:
            from alaz_tpu.protocols.compression import zstd_decompress

            return zstd_decompress(data)
        except Exception:
            return None
    return None


def _txt(b: bytes | None) -> str:
    if b is None:
        return ""
    return b.decode("utf-8", "replace")


def decode_record_set(topic: str, partition: int, data: bytes, mtype: str) -> List[KafkaMessage]:
    """Decode a record set: record batches v2 or legacy message sets v0/v1
    (records.go/record_batch.go/message_set.go analog)."""
    out: List[KafkaMessage] = []
    r = _Reader(data)
    try:
        while r.remaining() >= 17:
            base_off_pos = r.off
            _base_offset = r.i64()
            batch_len = r.i32()
            if r.remaining() < 1:
                break
            magic_probe = r.buf[r.off + 4] if r.remaining() >= 5 else -1
            if magic_probe == 2:
                # RecordBatch v2
                _leader_epoch = r.i32()
                magic = r.i8()
                _crc = r.i32()
                attrs = r.i16()
                _last_offset_delta = r.i32()
                _first_ts = r.i64()
                _max_ts = r.i64()
                _producer_id = r.i64()
                _producer_epoch = r.i16()
                _base_seq = r.i32()
                n_records = r.i32()
                codec = attrs & 0x07
                records_size = batch_len - 49  # bytes after the count field
                payload = r.read(max(0, min(records_size, r.remaining())))
                if codec:
                    payload2 = _decompress(codec, payload)
                    if payload2 is None:
                        out.append(
                            KafkaMessage(topic, partition, "", "<compressed>", mtype)
                        )
                        continue
                    payload = payload2
                rr = _Reader(payload)
                for _ in range(max(0, n_records)):
                    if rr.remaining() <= 0:
                        break
                    _rec_len = rr.varint()
                    _attr = rr.i8()
                    _ts_delta = rr.varint()
                    _off_delta = rr.varint()
                    key = rr.varint_bytes()
                    value = rr.varint_bytes()
                    n_headers = rr.varint()
                    for _h in range(max(0, n_headers)):
                        rr.varint_bytes()
                        rr.varint_bytes()
                    out.append(KafkaMessage(topic, partition, _txt(key), _txt(value), mtype))
            else:
                # Legacy message: crc i32, magic i8, attrs i8, [ts i64], key, value
                r.off = base_off_pos + 12  # past offset + message_size
                _crc = r.i32()
                magic = r.i8()
                attrs = r.i8()
                if magic >= 1:
                    _ts = r.i64()
                key = r.bytes_()
                value = r.bytes_()
                codec = attrs & 0x07
                if codec and value is not None:
                    inner = _decompress(codec, value)
                    if inner is None:
                        out.append(KafkaMessage(topic, partition, _txt(key), "<compressed>", mtype))
                    else:
                        out.extend(decode_record_set(topic, partition, inner, mtype))
                else:
                    out.append(KafkaMessage(topic, partition, _txt(key), _txt(value), mtype))
    except (EOFError, struct.error):
        pass
    return out


# First flexible api_version per api_key (versions.go analog): flexible
# requests use header v2 (tagged fields after client_id), flexible
# responses use header v1 (tagged fields after correlation_id).
FLEXIBLE_SINCE = {API_KEY_PRODUCE: 9, API_KEY_FETCH: 12}


def is_flexible(api_key: int, api_version: int) -> bool:
    since = FLEXIBLE_SINCE.get(api_key)
    return since is not None and api_version >= since


def decode_produce_request(buf: bytes, api_version: int) -> List[KafkaMessage]:
    """ProduceRequest body (after the request header) → PUBLISH messages
    (produce_request.go analog). v0-v8 classic encoding; v9+ flexible
    (compact strings/arrays, tagged fields)."""
    flexible = api_version >= FLEXIBLE_SINCE[API_KEY_PRODUCE]
    out: List[KafkaMessage] = []
    r = _Reader(buf)
    try:
        if flexible:
            r.compact_string()  # transactional_id
        elif api_version >= 3:
            r.string()  # transactional_id
        _acks = r.i16()
        _timeout = r.i32()
        n_topics = r.compact_array_len() if flexible else r.i32()
        for _ in range(max(0, n_topics)):
            topic = (r.compact_string() if flexible else r.string()) or ""
            n_parts = r.compact_array_len() if flexible else r.i32()
            for _p in range(max(0, n_parts)):
                partition = r.i32()
                record_set = (
                    r.compact_bytes_lenient() if flexible else r.bytes_lenient()
                )
                out.extend(decode_record_set(topic, partition, record_set, PUBLISH))
                if flexible:
                    r.tagged_fields()  # partition tail
            if flexible:
                r.tagged_fields()  # topic tail
        if flexible:
            r.tagged_fields()  # request tail
    except (EOFError, struct.error):
        pass
    return out


def split_request_header(buf: bytes) -> tuple[int, int, int, bytes]:
    """Full request wire bytes → (api_key, api_version, correlation_id,
    body). Header v1: size, api_key, api_version, correlation_id,
    client_id (nullable non-compact string). Header v2 (flexible versions)
    appends tagged fields; client_id stays a legacy string (KIP-482)."""
    r = _Reader(buf)
    _size = r.i32()
    api_key = r.i16()
    api_version = r.i16()
    corr = r.i32()
    r.string()  # client_id
    if is_flexible(api_key, api_version):
        r.tagged_fields()
    return api_key, api_version, corr, buf[r.off :]


def decode_fetch_response(buf: bytes, api_version: int) -> List[KafkaMessage]:
    """FetchResponse body (after size+correlation_id) → CONSUME messages
    (fetch_response.go analog). v0-v11 classic; v12+ flexible (the
    response-header-v1 tagged-field tail is consumed here so the caller
    can keep slicing off size+correlation_id uniformly); v13+ carries
    topic ids (UUID) instead of names."""
    flexible = api_version >= FLEXIBLE_SINCE[API_KEY_FETCH]
    out: List[KafkaMessage] = []
    r = _Reader(buf)
    try:
        if flexible:
            r.tagged_fields()  # response header v1 tail
        if api_version >= 1:
            r.i32()  # throttle_time_ms
        if api_version >= 7:
            r.i16()  # error_code
            r.i32()  # session_id
        n_topics = r.compact_array_len() if flexible else r.i32()
        for _ in range(max(0, n_topics)):
            if api_version >= 13:
                topic = r.uuid_hex()  # topic_id; name resolution is broker-side
            elif flexible:
                topic = r.compact_string() or ""
            else:
                topic = r.string() or ""
            n_parts = r.compact_array_len() if flexible else r.i32()
            for _p in range(max(0, n_parts)):
                partition = r.i32()
                _err = r.i16()
                _high_watermark = r.i64()
                if api_version >= 4:
                    _last_stable = r.i64()
                    if api_version >= 5:
                        _log_start = r.i64()
                    n_aborted = r.compact_array_len() if flexible else r.i32()
                    for _a in range(max(0, n_aborted)):
                        r.i64()  # producer_id
                        r.i64()  # first_offset
                        if flexible:
                            r.tagged_fields()
                if api_version >= 11:
                    r.i32()  # preferred_read_replica
                record_set = (
                    r.compact_bytes_lenient() if flexible else r.bytes_lenient()
                )
                out.extend(decode_record_set(topic, partition, record_set, CONSUME))
                if flexible:
                    r.tagged_fields()  # partition tail
            if flexible:
                r.tagged_fields()  # topic tail
        if flexible:
            r.tagged_fields()  # response tail
    except (EOFError, struct.error):
        pass
    return out
