"""MongoDB wire protocol classify + parse.

Kernel side: OP_MSG/OP_COMPRESSED header match, request vs reply via the
``response_to`` field (ebpf/c/mongo.c:55-92). Userspace: OP_MSG body
section walk extracting "<command> <collection>" (data.go:1558-1617).
"""

from __future__ import annotations

import struct

from alaz_tpu.events.schema import MongoMethod

OP_COMPRESSED = 2012
OP_MSG = 2013


def classify_request(buf: bytes) -> int:
    """→ MongoMethod value or 0; requires response_to == 0 (mongo.c:55-66)."""
    if len(buf) < 16:
        return 0
    _length, _request_id, response_to, opcode = struct.unpack_from("<iiii", buf, 0)
    if response_to != 0:
        return 0
    if opcode == OP_MSG:
        return MongoMethod.OP_MSG
    if opcode == OP_COMPRESSED:
        return MongoMethod.OP_COMPRESSED
    return 0


def is_reply(buf: bytes) -> bool:
    """Reply headers arrive without the length prefix (mongo.c:70-92): the
    first 12 bytes are request_id, response_to, opcode."""
    if len(buf) < 12:
        return False
    _request_id, response_to, opcode = struct.unpack_from("<iii", buf, 0)
    return response_to != 0 and opcode in (OP_MSG, OP_COMPRESSED)


def parse_summary(payload: bytes) -> str | None:
    """'<first-element-name> <string-value>' from an OP_MSG kind-0 body
    section — e.g. 'find myCollection' — mirroring parseMongoEvent
    (data.go:1558-1617). None on anything unparsable."""
    try:
        p = payload[12:]  # cut length, request_id, response_to
        (opcode,) = struct.unpack_from("<I", p, 0)
        p = p[8:]  # cut opcode + flags
        if opcode == OP_COMPRESSED:
            return "compressed mongo event"
        if opcode != OP_MSG:
            return None
        kind = p[0]
        p = p[1:]
        if kind != 0:
            return None
        (doc_len,) = struct.unpack_from("<I", p, 0)
        p = p[4:doc_len]
        elem_type = p[0]
        if elem_type != 2:  # BSON string
            return None
        p = p[1:]
        null_at = p.index(0)
        element = p[:null_at]
        (elem_len,) = struct.unpack_from("<I", p, null_at + 1)
        p = p[null_at + 5 :]
        value = p[: elem_len - 1]
        return f"{element.decode('latin-1')} {value.decode('latin-1')}"
    except (IndexError, ValueError, struct.error):
        return None
