"""Redis RESP classify + parse (ebpf/c/redis.c).

ping/pong, client commands, server-pushed pub/sub events, and response
type → success/error classification; the userspace side uses the raw
payload as the query string (data.go:1120-1160).
"""

from __future__ import annotations

from alaz_tpu.events.schema import RedisMethod

STATUS_SUCCESS = 1
STATUS_ERROR = 2
STATUS_UNKNOWN = 3


def is_ping(buf: bytes) -> bool:
    return len(buf) >= 14 and buf[:8] == b"*1\r\n$4\r\n" and buf[8:14] == b"ping\r\n"


def is_pong(buf: bytes) -> bool:
    if len(buf) < 14:
        return False
    return (
        buf[0:1] == b"*"
        and buf[1:2].isdigit()
        and buf[2:8] == b"\r\n$4\r\n"
        and buf[8:14] == b"pong\r\n"
    )


def is_command(buf: bytes) -> bool:
    """Client RESP array that isn't a pub/sub 'message' (redis.c:60-100)."""
    if len(buf) < 11:
        return False
    if buf[0:1] != b"*" or not buf[1:2].isdigit():
        return False
    if buf[2:4] == b"\r\n":
        if buf[4:11] == b"$7\r\nmes"[:7]:
            return False
        return True
    if buf[2:3].isdigit() and buf[3:5] == b"\r\n":
        if buf[5:11] == b"$7\r\nme":
            return False
        return True
    return False


def is_pushed_event(buf: bytes) -> bool:
    """RESP2 '*' / RESP3 '>' pushed 'message' event (redis.c:103-137)."""
    if len(buf) < 17:
        return False
    if buf[0:1] not in (b">", b"*") or not buf[1:2].isdigit():
        return False
    return buf[2:4] == b"\r\n" and buf[4:17] == b"$7\r\nmessage\r\n"


def classify_request(buf: bytes) -> int:
    """→ RedisMethod value or 0, following the l7.c dispatch order: ping,
    then pushed-event (server→client seen on writes), then command."""
    if is_ping(buf):
        return RedisMethod.PING
    if is_pushed_event(buf):
        return RedisMethod.PUSHED_EVENT
    if is_command(buf):
        return RedisMethod.COMMAND
    return 0


def parse_response(buf: bytes) -> int:
    """Response first-byte type → status (redis.c:140-181)."""
    if not buf:
        return STATUS_UNKNOWN
    if len(buf) < 2 or buf[-2:] != b"\r\n":
        return STATUS_UNKNOWN
    t = buf[0:1]
    if t in (b"*", b":", b"$", b"+", b"_", b"#", b",", b"(", b"=", b"%", b"~"):
        return STATUS_SUCCESS
    if t in (b"-", b"!"):
        return STATUS_ERROR
    return STATUS_UNKNOWN
