"""Pure-Python decompressors for Kafka record batches.

The reference's codec table (aggregator/kafka/decompress.go) handles gzip,
snappy, lz4, and zstd via Go libraries. Python ships gzip; snappy and lz4
get small from-scratch decoders here (their *decompression* formats are
simple tag machines), so Kafka payloads decode without optional C
libraries. zstd is a full entropy coder (FSE + Huffman) — reimplementing
it buys nothing, so it binds the system ``libzstd`` via ctypes
(``zstd_decompress`` below), with the optional ``zstandard`` wheel (which
bundles its own libzstd) as fallback. The reference decodes zstd
unconditionally
(decompress.go:87); here every mainstream base image ships libzstd, so
the decode path works in a bare environment too — only an image with
neither library logs a loud per-process warning instead of silently
yielding nothing.

Formats:
- snappy raw block (https://github.com/google/snappy/blob/main/format_description.txt):
  uncompressed-length varint, then literal/copy tags.
- snappy xerial framing (what Kafka's Java client writes): 8-byte magic
  ``\\x82SNAPPY\\x00`` + version/compat ints, then length-prefixed raw blocks.
- lz4 block (https://github.com/lz4/lz4/blob/dev/doc/lz4_Block_format.md):
  token-based literal/match sequences.
- lz4 frame: magic 0x184D2204 + descriptor + length-prefixed blocks
  (optionally uncompressed, high bit of the size).
"""

from __future__ import annotations

import struct


class CorruptData(Exception):
    pass


# ---------------------------------------------------------------------------
# snappy
# ---------------------------------------------------------------------------

_XERIAL_MAGIC = b"\x82SNAPPY\x00"


def snappy_decompress_raw(data: bytes) -> bytes:
    """Raw snappy block format."""
    # preamble: uncompressed length as little-endian varint
    n = 0
    shift = 0
    pos = 0
    while True:
        if pos >= len(data):
            raise CorruptData("truncated length varint")
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
        if shift > 32:
            raise CorruptData("length varint too long")

    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        elem_type = tag & 0x03
        if elem_type == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > len(data):
                    raise CorruptData("truncated literal length")
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            if pos + length > len(data):
                raise CorruptData("truncated literal")
            out += data[pos : pos + length]
            pos += length
        else:
            if elem_type == 1:  # copy, 1-byte offset
                length = ((tag >> 2) & 0x07) + 4
                if pos >= len(data):
                    raise CorruptData("truncated copy1")
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif elem_type == 2:  # copy, 2-byte offset
                length = (tag >> 2) + 1
                if pos + 2 > len(data):
                    raise CorruptData("truncated copy2")
                offset = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:  # copy, 4-byte offset
                length = (tag >> 2) + 1
                if pos + 4 > len(data):
                    raise CorruptData("truncated copy4")
                offset = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise CorruptData("bad copy offset")
            # overlapping copies are the point: copy byte-by-byte semantics
            start = len(out) - offset
            for i in range(length):
                out.append(out[start + i])
    if len(out) != n:
        raise CorruptData(f"length mismatch: {len(out)} != {n}")
    return bytes(out)


def snappy_decompress(data: bytes) -> bytes:
    """Snappy with Kafka's xerial framing auto-detected."""
    if data[:8] == _XERIAL_MAGIC:
        pos = 16  # magic + version + compat
        out = bytearray()
        while pos + 4 <= len(data):
            (block_len,) = struct.unpack_from(">I", data, pos)
            pos += 4
            out += snappy_decompress_raw(data[pos : pos + block_len])
            pos += block_len
        return bytes(out)
    return snappy_decompress_raw(data)


# ---------------------------------------------------------------------------
# lz4
# ---------------------------------------------------------------------------

_LZ4_FRAME_MAGIC = 0x184D2204


def lz4_block_decompress(data: bytes) -> bytes:
    """LZ4 block format (token machine)."""
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        token = data[pos]
        pos += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                if pos >= n:
                    raise CorruptData("truncated literal length")
                b = data[pos]
                pos += 1
                lit_len += b
                if b != 255:
                    break
        if pos + lit_len > n:
            raise CorruptData("truncated literals")
        out += data[pos : pos + lit_len]
        pos += lit_len
        if pos >= n:
            break  # last sequence has no match
        if pos + 2 > n:
            raise CorruptData("truncated offset")
        offset = int.from_bytes(data[pos : pos + 2], "little")
        pos += 2
        if offset == 0 or offset > len(out):
            raise CorruptData("bad match offset")
        match_len = (token & 0x0F) + 4
        if (token & 0x0F) == 15:
            while True:
                if pos >= n:
                    raise CorruptData("truncated match length")
                b = data[pos]
                pos += 1
                match_len += b
                if b != 255:
                    break
        start = len(out) - offset
        for i in range(match_len):
            out.append(out[start + i])
    return bytes(out)


# ---------------------------------------------------------------------------
# zstd — ctypes binding to the system libzstd (streaming API, so frames
# without a content-size header decode too)
# ---------------------------------------------------------------------------

_zstd_lib = None
_zstd_lib_tried = False
_ZstdBuf = None  # ZSTD_inBuffer/ZSTD_outBuffer layout (identical structs)


def _load_libzstd():
    global _zstd_lib, _zstd_lib_tried, _ZstdBuf
    if _zstd_lib_tried:
        return _zstd_lib
    _zstd_lib_tried = True
    import ctypes
    import ctypes.util

    name = ctypes.util.find_library("zstd") or "libzstd.so.1"
    try:
        lib = ctypes.CDLL(name)
    except OSError:
        return None
    ct = ctypes

    class _Buf(ct.Structure):
        _fields_ = [
            ("ptr", ct.c_void_p),
            ("size", ct.c_size_t),
            ("pos", ct.c_size_t),
        ]

    lib.ZSTD_createDStream.restype = ct.c_void_p
    lib.ZSTD_freeDStream.argtypes = [ct.c_void_p]
    lib.ZSTD_isError.argtypes = [ct.c_size_t]
    lib.ZSTD_isError.restype = ct.c_uint
    lib.ZSTD_DStreamOutSize.restype = ct.c_size_t
    lib.ZSTD_decompressStream.argtypes = [
        ct.c_void_p, ct.POINTER(_Buf), ct.POINTER(_Buf)
    ]
    lib.ZSTD_decompressStream.restype = ct.c_size_t
    _ZstdBuf = _Buf
    _zstd_lib = lib
    return lib


def zstd_decompress_ctypes(data: bytes, max_out: int = 1 << 30) -> bytes:
    """Decompress one or more zstd frames via libzstd's streaming API
    (ZSTD_decompressStream), bounded at ``max_out`` as a zip-bomb guard."""
    import ctypes as ct

    lib = _load_libzstd()
    if lib is None:
        raise CorruptData("libzstd unavailable")
    _Buf = _ZstdBuf

    ds = lib.ZSTD_createDStream()
    if not ds:
        raise CorruptData("ZSTD_createDStream failed")
    try:
        src = ct.create_string_buffer(data, len(data))
        inbuf = _Buf(ct.cast(src, ct.c_void_p), len(data), 0)
        chunk = int(lib.ZSTD_DStreamOutSize())
        out = bytearray()
        dst = ct.create_string_buffer(chunk)
        ret = 0
        while inbuf.pos < inbuf.size:
            outbuf = _Buf(ct.cast(dst, ct.c_void_p), chunk, 0)
            ret = lib.ZSTD_decompressStream(
                ds, ct.byref(outbuf), ct.byref(inbuf)
            )
            if lib.ZSTD_isError(ret):
                raise CorruptData("zstd: corrupt frame")
            out += dst.raw[: outbuf.pos]
            if len(out) > max_out:
                raise CorruptData("zstd: output exceeds bound")
        if ret != 0:
            # input exhausted mid-frame (ret is the bytes-still-needed
            # hint): partial output must NOT pass as a decoded batch
            raise CorruptData("zstd: truncated frame")
        return bytes(out)
    finally:
        lib.ZSTD_freeDStream(ds)


_zstd_warned = False


def _zstd_decompress_wheel(zstandard, data: bytes, max_out: int) -> bytes:
    """Wheel-path decode matching the ctypes contract. Input is fed in
    small chunks so the bomb bound is checked *during* expansion (a
    single decompress(whole_buffer) call would materialize the full
    output before any check could run); decompressobj handles frames
    with no content-size header, dobj.eof distinguishes a finished
    frame from truncation, unused_data chains concatenated frames."""
    chunk_sz = 4096
    out = bytearray()
    buf = data
    while buf:
        dobj = zstandard.ZstdDecompressor().decompressobj()
        pos = 0
        while pos < len(buf) and not dobj.eof:
            step = buf[pos : pos + chunk_sz]
            pos += len(step)
            try:
                out += dobj.decompress(step)
            except zstandard.ZstdError as exc:
                raise CorruptData(f"zstd: {exc}") from exc
            if len(out) > max_out:
                raise CorruptData("zstd: output exceeds bound")
        if not dobj.eof:
            raise CorruptData("zstd: truncated frame")
        buf = dobj.unused_data + buf[pos:]
    return bytes(out)


def zstd_decompress(data: bytes, max_out: int = 1 << 30) -> bytes:
    """zstd via the system libzstd (ctypes), falling back to the
    optional ``zstandard`` wheel (which bundles its own libzstd) where
    the system library is absent. Both backends share one contract: all
    concatenated frames decode, truncation raises, output is bounded at
    ``max_out``. Raises CorruptData on bad data; logs once and raises
    if no backend exists at all (the reference decodes zstd
    unconditionally, decompress.go:87 — silence here would drop every
    batch invisibly)."""
    if _load_libzstd() is not None:
        return zstd_decompress_ctypes(data, max_out=max_out)
    try:
        import zstandard  # type: ignore
    except ImportError:
        pass
    else:
        return _zstd_decompress_wheel(zstandard, data, max_out)
    global _zstd_warned
    if not _zstd_warned:
        _zstd_warned = True
        from alaz_tpu.logging import get_logger

        get_logger("protocols.compression").warning(
            "zstd-compressed Kafka batch but neither the zstandard module "
            "nor libzstd is installed — batches will be dropped"
        )
    raise CorruptData("no zstd backend available")


def lz4_frame_decompress(data: bytes) -> bytes:
    """LZ4 frame format (the container Kafka writes)."""
    if len(data) < 7 or struct.unpack_from("<I", data, 0)[0] != _LZ4_FRAME_MAGIC:
        # not a frame: treat as a bare block
        return lz4_block_decompress(data)
    flg = data[4]
    pos = 6  # magic + FLG + BD
    if flg & 0x08:  # content size present
        pos += 8
    if flg & 0x01:  # dict id
        pos += 4
    pos += 1  # header checksum
    content_checksum = bool(flg & 0x04)
    block_checksum = bool(flg & 0x10)
    out = bytearray()
    while pos + 4 <= len(data):
        (block_size,) = struct.unpack_from("<I", data, pos)
        pos += 4
        if block_size == 0:  # EndMark
            break
        uncompressed = bool(block_size & 0x80000000)
        block_size &= 0x7FFFFFFF
        block = data[pos : pos + block_size]
        pos += block_size
        if block_checksum:
            pos += 4
        out += block if uncompressed else lz4_block_decompress(block)
    if content_checksum:
        pos += 4
    return bytes(out)
