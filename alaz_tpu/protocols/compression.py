"""Pure-Python decompressors for Kafka record batches.

The reference's codec table (aggregator/kafka/decompress.go) handles gzip,
snappy, lz4, and zstd via Go libraries. Python ships gzip; snappy and lz4
get small from-scratch decoders here (their *decompression* formats are
simple tag machines), so Kafka payloads decode without optional C
libraries. zstd remains gated on the optional ``zstandard`` module — its
format is a full entropy coder, not worth a reimplementation.

Formats:
- snappy raw block (https://github.com/google/snappy/blob/main/format_description.txt):
  uncompressed-length varint, then literal/copy tags.
- snappy xerial framing (what Kafka's Java client writes): 8-byte magic
  ``\\x82SNAPPY\\x00`` + version/compat ints, then length-prefixed raw blocks.
- lz4 block (https://github.com/lz4/lz4/blob/dev/doc/lz4_Block_format.md):
  token-based literal/match sequences.
- lz4 frame: magic 0x184D2204 + descriptor + length-prefixed blocks
  (optionally uncompressed, high bit of the size).
"""

from __future__ import annotations

import struct


class CorruptData(Exception):
    pass


# ---------------------------------------------------------------------------
# snappy
# ---------------------------------------------------------------------------

_XERIAL_MAGIC = b"\x82SNAPPY\x00"


def snappy_decompress_raw(data: bytes) -> bytes:
    """Raw snappy block format."""
    # preamble: uncompressed length as little-endian varint
    n = 0
    shift = 0
    pos = 0
    while True:
        if pos >= len(data):
            raise CorruptData("truncated length varint")
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
        if shift > 32:
            raise CorruptData("length varint too long")

    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        elem_type = tag & 0x03
        if elem_type == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > len(data):
                    raise CorruptData("truncated literal length")
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            if pos + length > len(data):
                raise CorruptData("truncated literal")
            out += data[pos : pos + length]
            pos += length
        else:
            if elem_type == 1:  # copy, 1-byte offset
                length = ((tag >> 2) & 0x07) + 4
                if pos >= len(data):
                    raise CorruptData("truncated copy1")
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif elem_type == 2:  # copy, 2-byte offset
                length = (tag >> 2) + 1
                if pos + 2 > len(data):
                    raise CorruptData("truncated copy2")
                offset = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:  # copy, 4-byte offset
                length = (tag >> 2) + 1
                if pos + 4 > len(data):
                    raise CorruptData("truncated copy4")
                offset = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise CorruptData("bad copy offset")
            # overlapping copies are the point: copy byte-by-byte semantics
            start = len(out) - offset
            for i in range(length):
                out.append(out[start + i])
    if len(out) != n:
        raise CorruptData(f"length mismatch: {len(out)} != {n}")
    return bytes(out)


def snappy_decompress(data: bytes) -> bytes:
    """Snappy with Kafka's xerial framing auto-detected."""
    if data[:8] == _XERIAL_MAGIC:
        pos = 16  # magic + version + compat
        out = bytearray()
        while pos + 4 <= len(data):
            (block_len,) = struct.unpack_from(">I", data, pos)
            pos += 4
            out += snappy_decompress_raw(data[pos : pos + block_len])
            pos += block_len
        return bytes(out)
    return snappy_decompress_raw(data)


# ---------------------------------------------------------------------------
# lz4
# ---------------------------------------------------------------------------

_LZ4_FRAME_MAGIC = 0x184D2204


def lz4_block_decompress(data: bytes) -> bytes:
    """LZ4 block format (token machine)."""
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        token = data[pos]
        pos += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                if pos >= n:
                    raise CorruptData("truncated literal length")
                b = data[pos]
                pos += 1
                lit_len += b
                if b != 255:
                    break
        if pos + lit_len > n:
            raise CorruptData("truncated literals")
        out += data[pos : pos + lit_len]
        pos += lit_len
        if pos >= n:
            break  # last sequence has no match
        if pos + 2 > n:
            raise CorruptData("truncated offset")
        offset = int.from_bytes(data[pos : pos + 2], "little")
        pos += 2
        if offset == 0 or offset > len(out):
            raise CorruptData("bad match offset")
        match_len = (token & 0x0F) + 4
        if (token & 0x0F) == 15:
            while True:
                if pos >= n:
                    raise CorruptData("truncated match length")
                b = data[pos]
                pos += 1
                match_len += b
                if b != 255:
                    break
        start = len(out) - offset
        for i in range(match_len):
            out.append(out[start + i])
    return bytes(out)


def lz4_frame_decompress(data: bytes) -> bytes:
    """LZ4 frame format (the container Kafka writes)."""
    if len(data) < 7 or struct.unpack_from("<I", data, 0)[0] != _LZ4_FRAME_MAGIC:
        # not a frame: treat as a bare block
        return lz4_block_decompress(data)
    flg = data[4]
    pos = 6  # magic + FLG + BD
    if flg & 0x08:  # content size present
        pos += 8
    if flg & 0x01:  # dict id
        pos += 4
    pos += 1  # header checksum
    content_checksum = bool(flg & 0x04)
    block_checksum = bool(flg & 0x10)
    out = bytearray()
    while pos + 4 <= len(data):
        (block_size,) = struct.unpack_from("<I", data, pos)
        pos += 4
        if block_size == 0:  # EndMark
            break
        uncompressed = bool(block_size & 0x80000000)
        block_size &= 0x7FFFFFFF
        block = data[pos : pos + block_size]
        pos += block_size
        if block_checksum:
            pos += 4
        out += block if uncompressed else lz4_block_decompress(block)
    if content_checksum:
        pos += 4
    return bytes(out)
