"""AMQP 0-9-1 classify (ebpf/c/amqp.c).

METHOD frames of class BASIC with method PUBLISH(40)/DELIVER(60); publish
completion is observed on the write-exit path in the reference
(l7.c:178-191,485-573). DELIVER events get their direction reversed by the
aggregator (data.go:1110-1112).
"""

from __future__ import annotations

import struct

from alaz_tpu.events.schema import AmqpMethod

FRAME_TYPE_METHOD = 0x01
FRAME_END = 0xCE
CLASS_BASIC = 60
METHOD_PUBLISH = 40
METHOD_DELIVER = 60


def _method_is(buf: bytes, expected_method: int) -> bool:
    if len(buf) < 12:
        return False
    if buf[0] != FRAME_TYPE_METHOD:
        return False
    (size,) = struct.unpack_from("!I", buf, 3)
    if 7 + size + 1 > len(buf):
        return False
    if buf[7 + size] != FRAME_END:
        return False
    (class_id,) = struct.unpack_from("!H", buf, 7)
    if class_id != CLASS_BASIC:
        return False
    (method,) = struct.unpack_from("!H", buf, 9)
    return method == expected_method


def is_publish(buf: bytes) -> bool:
    return _method_is(buf, METHOD_PUBLISH)


def is_deliver(buf: bytes) -> bool:
    return _method_is(buf, METHOD_DELIVER)


def classify_request(buf: bytes) -> int:
    if is_publish(buf):
        return AmqpMethod.PUBLISH
    if is_deliver(buf):
        return AmqpMethod.DELIVER
    return 0


def build_method_frame(channel: int, class_id: int, method_id: int, args: bytes = b"") -> bytes:
    """Fabricate a METHOD frame (simulator/test helper)."""
    payload = struct.pack("!HH", class_id, method_id) + args
    return (
        struct.pack("!BHI", FRAME_TYPE_METHOD, channel, len(payload))
        + payload
        + bytes([FRAME_END])
    )
