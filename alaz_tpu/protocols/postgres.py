"""Postgres wire protocol classify + parse.

Kernel side: client Q/X and P/B+Sync detection, server response →
COMMAND_COMPLETE / ERROR_RESPONSE (ebpf/c/postgres.c:104-208). Userspace:
SQL statement extraction incl. the extended-protocol prepared-statement
cache (aggregator/data.go:1474-1556).
"""

from __future__ import annotations

import struct

from alaz_tpu.events.schema import PostgresMethod
from alaz_tpu.protocols.sql import contains_sql_keywords

COMMAND_COMPLETE = 1
ERROR_RESPONSE = 2


def classify_request(buf: bytes) -> int:
    """→ PostgresMethod value or 0; postgres.c:104-151 semantics."""
    if len(buf) < 5:
        return 0
    ident = buf[0:1]
    (length,) = struct.unpack_from("!I", buf, 1)
    if ident == b"X" and length == 4:
        return PostgresMethod.CLOSE_OR_TERMINATE
    if ident == b"Q":
        return PostgresMethod.SIMPLE_QUERY
    if ident in (b"P", b"B"):
        # distinguish from the HTTP/2 magic ('PRI * ...') by requiring a
        # trailing Sync message: 'S' + int32(4)
        tail = buf[-5:]
        if tail == b"S\x00\x00\x00\x04":
            return PostgresMethod.EXTENDED_QUERY
    return 0


def parse_response(buf: bytes) -> int:
    """→ COMMAND_COMPLETE | ERROR_RESPONSE | 0; postgres.c:153-208."""
    if len(buf) < 5:
        return 0
    (length,) = struct.unpack_from("!I", buf, 1)
    if length + 1 > len(buf):
        return 0
    ident = buf[0:1]
    if ident == b"E":
        return ERROR_RESPONSE
    if ident in (b"t", b"T", b"D", b"C"):
        return COMMAND_COMPLETE
    return 0


def parse_command(
    payload: bytes,
    method: int,
    stmt_cache: dict[tuple[int, int, str], str] | None = None,
    pid: int = 0,
    fd: int = 0,
) -> str | None:
    """SQL text for the Request.path field, mirroring parsePostgresCommand
    (data.go:1474-1556). ``stmt_cache`` is the pgStmts analog keyed
    (pid, fd, stmt_name); pass the same dict across calls per aggregator.

    Returns None where the reference returns an error (caller drops path).
    """
    r = payload
    if method == PostgresMethod.SIMPLE_QUERY:
        if len(r) < 5:
            return None
        sql = r[5:].split(b"\x00", 1)[0].decode("latin-1")
        if not contains_sql_keywords(sql):
            return None
        return sql
    if method == PostgresMethod.EXTENDED_QUERY:
        if not r:
            return None
        ident = r[0:1]
        if ident == b"P":
            parts = r[5:].split(b"\x00")
            if len(parts) >= 2:
                stmt_name = parts[0].decode("latin-1")
                query = parts[1].decode("latin-1")
                if len(parts) == 2:  # query truncated by capture window
                    query += "..."
            else:
                return None
            if stmt_cache is not None:
                stmt_cache[(pid, fd, stmt_name)] = query
            return f"PREPARE {stmt_name} AS {query}"
        if ident == b"B":
            parts = r[5:].split(b"\x00")
            if len(parts) < 2:
                return None
            stmt_name = parts[1].decode("latin-1")
            query = (stmt_cache or {}).get((pid, fd, stmt_name), "")
            if not query:
                return f"EXECUTE {stmt_name} *values*"
            return query
        return None
    if method == PostgresMethod.CLOSE_OR_TERMINATE:
        return payload.split(b"\x00", 1)[0].decode("latin-1")
    return None
