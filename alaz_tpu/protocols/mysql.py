"""MySQL client/server protocol classify + parse.

Kernel side: COM_QUERY/STMT_PREPARE/EXECUTE/CLOSE detection and OK/EOF/ERR
responses with prepared statement_id extraction (ebpf/c/mysql.c:39-99).
Userspace: SQL extraction + prepared-statement cache
(aggregator/data.go:1431-1472).
"""

from __future__ import annotations

import struct

from alaz_tpu.events.schema import MySqlMethod
from alaz_tpu.protocols.sql import contains_sql_keywords

COM_QUERY = 0x03
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_CLOSE = 0x19

RESPONSE_OK = 0x00
RESPONSE_EOF = 0xFE
RESPONSE_ERROR = 0xFF

STATUS_OK = 1
STATUS_FAILED = 2

_COM_TO_METHOD = {
    COM_QUERY: MySqlMethod.TEXT_QUERY,
    COM_STMT_PREPARE: MySqlMethod.PREPARE_STMT,
    COM_STMT_EXECUTE: MySqlMethod.EXEC_STMT,
    COM_STMT_CLOSE: MySqlMethod.STMT_CLOSE,
}


def classify_request(buf: bytes) -> tuple[int, int]:
    """→ (MySqlMethod value or 0, command byte); mysql.c:39-68. The packet
    length must cover the buffer exactly and sequence id must be 0."""
    if len(buf) < 5:
        return (0, 0)
    length = buf[0] | buf[1] << 8 | buf[2] << 16
    if length + 4 != len(buf) or buf[3] != 0:
        return (0, 0)
    method = _COM_TO_METHOD.get(buf[4])
    if method is None:
        return (0, 0)
    return (method, buf[4])


def parse_response(buf: bytes, request_method: int) -> tuple[int, int]:
    """→ (STATUS_OK | STATUS_FAILED | 0, statement_id); mysql.c:72-99."""
    if len(buf) < 5:
        return (0, 0)
    if buf[3] <= 0:  # sequence must be > 0
        return (0, 0)
    length = buf[0] | buf[1] << 8 | buf[2] << 16
    if length == 1 or buf[4] == RESPONSE_EOF:
        return (STATUS_OK, 0)
    if buf[4] == RESPONSE_OK:
        stmt_id = 0
        if request_method == MySqlMethod.PREPARE_STMT and len(buf) >= 9:
            (stmt_id,) = struct.unpack_from("<I", buf, 5)
        return (STATUS_OK, stmt_id)
    if buf[4] == RESPONSE_ERROR:
        return (STATUS_FAILED, 0)
    return (0, 0)


def parse_command(
    payload: bytes,
    method: int,
    stmt_cache: dict[tuple[int, int, int], str] | None = None,
    pid: int = 0,
    fd: int = 0,
    prep_stmt_id: int = 0,
) -> str | None:
    """SQL text for Request.path, mirroring parseMySQLCommand
    (data.go:1431-1472). ``stmt_cache`` is the mySqlStmts analog keyed
    (pid, fd, statement_id)."""
    if len(payload) < 5:
        return None
    r = payload[5:]
    if method == MySqlMethod.TEXT_QUERY:
        sql = r.split(b"\x00", 1)[0].decode("latin-1")
        if not contains_sql_keywords(sql):
            return None
        return sql
    if method == MySqlMethod.PREPARE_STMT:
        sql = r.split(b"\x00", 1)[0].decode("latin-1")
        if stmt_cache is not None:
            stmt_cache[(pid, fd, prep_stmt_id)] = sql
        return sql
    if method == MySqlMethod.EXEC_STMT:
        if len(r) < 4:
            return None
        (stmt_id,) = struct.unpack_from("<I", r, 0)
        query = (stmt_cache or {}).get((pid, fd, stmt_id), "")
        if not query:
            return f"EXECUTE {stmt_id} *values*"
        return query
    if method == MySqlMethod.STMT_CLOSE:
        if len(r) < 4:
            return None
        (stmt_id,) = struct.unpack_from("<I", r, 0)
        if stmt_cache is not None:
            stmt_cache.pop((pid, fd, stmt_id), None)
        return f"CLOSE STMT {stmt_id} "
    return None
