"""HTTP/2 frame detection and parsing (gRPC rides on this).

Kernel-side behavior (ebpf/c/http2.c:54-113): recognize the client magic
preface or a plausible frame header, and only track client-initiated (odd)
stream ids; frames are forwarded raw to userspace, where the aggregator
pairs client/server HEADERS per stream (data.go:533-810, G13).

Here: ``is_frame`` is the classifier; ``iter_frames`` walks a byte buffer
into (stream_id, type, flags, payload) tuples for the userspace assembler in
``alaz_tpu.aggregator.h2``.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple

CLIENT_FRAME = 1
SERVER_FRAME = 2

MAGIC = bytes(
    [
        0x50, 0x52, 0x49, 0x20, 0x2A, 0x20, 0x48, 0x54,
        0x54, 0x50, 0x2F, 0x32, 0x2E, 0x30, 0x0D, 0x0A,
        0x0D, 0x0A, 0x53, 0x4D, 0x0D, 0x0A, 0x0D, 0x0A,
    ]
)

FRAME_DATA = 0x0
FRAME_HEADERS = 0x1
FRAME_PRIORITY = 0x2
FRAME_RST_STREAM = 0x3
FRAME_SETTINGS = 0x4
FRAME_PUSH_PROMISE = 0x5
FRAME_PING = 0x6
FRAME_GOAWAY = 0x7
FRAME_WINDOW_UPDATE = 0x8
FRAME_CONTINUATION = 0x9

FLAG_END_STREAM = 0x1
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20


def is_magic(buf: bytes) -> bool:
    return buf[:14] == MAGIC[:14]  # is_http2_magic_2 checks the first 14 bytes


def is_frame(buf: bytes) -> bool:
    """http2.c:54-113: magic, or valid frame type with stream id 0 or odd."""
    if len(buf) < 9:
        return False
    if is_magic(buf):
        return True
    ftype = buf[3]
    if ftype > 0x09:
        return False
    stream_id = int.from_bytes(buf[5:9], "big") & 0x7FFFFFFF
    if stream_id == 0:
        return True
    return stream_id % 2 == 1


class Frame(NamedTuple):
    length: int
    type: int
    flags: int
    stream_id: int
    payload: bytes


def parse_frame_header(buf: bytes, off: int = 0) -> Frame | None:
    """Parse one 9-byte frame header (+payload view) at ``off``; None if the
    buffer is exhausted. Mirrors the aggregator's alloc-free manual parse
    (data.go:619-628)."""
    if off + 9 > len(buf):
        return None
    length = int.from_bytes(buf[off : off + 3], "big")
    ftype = buf[off + 3]
    flags = buf[off + 4]
    stream_id = int.from_bytes(buf[off + 5 : off + 9], "big") & 0x7FFFFFFF
    payload = bytes(buf[off + 9 : off + 9 + length])
    return Frame(length, ftype, flags, stream_id, payload)


def iter_frames(buf: bytes) -> Iterator[Frame]:
    """Walk a buffer of concatenated frames, skipping a leading magic.

    Truncated trailing frames yield with whatever payload prefix survived
    (payload capture is capped, like the kernel's 1024-byte window)."""
    off = 24 if buf[:24] == MAGIC else 0
    while off < len(buf):
        f = parse_frame_header(buf, off)
        if f is None:
            return
        yield f
        off += 9 + f.length


def build_frame(ftype: int, flags: int, stream_id: int, payload: bytes = b"") -> bytes:
    """Serialize one frame (the write side of parse_frame_header) — used by
    the CRI gRPC client, which speaks HTTP/2 over the runtime socket."""
    return (
        len(payload).to_bytes(3, "big")
        + bytes([ftype & 0xFF, flags & 0xFF])
        + (stream_id & 0x7FFFFFFF).to_bytes(4, "big")
        + payload
    )


def headers_block(frame: Frame) -> bytes:
    """Strip padding/priority from a HEADERS frame payload → HPACK block."""
    payload = frame.payload
    if frame.flags & FLAG_PADDED and payload:
        pad = payload[0]
        payload = payload[1 : len(payload) - pad if pad < len(payload) else 1]
    if frame.flags & FLAG_PRIORITY and len(payload) >= 5:
        payload = payload[5:]
    return payload
