"""HTTP/1.x classify + parse.

Kernel-side behavior: method byte-match on the first bytes of a write
payload and ``HTTP/x.y NNN`` status parse on the read side
(ebpf/c/http.c:17-77). Userspace: request-line + Host header extraction
(aggregator/data.go:508-531).

``classify_batch``/``parse_status_batch`` are the vectorized forms used on
columnar payload matrices — the replay hot path.
"""

from __future__ import annotations

import numpy as np

from alaz_tpu.events.schema import HttpMethod

MIN_METHOD_LEN = 8
MIN_RESP_LEN = 12

_METHOD_PREFIXES: list[tuple[bytes, int]] = [
    (b"GET", HttpMethod.GET),
    (b"POST", HttpMethod.POST),
    (b"PUT", HttpMethod.PUT),
    (b"PATCH", HttpMethod.PATCH),
    (b"DELETE", HttpMethod.DELETE),
    (b"HEAD", HttpMethod.HEAD),
    (b"CONNECT", HttpMethod.CONNECT),
    (b"OPTIONS", HttpMethod.OPTIONS),
    (b"TRACE", HttpMethod.TRACE),
]


def parse_method(buf: bytes) -> int:
    """Method enum, or 0/-1 matching http.c:17-45 semantics (0 = too short,
    -1 folded to 0 here: both mean 'not HTTP')."""
    if len(buf) < MIN_METHOD_LEN:
        return 0
    for prefix, method in _METHOD_PREFIXES:
        if buf.startswith(prefix):
            return method
    return 0


def parse_status(buf: bytes) -> int:
    """``HTTP/d.d NNN`` → NNN, else -1 (http.c:48-77); 0 if too short."""
    if len(buf) < MIN_RESP_LEN:
        return 0
    b = buf
    if not (b[0:5] == b"HTTP/" and b[5:6].isdigit() and b[6:7] == b"." and b[7:8].isdigit() and b[8:9] == b" "):
        return -1
    if not b[9:12].isdigit():
        return -1
    return int(b[9:12])


def parse_payload(request: bytes | str) -> tuple[str, str, str, str]:
    """Request line + Host header → (method, path, http_version, host),
    mirroring parseHttpPayload (data.go:508-531)."""
    if isinstance(request, (bytes, bytearray, memoryview)):
        request = bytes(request).split(b"\x00", 1)[0].decode("latin-1")
    method = path = version = host = ""
    lines = request.split("\n")
    parts = lines[0].split(" ")
    if len(parts) >= 3:
        method, path, version = parts[0], parts[1], parts[2]
    for line in lines[1:]:
        if line.startswith("Host:"):
            host_parts = line.split(" ")
            if len(host_parts) >= 2:
                host = host_parts[1].rstrip("\r")
                break
    return method, path, version, host


# ---------------------------------------------------------------------------
# Vectorized forms over payload matrices (uint8 [N, MAX_PAYLOAD_SIZE]).
# ---------------------------------------------------------------------------

_PREFIX_TABLE = np.zeros((len(_METHOD_PREFIXES), MIN_METHOD_LEN), dtype=np.uint8)
_PREFIX_LENS = np.zeros(len(_METHOD_PREFIXES), dtype=np.int64)
_PREFIX_IDS = np.zeros(len(_METHOD_PREFIXES), dtype=np.uint8)
for _i, (_p, _m) in enumerate(_METHOD_PREFIXES):
    _PREFIX_TABLE[_i, : len(_p)] = np.frombuffer(_p, dtype=np.uint8)
    _PREFIX_LENS[_i] = len(_p)
    _PREFIX_IDS[_i] = _m


def classify_batch(payloads: np.ndarray, sizes: np.ndarray) -> np.ndarray:
    """Vectorized parse_method over a [N, >=8] uint8 payload matrix.

    Returns a uint8 method array (0 where not HTTP)."""
    n = payloads.shape[0]
    out = np.zeros(n, dtype=np.uint8)
    window = payloads[:, :MIN_METHOD_LEN]  # [N, 8]
    for i in range(len(_METHOD_PREFIXES)):
        plen = _PREFIX_LENS[i]
        match = (window[:, :plen] == _PREFIX_TABLE[i, :plen]).all(axis=1)
        out = np.where((out == 0) & match, _PREFIX_IDS[i], out)
    out[sizes < MIN_METHOD_LEN] = 0
    return out


def parse_status_batch(payloads: np.ndarray, sizes: np.ndarray) -> np.ndarray:
    """Vectorized parse_status. int32 array: NNN, -1 not-HTTP, 0 too-short."""
    n = payloads.shape[0]
    b = payloads[:, :MIN_RESP_LEN]
    digits = (b >= ord("0")) & (b <= ord("9"))
    head_ok = (
        (b[:, 0] == ord("H"))
        & (b[:, 1] == ord("T"))
        & (b[:, 2] == ord("T"))
        & (b[:, 3] == ord("P"))
        & (b[:, 4] == ord("/"))
        & digits[:, 5]
        & (b[:, 6] == ord("."))
        & digits[:, 7]
        & (b[:, 8] == ord(" "))
        & digits[:, 9]
        & digits[:, 10]
        & digits[:, 11]
    )
    status = (
        (b[:, 9].astype(np.int32) - ord("0")) * 100
        + (b[:, 10].astype(np.int32) - ord("0")) * 10
        + (b[:, 11].astype(np.int32) - ord("0"))
    )
    out = np.where(head_ok, status, np.int32(-1))
    out = np.where(sizes < MIN_RESP_LEN, np.int32(0), out)
    return out
