"""L7 protocol classifiers and parsers.

This package is the userspace re-realization of the reference's kernel-side
classifiers (ebpf/c/{http,http2,postgres,mysql,mongo,redis,kafka,amqp}.c,
SURVEY §2.1 N5-N12) plus its userspace payload post-parsers
(aggregator/data.go:508-531,1431-1617 and aggregator/kafka/, G13-G15).

Two call surfaces:

- ``classify_request(buf)`` / per-protocol ``parse_response(buf)`` — given a
  raw payload, detect protocol + method the way the kernel programs do on
  write-syscall entry. Used by the trace replayer and by parity tests; the
  simulator emits pre-classified events so the hot path never touches bytes.
- richer post-parsers (HTTP path/host, SQL statement extraction with
  prepared-statement caches, Mongo section walk, Kafka record decode) used
  by the aggregator to fill ``Request.path``-style fields.

Classification order follows the kernel's write-path chain
(ebpf/c/l7.c:248-384): HTTP, Postgres, Redis (ping then command, unless a
pong), Kafka, AMQP publish, MySQL, Mongo, and HTTP2 frames **last** (the
frame check is permissive, so everything else must win first).
"""

from __future__ import annotations

from alaz_tpu.events.schema import L7Protocol

from alaz_tpu.protocols import amqp, http, http2, kafka, mongo, mysql, postgres, redis


def classify_request(buf: bytes) -> tuple[int, int]:
    """Classify a request payload → (protocol, method) the way
    process_enter_of_syscalls_write_sendto does (l7.c:248-384).

    Returns (L7Protocol.UNKNOWN, 0) when nothing matches.
    """
    m = http.parse_method(buf)
    if m > 0:
        return (L7Protocol.HTTP, m)
    m = postgres.classify_request(buf)
    if m > 0:
        return (L7Protocol.POSTGRES, m)
    if redis.is_ping(buf):
        return (L7Protocol.REDIS, 3)
    if not redis.is_pong(buf) and redis.is_command(buf):
        return (L7Protocol.REDIS, 1)
    ok, _corr, _key, _ver = kafka.parse_request_header(buf)
    if ok:
        return (L7Protocol.KAFKA, 0)  # method resolved in userspace decode
    m = amqp.classify_request(buf)
    if m > 0:
        return (L7Protocol.AMQP, m)
    m, _stmt = mysql.classify_request(buf)
    if m > 0:
        return (L7Protocol.MYSQL, m)
    m = mongo.classify_request(buf)
    if m > 0:
        return (L7Protocol.MONGO, m)
    if http2.is_frame(buf):
        return (L7Protocol.HTTP2, http2.CLIENT_FRAME)
    return (L7Protocol.UNKNOWN, 0)


__all__ = [
    "classify_request",
    "http",
    "http2",
    "postgres",
    "mysql",
    "mongo",
    "redis",
    "kafka",
    "amqp",
]
