"""Cold-start socket backfill from procfs — the sock_num_line.go:223-269,
352-429 analog.

On agent (re)start every pre-existing TCP connection is invisible until a
new kernel TCP event arrives, so L7 events on long-lived connections drop
for minutes. The reference rebuilds initial socket lines by joining
``/proc/<pid>/fd`` socket inodes against ``/proc/<pid>/net/tcp`` (the
pid's network-namespace view) and seeding an open interval per
established connection; this module does the same over a pluggable proc
root so fixtures can drive it in tests.
"""

from __future__ import annotations

import os
import re
from pathlib import Path
from typing import Iterable

from alaz_tpu.aggregator.sockline import SockInfo, SocketLineStore

TCP_ESTABLISHED = 0x01  # include/net/tcp_states.h

_SOCKET_LINK = re.compile(r"socket:\[(\d+)\]")


def _parse_hex_addr(addr: str) -> tuple[int, int]:
    """'0100007F:1F90' → (u32 big-endian ip, port). procfs stores the IPv4
    address as little-endian hex (readSockets parses the same columns)."""
    ip_hex, port_hex = addr.split(":")
    ip = int.from_bytes(bytes.fromhex(ip_hex), "little")
    return ip, int(port_hex, 16)


def parse_proc_net_tcp(text: str) -> dict[int, tuple[int, int, int, int]]:
    """/proc/<pid>/net/tcp → {inode: (saddr, sport, daddr, dport)} for
    ESTABLISHED sockets only (sock_num_line.go:236-265 keeps st==01)."""
    out: dict[int, tuple[int, int, int, int]] = {}
    for line in text.splitlines()[1:]:  # first line is the header
        parts = line.split()
        if len(parts) < 10:
            continue
        try:
            local, remote, state = parts[1], parts[2], int(parts[3], 16)
            inode = int(parts[9])
        except (ValueError, IndexError):
            continue
        if state != TCP_ESTABLISHED or inode == 0:
            continue
        try:
            saddr, sport = _parse_hex_addr(local)
            daddr, dport = _parse_hex_addr(remote)
        except ValueError:
            continue
        out[inode] = (saddr, sport, daddr, dport)
    return out


def read_fd_socket_inodes(proc_root: str | os.PathLike, pid: int) -> dict[int, int]:
    """/proc/<pid>/fd/* symlinks → {fd: socket inode}
    (getInodes, sock_num_line.go:352-383)."""
    out: dict[int, int] = {}
    fd_dir = Path(proc_root) / str(pid) / "fd"
    try:
        entries = os.listdir(fd_dir)
    except OSError:
        return out
    for name in entries:
        try:
            fd = int(name)
            target = os.readlink(fd_dir / name)
        except (ValueError, OSError):
            continue
        m = _SOCKET_LINK.match(target)
        if m:
            out[fd] = int(m.group(1))
    return out


def list_pids(proc_root: str | os.PathLike) -> list[int]:
    try:
        return sorted(int(d) for d in os.listdir(proc_root) if d.isdigit())
    except OSError:
        return []


def backfill_socket_lines(
    store: SocketLineStore,
    pids: Iterable[int] | None = None,
    proc_root: str | os.PathLike = "/proc",
    now_ns: int = 0,
) -> int:
    """Seed socket lines for every established connection visible in
    procfs; returns the number of lines created. Called once at aggregator
    construction (createSocketLine fetch path, sock_num_line.go:399-429)."""
    created = 0
    if pids is None:
        pids = list_pids(proc_root)
    # every pid in a network namespace sees the identical tcp table; parse
    # each namespace once (hostNetwork nodes would otherwise re-parse a
    # 50k-socket table per process at startup)
    tables_by_ns: dict[object, dict[int, tuple[int, int, int, int]]] = {}
    for pid in pids:
        inodes = read_fd_socket_inodes(proc_root, pid)
        if not inodes:
            continue
        pid_dir = Path(proc_root) / str(pid)
        try:
            ns_key = os.stat(pid_dir / "ns" / "net").st_ino
        except OSError:
            ns_key = pid  # no ns info (fixtures): parse per pid
        table = tables_by_ns.get(ns_key)
        if table is None:
            try:
                table = parse_proc_net_tcp((pid_dir / "net" / "tcp").read_text())
            except OSError:
                continue
            tables_by_ns[ns_key] = table
        for fd, inode in inodes.items():
            conn = table.get(inode)
            if conn is None:
                continue
            saddr, sport, daddr, dport = conn
            line = store.get_or_create(pid, fd)
            line.add_value(
                now_ns,
                SockInfo(
                    pid=pid, fd=fd, saddr=saddr, sport=sport, daddr=daddr, dport=dport
                ),
            )
            created += 1
    return created
