"""Sharded multi-worker host ingest (ISSUE 5 tentpole).

PR 1 vectorized ``process_l7`` to ~1M rows/s single-threaded; the
remaining gap to the host plane's per-stage capability is serialization,
not work — numpy releases the GIL on every big op, so N shard workers
running the SAME vectorized path on disjoint shards overlap most of the
wall clock (the FeatGraph / arxiv 2310.12184 shape: keep per-partition
aggregation data-parallel, push the irregular grouping kernel into the
tuned native backend — here ``alz_group_edges``).

Topology (every arrow a bounded queue or a locked hand-off):

    submit (any thread) → hash-partition by connection key (pid, fd)
        → [N worker queues] → shard workers, each running a PRIVATE
          ``Aggregator`` (socket lines, h2 state, stmt caches, path
          caches are per-connection state, and a connection always lands
          on the same worker) over the SHARED thread-safe ``Interner`` /
          ``ClusterInfo``, persisting REQUEST rows into a per-worker
          ``ShardPartialStore`` (window-bucketed raw rows)
        → close waves: when every worker's watermark passes a window,
          the merge thread broadcasts a close request; EACH WORKER then
          aggregates its own shard's window rows into one uid-keyed
          ``EdgePartial`` (one grouped reduction, on the worker thread —
          the expensive stage stays data-parallel)
        → merge thread: recombines the N partials per window with ONE
          more grouped reduction (sum/max per edge key) and assembles
          the ``GraphBatch`` through the shared ``GraphBuilder`` (slot
          assignment happens only here, so it is identical to the
          single-thread path's). With N == 1 there is nothing to
          recombine: the worker deposits its raw rows and the merge
          stage runs ``GraphBuilder.build`` verbatim — the pool adds
          queue hops, not work.

Determinism contract (tests/test_sharded_ingest.py): for the same input
rows, the merged ``GraphBatch`` is identical to the single-thread
``WindowedGraphStore`` output — same edges, features and counts — up to
two documented degrees of freedom: interner id NUMBERING (workers intern
concurrently, so the ids assigned to the same strings can differ between
runs; compare through the strings) and per-uid endpoint-type ties (a uid
seen with two different types keeps whichever its first-mapped row
carried). Feature equality is exact because every reduction input is an
integer-valued float64 (per-window latency sums stay below 2^53 ns).

Lock order (ARCHITECTURE §3g; alazsan-stressed in tests/test_sanitize.py):
worker threads take a store lock OR the progress condition, never both
at once; the merge path takes ``_merge_lock`` → worker-queue locks
(close broadcast) → the progress condition (ack wait) → store locks
(take_ready) → downstream emit locks — one direction only, a DAG by
construction. The supervision plane (ISSUE 6) adds ``_restart_lock`` →
``_wm_cond`` (restart bookkeeping) and keeps re-drives/queue puts
OUTSIDE the progress condition, so no reverse edge appears.

Self-healing (ISSUE 6, ARCHITECTURE §3j): worker threads run under a
supervisor shell — a crash (a chaos-injected ``WorkerCrash`` or an
escaped bug) marks the worker dead and wakes the merge plane, which
restarts the thread against the SAME queue/store/aggregator (none of
that state is thread-affine) and, when a close wave was in flight,
re-drives the close to the restarted worker so ``_await_wave`` can
never wedge on an ack that will not come. Rows in flight on the dying
thread are attributed to the shared :class:`DropLedger` (cause
``dropped``); scatter backpressure past ``shed_block_s`` sheds to the
ledger (cause ``shed``) instead of blocking the producer forever; late
stragglers keep their ``late`` attribution. Conservation — pushed ==
emitted + ledger total — is the chaos suite's checkable invariant.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Union

import numpy as np

from alaz_tpu.aggregator.cluster import ClusterInfo
from alaz_tpu.aggregator.engine import Aggregator, AggregatorStats, _conn_keys
from alaz_tpu.config import RuntimeConfig
from alaz_tpu.datastore.interface import BaseDataStore, DataStore
from alaz_tpu.events.intern import Interner
from alaz_tpu.events.k8s import K8sResourceMessage
from alaz_tpu.graph.builder import (
    EdgePartial,
    GraphBuilder,
    NodeTable,
    partial_from_rows,
)
from alaz_tpu.graph.snapshot import GraphBatch
from alaz_tpu.logging import get_logger
from alaz_tpu.obs.recorder import FlightRecorder
from alaz_tpu.obs.spans import SpanTracer
from alaz_tpu.utils.ledger import DropLedger
from alaz_tpu.utils.queues import BatchQueue, QueueClosed

log = get_logger("alaz_tpu.sharded")


class WorkerCrash(BaseException):
    """A worker thread's injected death (see alaz_tpu/chaos/injectors).

    BaseException-derived so the per-item ``except Exception`` net that
    keeps a shard alive through bad batches cannot absorb it — the
    thread must actually die for the supervisor path to be real."""

_W_FLOOR = -(2**62)  # "no window closed yet" sentinel (below any real id)


class _QItem:
    """One worker-queue element. ``__len__`` is the EVENT count so
    BatchQueue's events-denominated capacity stays truthful (a bare tuple
    would count its arity)."""

    __slots__ = ("kind", "payload", "now_ns")

    def __init__(self, kind: str, payload, now_ns):
        self.kind = kind
        self.payload = payload
        self.now_ns = now_ns

    def __len__(self) -> int:
        p = self.payload
        if type(p) is tuple and len(p) == 2:  # (chunk, shard row index)
            p = p[1]
        shape = getattr(p, "shape", None)
        return int(shape[0]) if shape else 1


def _shard_rows(payload) -> np.ndarray:
    """Materialize a scattered slice: the scatter ships ``(chunk, idx)``
    so the record gather runs on the worker thread, not the submitter."""
    if type(payload) is tuple:
        chunk, idx = payload
        return chunk[idx]
    return payload


class ShardPartialStore(BaseDataStore):
    """One shard worker's DataStore sink: buckets persisted REQUEST rows
    into time windows (raw — bucketing is one cheap copy, exactly what
    the serial store pays) and, on a close request, aggregates each
    closed window's rows into a uid-keyed :class:`EdgePartial` **on the
    worker thread** — the grouped reduction is the expensive stage and
    runs in parallel across shards, outside any lock.

    Single-producer: exactly one worker thread calls persist_requests and
    close_upto; ``_local_nodes`` (the private grouping table) is
    worker-thread-only and never locked. The lock covers the window map,
    the ready shelf and the counters, which the merge thread also
    touches."""

    def __init__(
        self,
        window_ms: int,
        label_fn=None,
        aggregate: bool = True,
        ledger: Optional[DropLedger] = None,
        tracer: Optional[SpanTracer] = None,
    ):
        self.window_ms = int(window_ms)
        self.label_fn = label_fn
        # shared span tracer (ISSUE 9): first-row marks + per-shard
        # close timings; per window×stage, never per row
        self.tracer = tracer
        # False (the N==1 pool): deposit raw rows; the merge stage then
        # runs the serial GraphBuilder.build verbatim — no partial pass
        self.aggregate = aggregate
        # shared pipeline-wide loss accounting (late stragglers land here
        # in addition to the store-local counter)
        self.ledger = ledger
        self._local_nodes = NodeTable()  # worker-thread-only grouping aid
        self._pending: Dict[int, List[np.ndarray]] = {}  # guarded-by: self._lock
        # closed-and-aggregated windows awaiting the merge thread:
        # window id → EdgePartial (aggregate=True) | raw row array
        self._ready: Dict[int, Union[EdgePartial, np.ndarray]] = {}  # guarded-by: self._lock
        self._watermark: Optional[int] = None  # guarded-by: self._lock
        self._closed_upto = _W_FLOOR  # guarded-by: self._lock
        self.request_count = 0  # guarded-by: self._lock
        self.late_dropped = 0  # guarded-by: self._lock
        self.last_persist_monotonic: Optional[float] = None  # guarded-by: self._lock
        self._lock = threading.Lock()

    # -- DataStore surface (the worker's Aggregator persists here) ---------

    def persist_requests(self, batch: np.ndarray) -> None:
        with self._lock:
            self.last_persist_monotonic = time.monotonic()
            n = int(batch.shape[0])
            self.request_count += n
            if n == 0:
                return
            wids = batch["start_time_ms"] // self.window_ms
            wmin, wmax = int(wids.min()), int(wids.max())
            if wmin == wmax:
                # dominant steady-state shape: whole chunk in one window.
                # Copy — the rows are retained across calls and the
                # caller may reuse its buffer (the serial store's rule).
                present: Union[np.ndarray, List[int]] = [wmin]
            elif wmax - wmin < (1 << 20):
                present = np.flatnonzero(np.bincount(wids - wmin)) + wmin
            else:  # degenerate timestamps: don't size a bincount by span
                present = np.unique(wids)
            for w in present:
                w = int(w)
                if w <= self._closed_upto:
                    # stragglers for an already-closed window (the
                    # aggregator retry path, or chaos-delayed delivery):
                    # drop, never re-emit
                    k = n if wmin == wmax else int((wids == w).sum())
                    self.late_dropped += k
                    if self.ledger is not None:
                        self.ledger.add("late", k)
                    continue
                rows = batch.copy() if wmin == wmax else batch[wids == w]
                self._pending.setdefault(w, []).append(rows)
                # span origin (idempotent — first shard to see the
                # window wins; lock order: store lock → tracer lock)
                if self.tracer is not None:
                    self.tracer.first_row(w * self.window_ms)
                if self._watermark is None or w > self._watermark:
                    self._watermark = w

    # -- worker-side close ---------------------------------------------------

    def close_upto(self, upto: Optional[int]) -> None:
        """Pop every pending window ≤ ``upto`` (None = all), aggregate it
        on the calling (worker) thread, shelve the result for the merge
        thread, and seal the horizon so later rows drop as late.

        Windows the sealed horizon ALREADY passed (``seal_upto`` ran
        while this store still held their rows — only reachable through
        a crash/restart interleave) are late-dropped here instead of
        shelved: re-emitting a merged window would corrupt every
        downstream consumer, losing attributed rows merely degrades."""
        with self._lock:
            if upto is None:
                upto = max(self._pending, default=self._closed_upto)
                if self._watermark is not None:
                    upto = max(upto, self._watermark)
            floor = self._closed_upto
            popped = {w: ps for w, ps in self._pending.items() if w <= upto}
            for w in popped:
                del self._pending[w]
            stale_rows = 0
            for w in [w for w in popped if w <= floor]:
                stale_rows += sum(int(p.shape[0]) for p in popped.pop(w))
            if stale_rows:
                self.late_dropped += stale_rows
            if upto > self._closed_upto:
                self._closed_upto = upto
        if stale_rows and self.ledger is not None:
            self.ledger.add("late", stale_rows, reason="sealed_horizon")
        # the grouped reduction runs OUTSIDE the lock: it is the heavy
        # stage, and it must overlap across worker threads
        done: List[tuple] = []
        tr = self.tracer
        for w, parts in sorted(popped.items()):
            ws_ms = w * self.window_ms
            if tr is not None:
                # the close wave reached this window: residency since
                # first_row becomes `scatter` (first shard to close wins)
                tr.close_start(ws_ms)
            tc0 = time.perf_counter()
            rows = np.concatenate(parts) if len(parts) > 1 else parts[0]
            if self.aggregate:
                labels = self.label_fn(rows) if self.label_fn is not None else None
                done.append((w, partial_from_rows(rows, self._local_nodes, labels)))
            else:
                done.append((w, rows))
            if tr is not None:
                # per-shard parallel closes all report; the span keeps
                # the max — the critical-path shard
                tr.observe(ws_ms, "shard_close", time.perf_counter() - tc0)
        if done:
            with self._lock:
                for w, item in done:
                    self._ready[w] = item

    # -- merge-side surface --------------------------------------------------

    @property
    def watermark(self) -> Optional[int]:
        with self._lock:
            return self._watermark

    def take_ready(self, upto: Optional[int]) -> Dict[int, Union[EdgePartial, np.ndarray]]:
        """Remove and return shelved windows ≤ ``upto`` (None = all)."""
        with self._lock:
            if upto is None:
                done = dict(self._ready)
                self._ready.clear()
            else:
                done = {w: p for w, p in self._ready.items() if w <= upto}
                for w in done:
                    del self._ready[w]
            return done

    def seal_upto(self, upto: int) -> None:
        """Advance the never-reopen floor (applied globally after a merge
        so EVERY store agrees on the merged horizon, even stores that had
        no rows for those windows)."""
        with self._lock:
            if upto > self._closed_upto:
                self._closed_upto = upto


class ShardedIngest:
    """N-worker sharded ingest pipeline with close-wave merging.

    Duck-types the ``Aggregator`` ingestion surface (``process_l7`` /
    ``process_tcp`` / ``process_proc`` / ``process_k8s`` / ``gc`` /
    ``reap_zombies`` / ``flush_retries``) and the windowed-store surface
    (``flush`` / ``late_dropped`` / ``last_persist_monotonic`` /
    ``on_batch``), so `runtime.service.Service` can swap it in for the
    serial pair. Ingestion calls are asynchronous: they partition by
    connection key and enqueue; closed windows emit on the merge thread.

    ``tee`` (optional) is an extra DataStore every worker's emitted
    REQUEST rows fan out to (the export-backend leg). It is called from
    N worker threads concurrently and must be thread-safe — the batching
    export backend (queue-fronted) is; bespoke sinks must lock.
    """

    def __init__(
        self,
        n_workers: int,
        interner: Optional[Interner] = None,
        config: Optional[RuntimeConfig] = None,
        cluster: Optional[ClusterInfo] = None,
        window_s: float = 1.0,
        on_batch: Optional[Callable[[GraphBatch], None]] = None,
        label_fn=None,
        renumber: bool = False,
        tee: Optional[DataStore] = None,
        queue_events: int = 1 << 18,
        autostart: bool = True,
        ledger: Optional[DropLedger] = None,
        fault_hook: Optional[Callable[[int, str], None]] = None,
        shed_block_s: float = 5.0,
        degree_cap: int = 0,
        sample_seed: int = 0,
        tracer: Optional[SpanTracer] = None,
        recorder: Optional[FlightRecorder] = None,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.n = int(n_workers)
        # unified loss accounting (ISSUE 6): every row this pipeline
        # loses lands in exactly one ledger cause — the conservation
        # invariant the chaos suite checks
        self.ledger = ledger if ledger is not None else DropLedger()
        # span plane (ISSUE 9): ON by default. A standalone pipeline
        # (bench, chaos harness) gets a private tracer whose spans
        # complete at emit; the service passes its metrics-registered
        # tracer, which stays open through score/export.
        if tracer is None:
            tracer = SpanTracer(complete_at_emit=True, recorder=recorder)
        self.tracer = tracer
        # flight recorder (ISSUE 9): worker crashes/restarts and every
        # ledger decision become structured ring events; a dying worker
        # dumps the tail to the log automatically
        self.recorder = recorder
        if recorder is not None and self.ledger.recorder is None:
            self.ledger.recorder = recorder
        # chaos seam: called as fault_hook(worker_idx, kind) at item
        # boundaries on the worker thread; may raise WorkerCrash or stall
        self.fault_hook = fault_hook  # lockless-ok: attach-once chaos seam (wiring or harness, before traffic flows); workers null-check an atomic reference read
        # scatter backpressure bound: a producer blocks at most this long
        # on a backlogged shard queue before the rows shed to the ledger
        # (a stalled/dead worker must not wedge the submitting thread)
        self.shed_block_s = float(shed_block_s)
        self.interner = interner if interner is not None else Interner()
        self.config = config if config is not None else RuntimeConfig()
        self.cluster = (
            cluster if cluster is not None else ClusterInfo(self.interner)
        )
        self.window_s = window_s
        self.window_ms = int(window_s * 1000)
        self.on_batch = on_batch
        # in-class appends happen inside the close-wave merge region;
        # main reads .batches only after stop()/join (happens-before)
        self.batches: List[GraphBatch] = []  # guarded-by: self._merge_lock
        # the cap applies HERE, at the merge-stage assembly, never in the
        # per-shard partials: each worker sees only its shard's slice of
        # a dst's fan-in, so capping early would make the sample depend
        # on worker count — the merge sees the whole window, and the
        # priority hash (seed, window, uids) makes N∈{1..} select
        # identically (ISSUE 7 N-invariance contract)
        self.builder = GraphBuilder(
            window_s=window_s, renumber=renumber,
            degree_cap=degree_cap, sample_seed=sample_seed,
            ledger=self.ledger, tracer=self.tracer,
        )
        self.label_fn = label_fn
        self.tee = tee

        self.stores = [
            ShardPartialStore(
                self.window_ms,
                # N == 1: the close wave deposits raw rows and the merge
                # stage IS GraphBuilder.build — label_fn then applies at
                # build time exactly like the serial store
                label_fn=label_fn if self.n > 1 else None,
                aggregate=self.n > 1,
                ledger=self.ledger,
                tracer=self.tracer,
            )
            for _ in range(self.n)
        ]
        self.workers = [
            Aggregator(
                self._worker_sink(self.stores[i]),
                interner=self.interner,
                config=self.config,
                cluster=self.cluster,
                # semantic drops (filtered) join the SHARED ledger so the
                # pipeline's conservation reads delivered == emitted +
                # ledger.total with no per-worker side channel (ISSUE 8)
                ledger=self.ledger,
                recorder=recorder,
            )
            for i in range(self.n)
        ]
        # engine backend (ISSUE 16): when the config (or the A/B
        # override) asks for the native L7 engine, dlopen + layout-check
        # it at pool construction — the first traffic batch must not pay
        # the load, and a missing .so warns HERE, not mid-traffic
        if self.workers[0]._use_native_engine():
            loaded = all(
                w._native_l7_engine() is not None for w in self.workers
            )
            log.info(
                f"sharded ingest L7 engine backend: native "
                f"(loaded={loaded}, workers={self.n})"
            )
        self._queues = [
            BatchQueue(queue_events, f"shard{i}") for i in range(self.n)
        ]

        # progress plane: per-worker processed watermark, close-wave acks
        # and the merged horizon, all published under one condition
        self._wm_cond = threading.Condition()
        self._worker_wm: List[Optional[int]] = [None] * self.n  # guarded-by: self._wm_cond
        # scatters mid-flight: rows handed to process_l7 but not yet on
        # every worker queue. While nonzero the idle-watermark close rule
        # is suppressed — closing on "idle" workers whose slice of the
        # current chunk hasn't landed yet would late-drop it.
        self._inflight = 0  # guarded-by: self._wm_cond
        # wave id → set of worker indices that acked. A SET, not a
        # count: a restarted worker sees both the original close item
        # (queued behind its backlog) and the re-driven one — counting
        # it twice would let a wave complete before some OTHER worker
        # closed its shard, and the merge would seal rows that store
        # still holds (the seed-0 duplicate-emission bug).
        self._wave_acks: Dict[int, set] = {}  # guarded-by: self._wm_cond
        self._wave_seq = 0  # guarded-by: self._wm_cond
        self._merged_upto = _W_FLOOR  # guarded-by: self._wm_cond
        # serializes whole close waves (merge thread vs flush callers)
        self._merge_lock = threading.Lock()
        self.merge_s = 0.0  # merge-stage wall time (recombine+assemble)  # guarded-by: self._merge_lock
        self.windows_merged = 0  # guarded-by: self._merge_lock

        # supervision plane (ISSUE 6): per-worker thread handles so a
        # dead worker can be restarted in place; _worker_dead is the
        # dying thread's wake signal to anyone blocked on the condition
        self._restart_lock = threading.Lock()
        self._worker_threads: List[Optional[threading.Thread]] = []  # guarded-by: self._restart_lock
        self._merge_thread: Optional[threading.Thread] = None  # guarded-by: self._restart_lock
        self._worker_dead = [False] * self.n  # guarded-by: self._wm_cond
        self._worker_restarts = 0  # guarded-by: self._restart_lock
        # per-worker restart generation: close-wave re-drives key off
        # "was worker i restarted since this wave began", NOT "did MY
        # _supervise call do the restart" — the merger's supervision
        # heartbeat races the wave-waiter's, and whoever loses that race
        # must still re-drive (the original close died with the thread)
        self._worker_gen = [0] * self.n  # guarded-by: self._restart_lock
        self._last_wave_monotonic = time.monotonic()  # merge liveness gauge  # lockless-ok: written inside the merge lock's bounded-acquire region (which the lockset walk models since ISSUE 19); the sanction covers the racy float READ — it IS the last_wave_age_s freshness gauge. Every site is a plain float store/read, never a container mutation, so GIL-atomicity holds

        self._stop = threading.Event()
        if autostart:
            self.start()

    def _worker_sink(self, store: ShardPartialStore) -> DataStore:
        if self.tee is None:
            return store
        from alaz_tpu.runtime.service import FanoutDataStore

        return FanoutDataStore([store, self.tee])

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        with self._restart_lock:
            if self._worker_threads or self._merge_thread is not None:
                return
            self._stop.clear()
            for i in range(self.n):
                t = threading.Thread(
                    target=self._worker_main, args=(i,), name=f"alaz-shard{i}",
                    daemon=True,
                )
                t.start()
                self._worker_threads.append(t)
            t = threading.Thread(
                target=self._merger_loop, name="alaz-shard-merge", daemon=True
            )
            t.start()
            self._merge_thread = t

    def stop(self) -> None:
        self._stop.set()  # BEFORE the snapshot: _supervise refuses
        # restarts once set, so no thread can appear after we collect
        for q in self._queues:
            q.close()
        with self._wm_cond:
            self._wm_cond.notify_all()
        with self._restart_lock:
            threads = [t for t in self._worker_threads if t is not None]
            if self._merge_thread is not None:
                threads.append(self._merge_thread)
            self._worker_threads = []
            self._merge_thread = None
        for t in threads:
            t.join(timeout=5)

    def close(self) -> None:
        self.stop()

    # -- supervision (ISSUE 6) ----------------------------------------------

    @property
    def worker_restarts(self) -> int:
        with self._restart_lock:
            return self._worker_restarts

    @property
    def last_wave_age_s(self) -> float:
        """Seconds since the last close wave completed its merge — the
        gauge that makes a stalled merge thread visible."""
        return time.monotonic() - self._last_wave_monotonic

    def _worker_main(self, i: int) -> None:
        """Supervisor shell around the worker loop: any escape — a chaos
        WorkerCrash or a real bug — marks the worker dead and wakes the
        merge plane (which restarts it) instead of leaving every future
        wave to wedge on a silent missing ack."""
        try:
            self._worker_loop(i)
            return  # clean shutdown path (stop/close)
        except WorkerCrash:
            log.warning(f"shard{i} worker killed (injected crash)")
            reason = "injected_crash"
        except BaseException as exc:
            log.error(f"shard{i} worker died: {exc!r}")
            reason = repr(exc)
        if self.recorder is not None:
            # the crash trail ships WITH the crash: the event lands in
            # the ring and the ring's tail lands in the log, so a chaos
            # failure (or a real one) reads as a story, not a bare mark.
            # Best-effort: a recorder/logging failure here must never
            # swallow the dead-mark below — that would permanently
            # disable supervision of this worker (no restart, every
            # future close wave timing out)
            try:
                self.recorder.record("worker_crash", worker=i, reason=reason)
                self.recorder.crash_dump(log, f"shard{i} worker died: {reason}")
            except Exception as exc:
                log.error(f"flight-recorder crash dump failed: {exc!r}")
        with self._wm_cond:
            self._worker_dead[i] = True
            self._wm_cond.notify_all()

    def _supervise(self) -> List[int]:
        """Restart every worker whose thread died; returns the restarted
        indices so a waiting close wave can re-drive its close request.
        The restarted thread resumes the SAME queue, store and private
        aggregator — none of that state died with the thread — so the
        shard's backlog (including any queued close items) drains in
        order exactly as if the worker had merely stalled."""
        restarted: List[int] = []
        if self._stop.is_set():
            return restarted
        with self._restart_lock:
            if not self._worker_threads:
                return restarted  # never started / already stopped
            for i in range(self.n):
                t = self._worker_threads[i]
                if t is None or t.is_alive():
                    continue
                self._worker_restarts += 1
                self._worker_gen[i] += 1
                with self._wm_cond:
                    self._worker_dead[i] = False
                nt = threading.Thread(
                    target=self._worker_main, args=(i,),
                    name=f"alaz-shard{i}r{self._worker_restarts}", daemon=True,
                )
                self._worker_threads[i] = nt
                nt.start()
                restarted.append(i)
                if self.recorder is not None:
                    self.recorder.record(
                        "worker_restart", worker=i,
                        restart=self._worker_restarts,
                    )
                log.warning(
                    f"shard{i} worker restarted "
                    f"(restart #{self._worker_restarts})"
                )
        return restarted

    def _gen_snapshot(self) -> List[int]:
        with self._restart_lock:
            return list(self._worker_gen)

    # -- ingestion surface (Aggregator duck type) ----------------------------

    def process_l7(self, events: np.ndarray, now_ns: Optional[int] = None) -> None:
        """Partition an L7 batch by connection key and enqueue per-shard
        slices. Asynchronous: returns before processing (the serial
        Aggregator returns the emitted rows; callers needing per-batch
        edge counts read the aggregated ``stats`` instead)."""
        self._scatter("l7", events, now_ns)

    def process_tcp(self, events: np.ndarray, now_ns: Optional[int] = None) -> None:
        self._scatter("tcp", events, now_ns)

    def process_proc(self, events: np.ndarray) -> None:
        # proc exit tears down per-pid state on EVERY worker that may own
        # one of the pid's connections — (pid, fd) sharding splits a
        # pid's fds across workers, so the event broadcasts
        self._broadcast("proc", events)

    def process_k8s(self, msg: K8sResourceMessage) -> None:
        # cluster state is shared (thread-safe _IpTable) — fold once,
        # from the caller's thread, exactly like the serial engine
        self.cluster.handle_msg(msg)
        if self.tee is not None:
            self.tee.persist_resource(msg.resource_type, msg.event_type, msg.object)

    def gc(self, now_ns: Optional[int] = None) -> None:
        """Housekeeping broadcast: each worker gc's its own aggregator ON
        its own thread, so socket-line/h2 state is never mutated from the
        housekeeping thread while a worker joins against it."""
        self._broadcast("gc", now_ns)

    def reap_zombies(self) -> None:
        self._broadcast("reap", None)

    def flush_retries(self, now_ns: int):
        """Timer-driven retry flush, broadcast to the owning workers.
        Returns None (retried rows surface through ``stats`` and the
        merged windows, not a return value — the serial path's contract
        of returning the rows cannot survive the queue hop)."""
        self._broadcast("retries", now_ns)
        return None

    def _put_or_shed(self, i: int, item: _QItem) -> None:
        """Bounded-backpressure enqueue: block at most ``shed_block_s``
        on a backlogged shard queue, then SHED the rows to the ledger —
        a stalled or dead worker must cost data (attributed), never
        wedge the submitting thread (the drop-not-block contract, one
        hop deeper). A queue closed by a racing stop() drops the item's
        rows too — ATTRIBUTED (alazflow ALZ043 found the old bare
        ``except QueueClosed: pass`` losing them untracked): per-item
        here, so shards that enqueued before the close keep their exact
        counts and only the rows that truly never landed are ledgered."""
        try:
            if self._queues[i].put(item, timeout=self.shed_block_s):
                return
        except QueueClosed:
            self.ledger.add("dropped", len(item), reason="closed")
            return
        n = len(item)
        self.ledger.add("shed", n, reason=f"shard{i}_backlog")
        log.warning(f"shard{i} backlogged past {self.shed_block_s}s; shed {n} rows")

    def _scatter(self, kind: str, events: np.ndarray, now_ns) -> None:
        with self._wm_cond:
            self._inflight += 1
        try:
            if self.n == 1:
                self._put_or_shed(0, _QItem(kind, events, now_ns))
                return
            shard = (
                _conn_keys(events["pid"], events["fd"]) % np.uint64(self.n)
            ).astype(np.int64)
            for i in range(self.n):
                idx = np.flatnonzero(shard == i)
                if idx.shape[0]:
                    # ship (chunk, index) and let the WORKER extract its
                    # slice: the 320-byte-record gather is a real copy,
                    # and doing it here would serialize N copies on the
                    # submitting thread
                    self._put_or_shed(i, _QItem(kind, (events, idx), now_ns))
        finally:
            with self._wm_cond:
                self._inflight -= 1
                self._wm_cond.notify_all()

    def _broadcast(self, kind: str, payload) -> None:
        """Control-plane broadcast (close/gc/proc/...): must DELIVER, so
        it retries a full queue instead of shedding — but a queue stays
        full forever only when its worker died, so each retry round
        supervises (restarts dead workers) to unwedge itself."""
        for i, q in enumerate(self._queues):
            item = _QItem(kind, payload, None)
            try:
                while not q.put(item, timeout=0.5):
                    if self._stop.is_set():
                        return
                    self._supervise()
            except QueueClosed:
                pass

    # -- worker / merger loops -----------------------------------------------

    def _worker_loop(self, i: int) -> None:
        q = self._queues[i]
        agg = self.workers[i]
        store = self.stores[i]
        last_wm: Optional[int] = None
        while True:
            item = q.get(timeout=0.1)
            if item is None:
                if self._stop.is_set() or q.closed:
                    return
                continue
            kind, payload, now_ns = item.kind, item.payload, item.now_ns
            try:
                if self.fault_hook is not None:
                    # chaos seam: fires at the item boundary (all-or-
                    # nothing row accounting), may raise WorkerCrash
                    self.fault_hook(i, kind)
                if kind == "l7":
                    agg.process_l7(_shard_rows(payload), now_ns=now_ns)
                elif kind == "tcp":
                    agg.process_tcp(_shard_rows(payload), now_ns=now_ns)
                elif kind == "close":
                    wave, upto = payload
                    try:
                        store.close_upto(upto)
                    finally:
                        # the ack must flow even if aggregation raised —
                        # a silent miss would strand the wave until stop.
                        # Membership-guarded: a straggler ack for a wave
                        # that already completed (or timed out) must not
                        # resurrect its entry. Per-worker set: a
                        # restarted worker acking both the original and
                        # the re-driven close counts ONCE.
                        with self._wm_cond:
                            if wave in self._wave_acks:
                                self._wave_acks[wave].add(i)
                            self._wm_cond.notify_all()
                elif kind == "proc":
                    agg.process_proc(payload)
                elif kind == "retries":
                    agg.flush_retries(
                        payload if payload is not None else time.time_ns()
                    )
                elif kind == "gc":
                    agg.gc(payload)
                elif kind == "reap":
                    agg.reap_zombies()
            except WorkerCrash:
                # the thread dies with this item in flight: attribute its
                # rows before going (conservation survives the crash),
                # then let the supervisor shell take over. ONLY L7 rows
                # carry weight in the conservation books (the process
                # backend's kill-settle rule, process_pool.py): a TCP
                # establish never becomes a REQUEST row, so ledgering a
                # crashed tcp item counts rows no numerator pushed —
                # the per-tenant gate reads that as a negative gap. The
                # row-visible consequence of the lost socket state is
                # ledgered downstream as filtered/no_socket.
                if kind == "l7":
                    self.ledger.add("dropped", len(item), reason="worker_crash")
                raise
            except Exception as exc:  # keep the shard alive; mirror service workers
                # the failed batch's rows reach neither emit nor retry —
                # attribute them (alazflow ALZ043) so conservation holds
                # through a poison batch, not just through injected
                # crashes. Attribution errs toward overcounting when the
                # engine emitted part of the batch before raising; a
                # negative gap is the loud failure mode, not a silent one.
                # L7-only, same contract as the crash path above.
                if kind == "l7":
                    self.ledger.add("dropped", len(item), reason="batch_error")
                log.warning(f"shard{i} {kind} batch failed: {exc}")
            finally:
                q.task_done()
            if kind in ("l7", "retries"):
                wm = store.watermark
                if wm is not None and wm != last_wm:
                    last_wm = wm
                    with self._wm_cond:
                        self._worker_wm[i] = wm
                        self._wm_cond.notify_all()

    def _closable_locked(self) -> Optional[int]:
        """Highest window id safe to close, or None. Caller holds
        ``_wm_cond``. Workers with QUEUED work constrain the close (their
        backlog may hold older windows): min over their processed
        watermarks, the serial close rule taken shard-wise. Workers that
        are idle (everything delivered is processed) do NOT hold the
        horizon back — a shard whose connections simply went quiet must
        not stall emission forever — so with every worker idle the rule
        degenerates to max(watermark) - 1, exactly the serial store's.
        Idle-based closes are suppressed while a scatter is mid-flight
        (``_inflight``): an "idle" worker whose slice of the current
        chunk hasn't been enqueued yet isn't idle, it's early. Rows a
        quiet shard receives later for a closed window drop as late —
        the same fate the serial path gives rows behind the watermark."""
        busy: List[int] = []
        idle: List[int] = []
        for i in range(self.n):
            wm = self._worker_wm[i]  # alazlint: disable=ALZ010 -- both callers hold self._wm_cond (documented caller-holds-lock helper; the lint pass is intra-function)
            if self._queues[i].unfinished:
                if wm is None:
                    return None  # a worker with queued work hasn't started
                busy.append(wm)
            elif wm is not None:
                idle.append(wm)
        if busy:
            return min(busy) - 1
        if idle and not self._inflight:  # alazlint: disable=ALZ010 -- caller holds self._wm_cond, see above
            return max(idle) - 1
        return None

    def _merger_loop(self) -> None:
        while not self._stop.is_set():
            with self._wm_cond:
                closable = self._closable_locked()
                if closable is None or closable <= self._merged_upto:
                    self._wm_cond.wait(0.2)
                    closable = self._closable_locked()
                ready = closable is not None and closable > self._merged_upto
            if self._stop.is_set():
                return
            # supervision heartbeat: a worker that died outside any wave
            # (mid-l7) would otherwise pin _closable_locked to None via
            # its stale watermark + growing backlog, stalling every
            # window silently — restart it here, wave or no wave
            self._supervise()
            if ready:
                # bounded even on the merge thread: _await_wave self-
                # heals dead workers, so the bound only trips on a
                # pathological stall — in which case the merger must
                # come back to supervise rather than wedge forever
                self._run_close_wave(closable, timeout_s=60.0)

    def _run_close_wave(
        self, upto: Optional[int], timeout_s: Optional[float] = None
    ) -> bool:
        """One full close wave: broadcast the close request, wait for
        every worker's ack (each has aggregated its shard by then),
        recombine + assemble + emit in window order. Serialized under
        ``_merge_lock`` (merge thread vs flush callers), so emission
        order is globally window-ascending. With ``timeout_s`` the whole
        wave — including the wait for a concurrent wave's lock — is
        bounded: on expiry it returns False with shelved windows intact
        (the next wave merges them); True once the merge ran."""
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        if timeout_s is None:
            self._merge_lock.acquire()  # alazlint: disable=ALZ012,ALZ042 -- paired with the finally below; the timeout branch needs acquire(timeout=...) and `with` can't express it. Unbounded only when the CALLER passed timeout_s=None, an explicit opt-in (every entry-surface caller passes a budget)
        elif not self._merge_lock.acquire(timeout=timeout_s):  # alazlint: disable=ALZ012 -- bounded acquire (a stalled merge must not wedge flush); released in the finally
            log.error(
                f"close wave: merge lock not free within {timeout_s}s "
                "(stalled merge?); giving up this wave"
            )
            return False
        try:
            # restart-generation baseline BEFORE the broadcast: a worker
            # restarted between here and the ack wait shows as a gen
            # bump, and _await_wave re-drives its close regardless of
            # WHICH thread's supervision performed the restart
            gen0 = self._gen_snapshot()
            wave = self._start_wave()
            self._broadcast("close", (wave, upto))
            remaining = (
                None if deadline is None
                else max(deadline - time.monotonic(), 0.05)
            )
            if not self._await_wave(wave, upto, remaining, gen0):
                return False  # stopped or timed out mid-wave
            t0 = time.perf_counter()
            taken = [s.take_ready(upto) for s in self.stores]
            windows = sorted(set().union(*[set(t) for t in taken]))
            if windows:
                horizon = windows[-1]
                for s in self.stores:
                    s.seal_upto(horizon)
            for w in windows:
                parts = [t[w] for t in taken if w in t]
                if self.n == 1:
                    # single shard: the serial builder path verbatim
                    rows = parts[0]
                    labels = (
                        self.label_fn(rows) if self.label_fn is not None else None
                    )
                    batch = self.builder.build(
                        rows,
                        window_start_ms=w * self.window_ms,
                        window_end_ms=(w + 1) * self.window_ms,
                        edge_label=labels,
                    )
                else:
                    batch = self.builder.build_from_partials(
                        parts,
                        window_start_ms=w * self.window_ms,
                        window_end_ms=(w + 1) * self.window_ms,
                    )
                if self.on_batch is not None:
                    self.on_batch(batch)
                else:
                    self.batches.append(batch)
                # completes the span here when no scorer follows
                # (complete_at_emit); the service's tracer keeps it open
                self.tracer.emit(w * self.window_ms)
            self.merge_s += time.perf_counter() - t0
            self.windows_merged += len(windows)
            self._last_wave_monotonic = time.monotonic()
        finally:
            self._merge_lock.release()
        # advance the merged horizon to the WAVE's target even when no
        # window had rows — otherwise an empty wave never moves it and
        # the merger loop re-broadcasts the same close at full spin
        target = upto
        if windows and (target is None or windows[-1] > target):
            target = windows[-1]
        if target is not None:
            with self._wm_cond:
                if target > self._merged_upto:
                    self._merged_upto = target
        return True

    def _start_wave(self) -> int:
        with self._wm_cond:
            self._wave_seq += 1
            wave = self._wave_seq
            self._wave_acks[wave] = set()
            return wave

    def _await_wave(
        self,
        wave: int,
        upto: Optional[int],
        timeout_s: Optional[float],
        gen0: List[int],
    ) -> bool:
        """Wait for every worker's close ack, self-healing as it waits:
        a worker that died can never ack, so each poll round restarts
        dead workers and RE-DRIVES the close to any worker whose restart
        GENERATION moved past the wave-start baseline ``gen0`` without
        an ack — whichever thread's supervision actually performed the
        restart (the merger heartbeat races this waiter; keying off "my
        _supervise restarted it" loses that race and strands the wave).
        The original close item died with the crashed thread or sits
        behind a backlog the restarted thread drains first — a duplicate
        close is idempotent: the store pops nothing new and the
        straggler ack is a per-worker set entry. Returns False when
        stopped or when ``timeout_s`` expires first."""
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        seen_gen = list(gen0)
        while True:
            with self._wm_cond:
                if len(self._wave_acks.get(wave, ())) >= self.n:
                    del self._wave_acks[wave]
                    return True
                if self._stop.is_set():
                    self._wave_acks.pop(wave, None)
                    return False
                self._wm_cond.wait(0.2)
                if len(self._wave_acks.get(wave, ())) >= self.n:
                    del self._wave_acks[wave]
                    return True
                acked = set(self._wave_acks.get(wave, ()))
            if deadline is not None and time.monotonic() > deadline:
                with self._wm_cond:
                    self._wave_acks.pop(wave, None)
                log.error(
                    f"close wave {wave} timed out awaiting worker acks"
                )
                return False
            # outside the condition (lock order: never queue-put under
            # _wm_cond): restart the dead, re-drive restarted non-ackers
            self._supervise()
            cur = self._gen_snapshot()
            for i in range(self.n):
                if cur[i] != seen_gen[i] and i not in acked:
                    if self._redrive_close(i, wave, upto, deadline):
                        seen_gen[i] = cur[i]
                    # on failure seen_gen stays: the next poll round
                    # retries the re-drive (gen still differs)

    def _redrive_close(
        self, i: int, wave: int, upto: Optional[int], deadline: Optional[float]
    ) -> bool:
        """Bounded, self-healing re-drive: each retry round supervises
        (the restarted worker may have crashed AGAIN with its queue at
        capacity — without a restart nothing ever drains it) and the
        wave's own deadline caps the whole attempt, so the merge thread
        can degrade to a timed-out wave but never wedge here."""
        item = _QItem("close", (wave, upto), None)
        while not self._stop.is_set():
            if deadline is not None and time.monotonic() > deadline:
                return False
            try:
                if self._queues[i].put(item, timeout=0.5):
                    return True
            except QueueClosed:
                return False
            self._supervise()
        return False

    # -- windowed-store surface ---------------------------------------------

    def flush(self, timeout_s: float = 30.0) -> bool:
        """Close and merge every open window. The close requests queue
        BEHIND all previously submitted batches, so no pre-drain is
        needed — the wave ack means each worker has processed everything
        that was in flight when flush was called (the serial store's
        watermark-inclusive ``flush()`` semantics).

        BOUNDED (ISSUE 6): returns within ~``timeout_s`` even with a
        worker killed or stalled mid-wave — dead workers restart and the
        close re-drives; a stall longer than the budget yields False
        with all state intact (call again to finish). The regression
        gate: flush/drain may degrade to False, never to a hang."""
        return self._run_close_wave(None, timeout_s=timeout_s)

    def drain(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.unfinished == 0:
                return True
            time.sleep(0.002)
        return False

    @property
    def unfinished(self) -> int:
        return sum(q.unfinished for q in self._queues)

    @property
    def pending_retries(self) -> int:
        return sum(a.pending_retries for a in self.workers)

    @property
    def request_count(self) -> int:
        return sum(s.request_count for s in self.stores)

    @property
    def late_dropped(self) -> int:
        return sum(s.late_dropped for s in self.stores)

    @property
    def last_persist_monotonic(self) -> Optional[float]:
        stamps = [
            s.last_persist_monotonic
            for s in self.stores
            if s.last_persist_monotonic is not None
        ]
        return max(stamps) if stamps else None

    @property
    def stats(self) -> AggregatorStats:
        """Aggregated engine stats across the shard workers (a snapshot —
        the summed object is fresh per read, not shared state)."""
        total = AggregatorStats()
        for a in self.workers:
            for k, v in a.stats.as_dict().items():
                setattr(total, k, getattr(total, k) + v)
        return total
