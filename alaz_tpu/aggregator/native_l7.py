"""Native L7 engine binding (ISSUE 16).

``alz_process_l7`` (native/ingest.cc) executes the ``_process_l7_inner``
join + attribution + REQUEST-row emission body in one C++ pass. This
module owns the Python side of that handoff:

- the **socket-line snapshot**: the store's per-(pid, fd) histories
  flattened into one contiguous arena (lines lexsorted by key, offsets
  array), cached per engine instance and rebuilt only when the store's
  revision counter moves — steady-state batches hand the same arrays over
  again, so the GIL is held only for pointer marshalling;
- the **attribution tables**: `_IpTable._compile()`'s sorted arrays,
  passed by reference (recompiles swap arrays, never mutate in place);
- the **last-match writeback**: the C side flags touched snapshot entries,
  and `SocketLine.touch` folds them back under each line's lock so
  DeleteUnused staleness GC sees native joins exactly like Python ones.

Everything stateful beyond that is the caller's (aggregator/engine.py)
refusal surface: retry scheduling, drop-ledger accounting (the engine
consumes the counts vector — order pinned as
``graph.native.L7_ENGINE_DROP_CAUSES``), outbound reverse-DNS interning,
payload enrichment, h2/kafka reassembly.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from alaz_tpu.aggregator.sockline import SocketLine, SocketLineStore
from alaz_tpu.datastore.dto import REQUEST_DTYPE


def _ptr(a: np.ndarray) -> ctypes.c_void_p:
    return a.ctypes.data_as(ctypes.c_void_p)


class SockSnapshot:
    """The socket-line store flattened for ``alz_process_l7``: entry
    columns concatenated line-major, lines lexsorted by (pid, fd)."""

    __slots__ = (
        "rev", "pid", "fd", "off", "ts", "open_", "saddr", "sport",
        "daddr", "dport", "lines",
    )

    def __init__(self, store: SocketLineStore):
        # record the revision BEFORE flattening: a concurrent mutation
        # mid-build leaves rev behind the store's, so the next batch
        # rebuilds instead of reusing a torn snapshot
        self.rev = store.rev.n
        items = store.items()
        n_lines = len(items)
        self.pid = np.empty(n_lines, dtype=np.uint32)
        self.fd = np.empty(n_lines, dtype=np.uint64)
        exports = []
        for i, ((pid, fd), line) in enumerate(items):
            self.pid[i] = pid
            self.fd[i] = fd
            exports.append(line.export_arrays())  # per-line consistent copy
        order = np.lexsort((self.fd, self.pid))
        self.pid = np.ascontiguousarray(self.pid[order])
        self.fd = np.ascontiguousarray(self.fd[order])
        self.lines: list[SocketLine] = [items[int(j)][1] for j in order]
        lens = np.array(
            [exports[int(j)][0].shape[0] for j in order], dtype=np.int64
        )
        self.off = np.zeros(n_lines + 1, dtype=np.int64)
        np.cumsum(lens, out=self.off[1:])
        total = int(self.off[-1]) if n_lines else 0
        self.ts = np.empty(total, dtype=np.uint64)
        self.open_ = np.empty(total, dtype=np.uint8)
        self.saddr = np.empty(total, dtype=np.uint32)
        self.sport = np.empty(total, dtype=np.uint16)
        self.daddr = np.empty(total, dtype=np.uint32)
        self.dport = np.empty(total, dtype=np.uint16)
        for k, j in enumerate(order):
            ts, open_, saddr, sport, daddr, dport = exports[int(j)]
            a, b = self.off[k], self.off[k + 1]
            self.ts[a:b] = ts
            self.open_[a:b] = open_
            self.saddr[a:b] = saddr
            self.sport[a:b] = sport
            self.daddr[a:b] = daddr
            self.dport[a:b] = dport

    @property
    def n_entries(self) -> int:
        return self.ts.shape[0]


class NativeL7Engine:
    """Per-aggregator handle: owns the snapshot cache (keyed by the
    aggregator's OWN socket-line store revision — engines are not shared
    across aggregators)."""

    def __init__(self, lib):
        self._lib = lib
        self._snap: Optional[SockSnapshot] = None

    def snapshot(self, store: SocketLineStore) -> SockSnapshot:
        snap = self._snap
        if snap is None or snap.rev != store.rev.n:
            snap = SockSnapshot(store)
            self._snap = snap
        return snap

    def process(
        self,
        events: np.ndarray,
        now_ns: int,
        store: SocketLineStore,
        pod_table: tuple[np.ndarray, np.ndarray],
        svc_table: tuple[np.ndarray, np.ndarray],
    ):
        """One native pass over an L7_EVENT_DTYPE batch. Returns
        ``(out_rows, kept_idx, unmatched_idx, n_not_pod)`` with indexes
        ascending in ORIGINAL row order (the numpy boolean-mask order), or
        None when the call cannot run (caller falls back to Python)."""
        n = events.shape[0]
        events = np.ascontiguousarray(events)
        snap = self.snapshot(store)
        pod_ips, pod_uids = pod_table
        svc_ips, svc_uids = svc_table
        out = np.zeros(n, dtype=REQUEST_DTYPE)
        kept_idx = np.empty(n, dtype=np.int64)
        unmatched_idx = np.empty(n, dtype=np.int64)
        counts = np.zeros(2, dtype=np.int64)
        touched = np.zeros(max(snap.n_entries, 1), dtype=np.uint8)
        emitted = int(
            self._lib.alz_process_l7(
                _ptr(events), n, now_ns,
                _ptr(snap.pid), _ptr(snap.fd), _ptr(snap.off),
                snap.pid.shape[0],
                _ptr(snap.ts), _ptr(snap.open_), _ptr(snap.saddr),
                _ptr(snap.sport), _ptr(snap.daddr), _ptr(snap.dport),
                _ptr(touched),
                _ptr(pod_ips), _ptr(pod_uids), pod_ips.shape[0],
                _ptr(svc_ips), _ptr(svc_uids), svc_ips.shape[0],
                _ptr(out), _ptr(kept_idx), _ptr(unmatched_idx), _ptr(counts),
            )
        )
        if emitted < 0:  # defensive: no current failure mode returns < 0
            return None
        if now_ns and snap.n_entries and touched.any():
            # fold last-match marks back into the authoritative lines —
            # identical to get_values' `_last_match[np.unique(si)] = now`
            t_idx = np.flatnonzero(touched[: snap.n_entries])
            line_of = np.searchsorted(snap.off, t_idx, side="right") - 1
            for ln in np.unique(line_of):
                local = t_idx[line_of == ln] - snap.off[ln]
                snap.lines[int(ln)].touch(local, now_ns)
        return (
            out[:emitted],
            kept_idx[:emitted],
            unmatched_idx[: int(counts[0])],
            int(counts[1]),
        )


def make_engine() -> Optional[NativeL7Engine]:
    """A fresh per-aggregator engine handle, or None when the .so is
    unavailable (stale, unbuilt, or layout-drifted — graph.native's load
    path already logged/raised accordingly)."""
    from alaz_tpu.graph import native

    lib = native._load()
    if lib is None:
        return None
    return NativeL7Engine(lib)


def available() -> bool:
    from alaz_tpu.graph import native

    return native.available()
