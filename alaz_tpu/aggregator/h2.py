"""HTTP/2-gRPC request/response assembly — the G13 analog
(aggregator/data.go:533-810).

L7 events for HTTP2 carry raw frame bytes (the kernel forwards them
unparsed, l7.c:335-379,687-730). Per connection (pid, fd) we keep client-
and server-side HPACK decoders (data.go:93-103) and a stream table pairing
client HEADERS (:method, :path, :authority, content-type→gRPC) with server
HEADERS (:status, grpc-status) (data.go:705-800). Latency is server frame
write time − client frame write time (data.go:586,702). Half-arrived pairs
are reaped after one minute (data.go:551-571).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from alaz_tpu.protocols import hpack, http2

ONE_MINUTE_NS = 60_000_000_000


@dataclass
class _StreamState:
    stream_id: int
    method: str = ""
    path: str = ""
    authority: str = ""
    content_type: str = ""
    client_time_ns: int = 0
    status: int = 0
    grpc_status: int | None = None
    server_time_ns: int = 0
    has_client: bool = False
    has_server: bool = False


@dataclass
class _ConnState:
    client_decoder: hpack.Decoder = field(default_factory=hpack.Decoder)
    server_decoder: hpack.Decoder = field(default_factory=hpack.Decoder)
    streams: dict[int, _StreamState] = field(default_factory=dict)
    # header block spanning HEADERS + CONTINUATION frames, per direction:
    # (stream_id, accumulated block, first frame time) until END_HEADERS
    client_partial: tuple[int, bytes, int] | None = None
    server_partial: tuple[int, bytes, int] | None = None


@dataclass
class CompletedH2Request:
    pid: int
    fd: int
    stream_id: int
    method: str
    path: str
    authority: str
    is_grpc: bool
    status: int
    grpc_status: int | None
    start_time_ns: int
    latency_ns: int
    tls: bool


class Http2Assembler:
    """Thread-safe: feed() runs on the l7 worker while reap() runs on the
    housekeeping ticker."""

    def __init__(self) -> None:
        self._conns: dict[tuple[int, int], _ConnState] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _set_partial(conn: _ConnState, is_client: bool, value) -> None:
        if is_client:
            conn.client_partial = value
        else:
            conn.server_partial = value

    def _conn(self, pid: int, fd: int) -> _ConnState:
        key = (pid, fd)
        st = self._conns.get(key)
        if st is None:
            st = _ConnState()
            self._conns[key] = st
        return st

    def feed(
        self,
        pid: int,
        fd: int,
        is_client: bool,
        payload: bytes,
        write_time_ns: int,
        tls: bool = False,
    ) -> list[CompletedH2Request]:
        """Feed one captured frame buffer; returns any completed requests."""
        with self._lock:
            return self._feed_locked(pid, fd, is_client, payload, write_time_ns, tls)

    def _feed_locked(self, pid, fd, is_client, payload, write_time_ns, tls) -> list[CompletedH2Request]:
        conn = self._conn(pid, fd)
        done: list[CompletedH2Request] = []
        for frame in http2.iter_frames(payload):
            if len(frame.payload) < frame.length:
                # truncated by the capture window — also drop any pending
                # partial: a later CONTINUATION would assemble a block with
                # a missing middle chunk and desync the HPACK table
                self._set_partial(conn, is_client, None)
                continue
            # header blocks may span HEADERS + CONTINUATION frames; hold the
            # partial block per direction until END_HEADERS
            partial = conn.client_partial if is_client else conn.server_partial
            if frame.type == http2.FRAME_HEADERS:
                block = http2.headers_block(frame)
                stream_id = frame.stream_id
                block_time_ns = write_time_ns
            elif frame.type == http2.FRAME_CONTINUATION and partial is not None:
                stream_id, acc, block_time_ns = partial
                if stream_id != frame.stream_id:
                    # interleaved continuation for a different stream is a
                    # protocol error; drop the partial
                    self._set_partial(conn, is_client, None)
                    continue
                block = acc + frame.payload
            else:
                continue
            if not frame.flags & http2.FLAG_END_HEADERS:
                self._set_partial(conn, is_client, (stream_id, block, block_time_ns))
                continue
            self._set_partial(conn, is_client, None)
            decoder = conn.client_decoder if is_client else conn.server_decoder
            try:
                headers = decoder.decode(block)
            except hpack.HpackError:
                continue
            stream = conn.streams.get(stream_id)
            if stream is None:
                stream = _StreamState(stream_id)
                conn.streams[stream_id] = stream
            if is_client:
                stream.has_client = True
                stream.client_time_ns = block_time_ns
                for name, value in headers:
                    if name == ":method":
                        stream.method = value
                    elif name == ":path":
                        stream.path = value
                    elif name == ":authority":
                        stream.authority = value
                    elif name == "content-type":
                        stream.content_type = value
            else:
                # any server HEADERS frame completes the server side, even
                # without a decodable :status — the reference flags
                # ServerHeadersFrameArrived unconditionally (data.go:775-777)
                stream.has_server = True
                stream.server_time_ns = block_time_ns
                for name, value in headers:
                    if name == ":status":
                        try:
                            stream.status = int(value)
                        except ValueError:
                            pass
                    elif name == "grpc-status":
                        try:
                            stream.grpc_status = int(value)
                        except ValueError:
                            pass
            if stream.has_client and stream.has_server:
                done.append(
                    CompletedH2Request(
                        pid=pid,
                        fd=fd,
                        stream_id=stream.stream_id,
                        method=stream.method,
                        path=stream.path,
                        authority=stream.authority,
                        is_grpc=stream.content_type.startswith("application/grpc"),
                        status=stream.status,
                        grpc_status=stream.grpc_status,
                        start_time_ns=stream.client_time_ns,
                        latency_ns=max(0, stream.server_time_ns - stream.client_time_ns),
                        tls=tls,
                    )
                )
                del conn.streams[stream_id]
        return done

    def remove_conn(self, pid: int, fd: int) -> None:
        """Tear down one connection's state on TCP CLOSED — the reference
        deletes h2Parsers on close (data.go:363-380); without this a reused
        (pid, fd) inherits a desynced HPACK table from the prior
        connection."""
        with self._lock:
            self._conns.pop((pid, fd), None)

    def remove_pid(self, pid: int) -> None:
        """Tear down all of a pid's connections on process EXIT
        (data.go:486-494)."""
        with self._lock:
            doomed = [k for k in self._conns if k[0] == pid]
            for k in doomed:
                del self._conns[k]

    def conn_count(self) -> int:
        with self._lock:
            return len(self._conns)

    def reap(self, now_ns: int) -> int:
        """Drop half-arrived pairs older than a minute (data.go:551-571)."""
        with self._lock:
            return self._reap_locked(now_ns)

    def _reap_locked(self, now_ns: int) -> int:
        dropped = 0
        for conn in self._conns.values():
            doomed = [
                sid
                for sid, s in conn.streams.items()
                if max(s.client_time_ns, s.server_time_ns) + ONE_MINUTE_NS < now_ns
            ]
            for sid in doomed:
                del conn.streams[sid]
                dropped += 1
            # stale partial header blocks age out the same way
            for attr in ("client_partial", "server_partial"):
                partial = getattr(conn, attr)
                if partial is not None and partial[2] + ONE_MINUTE_NS < now_ns:
                    setattr(conn, attr, None)
                    dropped += 1
        return dropped
