"""Cluster metadata state — the aggregator/cluster.go + persist.go analog.

The reference keeps ``PodIPToPodUid`` / ``ServiceIPToServiceUid`` string
maps guarded by RWMutexes (cluster.go:15-16). Here the authoritative state
is a dict keyed by uint32 IP, compiled lazily into sorted numpy arrays so a
whole event batch resolves src/dst attribution with two ``searchsorted``
calls (the setFromToV2 analog, data.go:827-870).
"""

from __future__ import annotations

import threading

import numpy as np

from alaz_tpu.datastore.dto import EP_OUTBOUND, EP_POD, EP_SERVICE
from alaz_tpu.events.intern import Interner
from alaz_tpu.events.k8s import (
    Endpoints,
    EventType,
    K8sResourceMessage,
    Pod,
    ResourceType,
    Service,
)
from alaz_tpu.events.net import ip_to_u32


class _IpTable:
    """dict[u32 ip] -> int32 uid-id with a lazily compiled sorted-array view."""

    def __init__(self) -> None:
        self._map: dict[int, int] = {}  # guarded-by: self._lock
        self._dirty = True  # guarded-by: self._lock
        self._ips = np.zeros(0, dtype=np.uint32)  # guarded-by: self._lock
        self._uids = np.zeros(0, dtype=np.int32)  # guarded-by: self._lock
        self._lock = threading.Lock()

    def set(self, ip: int, uid_id: int) -> None:
        with self._lock:
            self._map[ip] = uid_id
            self._dirty = True

    def remove(self, ip: int) -> None:
        with self._lock:
            if self._map.pop(ip, None) is not None:
                self._dirty = True

    def _compile(self) -> tuple[np.ndarray, np.ndarray]:
        """Return a consistent (ips, uids) snapshot, recompiling if dirty."""
        with self._lock:
            if self._dirty:
                if self._map:
                    ips = np.fromiter(self._map.keys(), dtype=np.uint32, count=len(self._map))
                    uids = np.fromiter(self._map.values(), dtype=np.int32, count=len(self._map))
                    order = np.argsort(ips, kind="stable")
                    self._ips = ips[order]
                    self._uids = uids[order]
                else:
                    self._ips = np.zeros(0, dtype=np.uint32)
                    self._uids = np.zeros(0, dtype=np.int32)
                self._dirty = False
            return self._ips, self._uids

    def contains(self, ip: int) -> bool:
        # under the lock like every other _map access (alazrace ALZ050:
        # this read used to race the k8s fold's set/remove rehash)
        with self._lock:
            return ip in self._map

    def lookup(self, ips: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(found_mask, uid_ids) for a batch of uint32 IPs."""
        table_ips, table_uids = self._compile()
        if table_ips.size == 0:
            z = np.zeros(ips.shape[0], dtype=np.int32)
            return np.zeros(ips.shape[0], dtype=bool), z
        pos = np.searchsorted(table_ips, ips)
        pos = np.minimum(pos, table_ips.size - 1)
        found = table_ips[pos] == ips
        uids = np.where(found, table_uids[pos], np.int32(0))
        return found, uids

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


class ClusterInfo:
    """IP→identity attribution + the metadata pass-through to the datastore."""

    def __init__(self, interner: Interner):
        self.interner = interner
        self.pod_ips = _IpTable()
        self.svc_ips = _IpTable()
        # uid-id keyed object snapshots (for features + datastore
        # forward). The IP tables carry their own locks; these dicts
        # used to ride bare on "only the k8s fold writes them" — true
        # today, but the fold thread differs by mode (k8s worker serial,
        # the scatter caller sharded) and nothing stopped a reader from
        # growing on another role (alazrace ALZ050). One low-rate lock
        # per k8s EVENT — control plane, never the row path.
        self._meta_lock = threading.Lock()
        self.pods: dict[int, Pod] = {}  # guarded-by: self._meta_lock
        self.services: dict[int, Service] = {}  # guarded-by: self._meta_lock
        self._pod_uid_to_ip: dict[int, int] = {}  # guarded-by: self._meta_lock
        self._svc_uid_to_ips: dict[int, list[int]] = {}  # guarded-by: self._meta_lock

    # -- k8s event folding (persist.go:55-130 handler analog) --------------

    def handle_msg(self, msg: K8sResourceMessage) -> None:
        if msg.resource_type == ResourceType.POD:
            self._handle_pod(msg.event_type, msg.object)
        elif msg.resource_type == ResourceType.SERVICE:
            self._handle_service(msg.event_type, msg.object)
        elif msg.resource_type == ResourceType.ENDPOINTS:
            self._handle_endpoints(msg.event_type, msg.object)
        # ReplicaSet/Deployment/DaemonSet/StatefulSet/Container carry no IPs;
        # they flow straight through to the datastore (engine forwards them).

    def _handle_pod(self, event: EventType, pod: Pod) -> None:
        uid_id = self.interner.intern(pod.uid)
        # lock order: _meta_lock → _IpTable._lock (one direction — the
        # IP tables never call back into ClusterInfo)
        with self._meta_lock:
            old_ip = self._pod_uid_to_ip.get(uid_id)
            if event == EventType.DELETE:
                if old_ip is not None:
                    self.pod_ips.remove(old_ip)
                    self._pod_uid_to_ip.pop(uid_id, None)
                self.pods.pop(uid_id, None)
                return
            self.pods[uid_id] = pod
            if not pod.ip:
                return
            ip = ip_to_u32(pod.ip)
            if old_ip is not None and old_ip != ip:
                self.pod_ips.remove(old_ip)
            self.pod_ips.set(ip, uid_id)
            self._pod_uid_to_ip[uid_id] = ip

    def _handle_service(self, event: EventType, svc: Service) -> None:
        uid_id = self.interner.intern(svc.uid)
        with self._meta_lock:
            old_ips = self._svc_uid_to_ips.get(uid_id, [])
            if event == EventType.DELETE:
                for ip in old_ips:
                    self.svc_ips.remove(ip)
                self._svc_uid_to_ips.pop(uid_id, None)
                self.services.pop(uid_id, None)
                return
            self.services[uid_id] = svc
            ips = []
            candidates = list(svc.cluster_ips) if svc.cluster_ips else []
            if svc.cluster_ip and svc.cluster_ip not in candidates:
                candidates.append(svc.cluster_ip)
            for ip_s in candidates:
                if ip_s and ip_s not in ("None", ""):
                    try:
                        ips.append(ip_to_u32(ip_s))
                    except OSError:
                        continue
            for ip in old_ips:
                if ip not in ips:
                    self.svc_ips.remove(ip)
            for ip in ips:
                self.svc_ips.set(ip, uid_id)
            self._svc_uid_to_ips[uid_id] = ips

    def _handle_endpoints(self, event: EventType, ep: Endpoints) -> None:
        # Endpoints → pod-IP hints for pods scheduled before their informer
        # event landed (persist.go forwards them; we fold addresses in).
        if event == EventType.DELETE:
            return
        with self._meta_lock:
            for addr in ep.addresses:
                for aip in addr.ips:
                    if aip.type == "pod" and aip.ip and aip.id:
                        try:
                            ip = ip_to_u32(aip.ip)
                        except OSError:
                            continue
                        if self.pod_ips.contains(ip):
                            continue  # pod informer already owns this IP
                        uid_id = self.interner.intern(aip.id)
                        self.pod_ips.set(ip, uid_id)
                        # record ownership so a later pod DELETE cleans it up
                        self._pod_uid_to_ip.setdefault(uid_id, ip)

    # -- batch attribution (setFromToV2, data.go:827-870) ------------------

    def attribute(self, ips: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """For a batch of IPs → (ep_type, uid_id): pod first, then service,
        else outbound — the reference's resolution order. Large batches
        compress to unique IPs first: a 64k-row chunk usually carries a
        few hundred distinct addresses, so the interval lookups and masks
        run over those and rows resolve by one take each."""
        if ips.shape[0] > 2048:
            uniq, inverse = np.unique(ips, return_inverse=True)
            if uniq.shape[0] < ips.shape[0]:
                # the sort is paid either way — resolve over the uniques
                # whenever they compress the batch at all (straight to
                # the lookup body: uniq is already unique, re-running
                # this compression on it could only waste a second sort)
                ep_type, uid = self._attribute_direct(uniq)
                return ep_type[inverse], uid[inverse]
        return self._attribute_direct(ips)

    def compiled_tables(
        self,
    ) -> tuple[tuple[np.ndarray, np.ndarray], tuple[np.ndarray, np.ndarray]]:
        """((pod_ips, pod_uids), (svc_ips, svc_uids)) — the sorted-array
        snapshots the native L7 engine binary-searches. Recompiles swap in
        NEW arrays (never mutate in place), so handing these out without
        holding the table locks is safe; a stale snapshot is at most one
        k8s fold behind, same as the numpy lookup path."""
        return self.pod_ips._compile(), self.svc_ips._compile()

    def _attribute_direct(self, ips: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        pod_found, pod_uid = self.pod_ips.lookup(ips)
        svc_found, svc_uid = self.svc_ips.lookup(ips)
        ep_type = np.full(ips.shape[0], EP_OUTBOUND, dtype=np.uint8)
        ep_type[svc_found] = EP_SERVICE
        ep_type[pod_found] = EP_POD
        uid = np.where(pod_found, pod_uid, np.where(svc_found, svc_uid, np.int32(0)))
        return ep_type, uid
