"""Distributed-tracing correlation (gated off by default).

The reference ships a full design that is compiled out
(``DIST_TRACING_ENABLED``, ebpf/c/bpf.c:19; tcp seq/tid capture
tcp_sock.c:206-282; the ``ingress_egress_calls`` perf map and the
``/dist_tracing/traffic/`` endpoint backend.go:879-900). The captured
signals are the thread id and tcp sequence number on each L7 event
(l7.go:409-410 — our schema carries both).

This correlator implements that design: within one process, an *ingress*
event (a request this process served) is linked to the *egress* events
(requests it made) observed on the same thread while handling it — the
classic thread-propagation heuristic. Links export as caller→callee span
pairs. Enable with ``ALAZ_TPU_DIST_TRACING_ENABLED=1`` or by constructing
the correlator explicitly; the default build leaves it off, matching the
reference.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List

import numpy as np

from alaz_tpu.config import env_bool

DEFAULT_WINDOW_NS = 5_000_000_000  # how long an ingress stays linkable


def enabled() -> bool:
    return env_bool("DIST_TRACING_ENABLED", False)


@dataclass
class SpanLink:
    """caller (ingress into pid) → callee (egress out of pid)."""

    pid: int
    tid: int
    ingress_seq: int
    egress_seq: int
    ingress_time_ns: int
    egress_time_ns: int


@dataclass
class _Ingress:
    seq: int
    time_ns: int


class DistTracingCorrelator:
    """Feed L7 event batches tagged with direction; emit span links.

    Direction convention: ``is_ingress`` True for events this process
    *served* (read-side), False for calls it *made* (write-side) — the
    aggregator knows which from the protocol handler (e.g. server frames,
    DELIVER/PUSHED events are ingress-shaped).
    """

    def __init__(
        self,
        window_ns: int = DEFAULT_WINDOW_NS,
        max_per_thread: int = 8,
        max_links: int = 100_000,
    ):
        self.window_ns = window_ns
        self.max_per_thread = max_per_thread
        self._open: Dict[tuple[int, int], Deque[_Ingress]] = {}
        # bounded: export/drain or the oldest links fall off
        self.links: Deque[SpanLink] = deque(maxlen=max_links)
        self.dropped_unmatched = 0
        self._last_seen: Dict[tuple[int, int], int] = {}

    def observe(self, events: np.ndarray, is_ingress: np.ndarray) -> List[SpanLink]:
        """events: L7_EVENT_DTYPE rows (need pid/tid/seq/write_time_ns)."""
        out: List[SpanLink] = []
        order = np.argsort(events["write_time_ns"], kind="stable")
        now = int(events["write_time_ns"].max()) if events.shape[0] else 0
        for i in order:
            row = events[i]
            key = (int(row["pid"]), int(row["tid"]))
            t = int(row["write_time_ns"])
            self._last_seen[key] = t
            if is_ingress[i]:
                dq = self._open.setdefault(key, deque(maxlen=self.max_per_thread))
                dq.append(_Ingress(seq=int(row["seq"]), time_ns=t))
            else:
                dq = self._open.get(key)
                if not dq:
                    self.dropped_unmatched += 1
                    continue
                # most recent ingress on this thread still inside the window
                while dq and t - dq[0].time_ns > self.window_ns:
                    dq.popleft()
                if not dq:
                    self.dropped_unmatched += 1
                    continue
                ing = dq[-1]
                out.append(
                    SpanLink(
                        pid=key[0],
                        tid=key[1],
                        ingress_seq=ing.seq,
                        egress_seq=int(row["seq"]),
                        ingress_time_ns=ing.time_ns,
                        egress_time_ns=t,
                    )
                )
        self.links.extend(out)
        # evict idle threads so _open stays bounded on long runs
        if len(self._open) > 4096:
            stale = [
                k
                for k, last in self._last_seen.items()
                if now - last > 2 * self.window_ns
            ]
            for k in stale:
                self._open.pop(k, None)
                self._last_seen.pop(k, None)
        return out

    def export_rows(self, drain: bool = True) -> list[list]:
        """Wire rows for a /dist_tracing/traffic/ style endpoint
        (backend.go:879-900 analog); drains the buffer by default."""
        rows = [
            [l.pid, l.tid, l.ingress_seq, l.egress_seq, l.ingress_time_ns, l.egress_time_ns]
            for l in self.links
        ]
        if drain:
            self.links.clear()
        return rows
