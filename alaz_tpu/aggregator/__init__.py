"""The vectorized stream-join engine.

This is the aggregator/ package analog (SURVEY §2.2 G9-G15): join L7 events
with TCP-connection state (socket lines) and Kubernetes metadata (cluster
IP maps) to produce directed pod→pod/service edges, in columnar batches.
"""

from alaz_tpu.aggregator.cluster import ClusterInfo
from alaz_tpu.aggregator.sockline import SocketLine, SocketLineStore
from alaz_tpu.aggregator.engine import Aggregator

__all__ = ["ClusterInfo", "SocketLine", "SocketLineStore", "Aggregator"]
