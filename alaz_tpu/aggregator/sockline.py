"""Socket-line interval join — the sock_num_line.go analog.

A socket line is the time-ordered history of connections seen on one
(pid, fd): open intervals carry a ``SockInfo`` (addresses), closes are nil
markers. L7 events are attributed to a connection by binary-searching their
write timestamp into this history with tolerance heuristics for out-of-order
arrival and close races (GetValue, sock_num_line.go:82-158).

This implementation keeps each line as parallel numpy arrays and answers a
whole batch of timestamps per line in one vectorized call — the per-event
semantics match the reference case for case (see tests/test_sockline.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

ONE_MINUTE_NS = 60_000_000_000
ASSUMED_INTERVAL_NS = 5 * ONE_MINUTE_NS  # DeleteUnused assumedInterval


class _Rev:
    """Store-wide mutation counter. The native L7 engine flattens the whole
    store into contiguous arrays once and reuses that snapshot until this
    revision moves — only mutations that can change a join result bump it
    (inserts, clears, compaction, pid removal; ``_last_match`` writes don't)."""

    __slots__ = ("n", "_lock")

    def __init__(self) -> None:
        self.n = 0  # guarded-by: self._lock (writes); racy reads see a
        # value at most one bump behind — the snapshot records it BEFORE
        # flattening, so any later mutation forces a rebuild
        self._lock = threading.Lock()

    def bump(self) -> None:
        # the lock makes every mutation ADVANCE the counter (a lost
        # update could leave the revision unchanged across a mutation
        # and let a torn snapshot be reused forever)
        with self._lock:
            self.n += 1


@dataclass
class SockInfo:
    pid: int
    fd: int
    saddr: int  # u32
    sport: int
    daddr: int  # u32
    dport: int


class SocketLine:
    """Sorted (timestamp, sockinfo|None) history for one (pid, fd)."""

    __slots__ = ("pid", "fd", "_ts", "_open", "_saddr", "_sport", "_daddr", "_dport", "_last_match", "_n", "_lock", "_rev")

    def __init__(self, pid: int, fd: int, cap: int = 4, rev: _Rev | None = None):
        self.pid = pid
        self.fd = fd
        self._rev = rev if rev is not None else _Rev()
        self._n = 0
        self._ts = np.zeros(cap, dtype=np.uint64)
        self._open = np.zeros(cap, dtype=bool)  # False = close marker
        self._saddr = np.zeros(cap, dtype=np.uint32)
        self._sport = np.zeros(cap, dtype=np.uint16)
        self._daddr = np.zeros(cap, dtype=np.uint32)
        self._dport = np.zeros(cap, dtype=np.uint16)
        self._last_match = np.zeros(cap, dtype=np.uint64)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return self._n

    def _grow(self) -> None:
        cap = max(8, self._ts.shape[0] * 2)
        for name in ("_ts", "_open", "_saddr", "_sport", "_daddr", "_dport", "_last_match"):
            arr = getattr(self, name)
            new = np.zeros(cap, dtype=arr.dtype)
            new[: self._n] = arr[: self._n]
            setattr(self, name, new)

    def clear(self) -> None:
        with self._lock:
            self._n = 0
            self._rev.bump()

    def add_value(self, timestamp: int, info: SockInfo | None) -> None:
        """Sorted insert with tail dedup (AddValue, sock_num_line.go:62-80):
        if the last entry is an identical open socket, skip."""
        with self._lock:
            n = self._n
            if n > 0 and info is not None and self._open[n - 1]:
                if (
                    self._saddr[n - 1] == info.saddr
                    and self._sport[n - 1] == info.sport
                    and self._daddr[n - 1] == info.daddr
                    and self._dport[n - 1] == info.dport
                ):
                    return
            if n == self._ts.shape[0]:
                self._grow()
            idx = int(np.searchsorted(self._ts[:n], np.uint64(timestamp)))
            for name in ("_ts", "_open", "_saddr", "_sport", "_daddr", "_dport", "_last_match"):
                arr = getattr(self, name)
                arr[idx + 1 : n + 1] = arr[idx:n]
            self._ts[idx] = timestamp
            if info is None:
                self._open[idx] = False
                self._saddr[idx] = 0
                self._sport[idx] = 0
                self._daddr[idx] = 0
                self._dport[idx] = 0
            else:
                self._open[idx] = True
                self._saddr[idx] = info.saddr
                self._sport[idx] = info.sport
                self._daddr[idx] = info.daddr
                self._dport[idx] = info.dport
            self._last_match[idx] = 0
            self._n = n + 1
            self._rev.bump()

    def get_value(self, timestamp: int, now_ns: int = 0) -> SockInfo | None:
        out = self.get_values(np.asarray([timestamp], dtype=np.uint64), now_ns)
        if not out[0][0]:
            return None
        return SockInfo(
            pid=self.pid,
            fd=self.fd,
            saddr=int(out[1][0]),
            sport=int(out[2][0]),
            daddr=int(out[3][0]),
            dport=int(out[4][0]),
        )

    def get_values(
        self, timestamps: np.ndarray, now_ns: int = 0
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Vectorized GetValue (sock_num_line.go:82-158) for a batch of
        timestamps → (found, saddr, sport, daddr, dport).

        Case-for-case with the reference:
        - after the last entry → last entry if open; if the line ends with a
          close, fall back to the previous open when within 1 minute.
        - before the first entry → first entry if it's an open (cold-start
          userspace-timestamp tolerance), else miss.
        - landed on a close → if the neighboring opens agree on daddr:dport,
          take the closest; else miss.
        """
        n = self._n
        m = timestamps.shape[0]
        found = np.zeros(m, dtype=bool)
        saddr = np.zeros(m, dtype=np.uint32)
        sport = np.zeros(m, dtype=np.uint16)
        daddr = np.zeros(m, dtype=np.uint32)
        dport = np.zeros(m, dtype=np.uint16)
        if n == 0:
            return found, saddr, sport, daddr, dport

        with self._lock:
            ts = self._ts[:n]
            is_open = self._open[:n]
            idx = np.searchsorted(ts, timestamps, side="left")  # first >= t

            sel = np.full(m, -1, dtype=np.int64)

            # -- case: timestamp after the last entry
            after = idx == n
            if after.any():
                if is_open[n - 1]:
                    sel[after] = n - 1
                else:
                    # closed last entry: use n-2 if open and within 1 minute
                    if n >= 2 and is_open[n - 2]:
                        within = (timestamps - ts[n - 2]) < ONE_MINUTE_NS
                        sel[after & within] = n - 2

            # -- case: timestamp before or at the first entry
            first = (idx == 0) & ~after
            if first.any() and is_open[0]:
                sel[first] = 0

            # -- general case: previous entry
            mid = ~after & ~first
            if mid.any():
                prev = idx[mid] - 1
                prev_open = is_open[prev]
                sel_mid = np.where(prev_open, prev, -1)
                # landed on a close: neighbor agreement heuristic
                closed = ~prev_open
                if closed.any():
                    c_prev = prev[closed] - 1  # index-2
                    c_after = prev[closed] + 1  # index
                    ok_prev = (c_prev >= 0) & is_open[np.clip(c_prev, 0, n - 1)]
                    ok_after = (c_after < n) & is_open[np.clip(c_after, 0, n - 1)]
                    both = ok_prev & ok_after
                    cp = np.clip(c_prev, 0, n - 1)
                    ca = np.clip(c_after, 0, n - 1)
                    agree = both & (self._daddr[cp] == self._daddr[ca]) & (
                        self._dport[cp] == self._dport[ca]
                    )
                    t_mid = timestamps[mid][closed]
                    pick_prev = (t_mid - ts[cp]) < (ts[ca] - t_mid)
                    chosen = np.where(pick_prev, cp, ca)
                    resolved = np.where(agree, chosen, -1)
                    sel_closed = sel_mid[closed]
                    sel_closed = np.where(agree, resolved, sel_closed)
                    sel_mid[closed] = sel_closed
                sel[mid] = sel_mid

            hit = sel >= 0
            found[hit] = True
            si = sel[hit]
            saddr[hit] = self._saddr[si]
            sport[hit] = self._sport[si]
            daddr[hit] = self._daddr[si]
            dport[hit] = self._dport[si]
            if hit.any() and now_ns:
                self._last_match[np.unique(si)] = now_ns
            return found, saddr, sport, daddr, dport

    def delete_unused(self) -> None:
        """GC (DeleteUnused, sock_num_line.go:160-208): collapse paired
        consecutive opens (lost close), then drop open+close pairs whose
        last match is ≥5 minutes older than the newest match on the line."""
        with self._lock:
            n = self._n
            if n <= 1:
                return
            # collapse consecutive opens, keeping the later one
            keep: list[int] = []
            i = 0
            while i < n - 1:
                if self._open[i] and self._open[i + 1]:
                    keep.append(i + 1)
                    i += 2
                else:
                    keep.append(i)
                    i += 1
            if i == n - 1:
                keep.append(n - 1)
            self._compact(keep)
            n = self._n

            last_matched = int(self._last_match[:n].max()) if n else 0
            # drop (open@i-1, close@i) pairs that went stale
            i = n - 1
            dead: set[int] = set()
            while i >= 1:
                if (
                    not self._open[i]
                    and self._open[i - 1]
                    and int(self._last_match[i - 1]) + ASSUMED_INTERVAL_NS < last_matched
                    and i - 1 not in dead
                ):
                    dead.add(i)
                    dead.add(i - 1)
                    i -= 2
                else:
                    i -= 1
            if dead:
                self._compact([j for j in range(n) if j not in dead])

    def _compact(self, keep: list[int]) -> None:
        k = np.asarray(keep, dtype=np.int64)
        for name in ("_ts", "_open", "_saddr", "_sport", "_daddr", "_dport", "_last_match"):
            arr = getattr(self, name)
            arr[: k.shape[0]] = arr[k]
        self._n = k.shape[0]
        self._rev.bump()

    def snapshot(self) -> list[tuple[int, bool]]:
        with self._lock:
            return [(int(self._ts[i]), bool(self._open[i])) for i in range(self._n)]

    def export_arrays(
        self,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Consistent copies of (ts, open, saddr, sport, daddr, dport) for the
        native engine's flattened snapshot."""
        with self._lock:
            n = self._n
            return (
                self._ts[:n].copy(),
                self._open[:n].copy(),
                self._saddr[:n].copy(),
                self._sport[:n].copy(),
                self._daddr[:n].copy(),
                self._dport[:n].copy(),
            )

    def touch(self, local_idx: np.ndarray, now_ns: int) -> None:
        """Mark snapshot-resolved entries as matched (native join writeback).

        ``local_idx`` indexes the entries as of the snapshot; a concurrent
        insert can shift them, so out-of-range hits are clipped away — this
        only feeds the DeleteUnused staleness heuristic, not join results."""
        if not now_ns:
            return
        with self._lock:
            idx = local_idx[local_idx < self._n]
            if idx.shape[0]:
                self._last_match[idx] = np.uint64(now_ns)


class SocketLineStore:
    """All socket lines, keyed (pid, fd) — the SocketMaps[pid] analog
    (cluster.go:20-37) without the pid_max-sized array: a dict is enough
    because keys are interned tuples, not a kernel address space."""

    def __init__(self) -> None:
        self._lines: dict[tuple[int, int], SocketLine] = {}  # lockless-ok: double-checked fast path — reads are single GIL-atomic dict lookups; every structural mutation holds self._lock
        self._lock = threading.Lock()
        self.rev = _Rev()  # shared with every line; native snapshot cache key

    def __len__(self) -> int:
        return len(self._lines)

    def items(self) -> list[tuple[tuple[int, int], SocketLine]]:
        return list(self._lines.items())

    def get(self, pid: int, fd: int) -> SocketLine | None:
        return self._lines.get((pid, fd))

    def get_or_create(self, pid: int, fd: int) -> SocketLine:
        key = (pid, fd)
        line = self._lines.get(key)
        if line is None:
            with self._lock:
                line = self._lines.get(key)
                if line is None:
                    line = SocketLine(pid, fd, rev=self.rev)
                    self._lines[key] = line
        return line

    def remove_pid(self, pid: int) -> int:
        """Drop all lines of an exited process (processExit path,
        data.go:404-437 vicinity)."""
        with self._lock:
            doomed = [k for k in self._lines if k[0] == pid]
            for k in doomed:
                del self._lines[k]
            if doomed:
                self.rev.bump()
            return len(doomed)

    def gc(self) -> None:
        for line in list(self._lines.values()):
            line.delete_unused()
