"""The aggregator engine — the aggregator/data.go join core (G9), columnar.

Responsibilities, mapped to the reference:

- ``process_tcp``  : TCP state events → socket-line opens/closes
  (processTcpConnect, data.go:404-476) + optional AliveConnection emits.
- ``process_l7``   : L7 event batches → attributed ``REQUEST_DTYPE`` edges
  (processL7 → per-protocol handlers, data.go:1364-1383,1208-1272) with
  socket-line fallback join for events without embedded addresses
  (findRelatedSocket, data.go:1407-1429) and a bounded retry queue for
  events that raced their TCP state (signal-and-requeue, data.go:404-437;
  attemptLimit 3 / 20ms, data.go:105-110).
- ``process_proc`` : proc exit → socket-line teardown (zombie reaper analog,
  data.go:192-219).
- ``process_k8s``  : informer messages → cluster IP maps + datastore
  forwarding (processk8s, data.go:239-263; persist.go).

Everything hot is vectorized over the batch; per-event Python happens only
for low-rate protocols (SQL/Mongo/Kafka/AMQP payload parsing) and is
amortized by unique-payload grouping.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from alaz_tpu.aggregator.cluster import ClusterInfo
from alaz_tpu.aggregator.dns import ReverseDnsCache
from alaz_tpu.aggregator.h2 import Http2Assembler
from alaz_tpu.aggregator.sockline import SockInfo, SocketLineStore
from alaz_tpu.config import RuntimeConfig
from alaz_tpu.datastore.dto import (
    ALIVE_CONNECTION_DTYPE,
    EP_OUTBOUND,
    EP_POD,
    KAFKA_CONSUME,
    KAFKA_EVENT_DTYPE,
    KAFKA_PUBLISH,
    REQUEST_DTYPE,
    reverse_direction,
)
from alaz_tpu.datastore.interface import DataStore
from alaz_tpu.events.intern import Interner
from alaz_tpu.events.k8s import K8sResourceMessage
from alaz_tpu.events.schema import (
    PROC_EVENT_DTYPE,
    AmqpMethod,
    Http2Method,
    L7Protocol,
    MongoMethod,
    ProcEventType,
    RedisMethod,
    TcpEventType,
)
from alaz_tpu.logging import get_logger
from alaz_tpu.protocols import http as http_proto
from alaz_tpu.protocols import kafka as kafka_proto
from alaz_tpu.protocols import mongo as mongo_proto
from alaz_tpu.protocols import mysql as mysql_proto
from alaz_tpu.protocols import postgres as postgres_proto
from alaz_tpu.utils.ratelimit import TokenBucket, admit_batch

log = get_logger("alaz_tpu.aggregator")

RETRY_ATTEMPT_LIMIT = 3  # data.go:109 attemptLimit
RETRY_INTERVAL_NS = 20_000_000  # data.go:108 retryInterval (20ms)

_PATH_CACHE_MAX = 65536  # per-protocol parsed-path cache bound (cleared in gc)

# A/B toggle for the native L7 engine body (ISSUE 16), mirroring
# builder.set_native_grouping: None follows RuntimeConfig.engine_backend,
# True/False force the native/python join stage regardless of config.
_native_engine_override: Optional[bool] = None


def set_native_engine(enabled: Optional[bool]) -> None:
    """Force the L7 engine backend: True = native (alz_process_l7),
    False = python (numpy join stage), None = follow
    ``RuntimeConfig.engine_backend``. Parity tests and the bench A/B flip
    both backends through this one switch, like ``set_native_grouping``
    does for the grouping stage."""
    global _native_engine_override
    _native_engine_override = enabled


# sentinel: the join/fill stage ran (side effects: requeue/ledger/stats
# done) but every row dropped — distinct from None, which means the stage
# did NOT run and the caller may fall back without double-counting
_EMPTY_BATCH = ()


def _conn_keys(pid: np.ndarray, fd: np.ndarray) -> np.ndarray:
    """(pid, fd) → mixed u64 grouping key (collision odds are 2^-64-ish;
    used only to group rows that share a socket line)."""
    with np.errstate(over="ignore"):
        return (pid.astype(np.uint64) << np.uint64(32)) ^ (
            fd * np.uint64(0x9E3779B97F4A7C15)
        )


class ConnStmtCache(dict):
    """Prepared-statement cache keyed ``(pid, fd, stmt-id)`` with a
    per-connection index, so teardown on TCP CLOSED / proc EXIT costs
    O(statements on that connection), not O(whole cache): the previous
    scan walked every cached statement per closed-pair batch, which at a
    65k-entry cache made every connection churn a full-cache sweep.

    Only the mutation surface the engine and protocol parsers actually
    use is indexed (``[]=``, ``pop``, ``del``, the drop_* teardowns) —
    other dict mutators are unsupported."""

    def __init__(self) -> None:
        super().__init__()
        self._by_conn: dict[tuple[int, int], set] = {}
        self._fds_of_pid: dict[int, set] = {}

    def __setitem__(self, key, value) -> None:
        if key not in self:
            conn = (key[0], key[1])
            self._by_conn.setdefault(conn, set()).add(key)
            self._fds_of_pid.setdefault(key[0], set()).add(key[1])
        super().__setitem__(key, value)

    def _unindex(self, key) -> None:
        conn = (key[0], key[1])
        keys = self._by_conn.get(conn)
        if keys is None:
            return
        keys.discard(key)
        if not keys:
            del self._by_conn[conn]
            fds = self._fds_of_pid.get(key[0])
            if fds is not None:
                fds.discard(key[1])
                if not fds:
                    del self._fds_of_pid[key[0]]

    def __delitem__(self, key) -> None:
        super().__delitem__(key)
        self._unindex(key)

    _MISSING = object()

    def pop(self, key, default=_MISSING):
        if default is self._MISSING:
            value = super().pop(key)
        else:
            if key not in self:
                return default
            value = super().pop(key)
        self._unindex(key)
        return value

    def clear(self) -> None:
        super().clear()
        self._by_conn.clear()
        self._fds_of_pid.clear()

    def _unsupported(self, *_a, **_k):
        raise NotImplementedError(
            "ConnStmtCache indexes only []=, del, pop and the drop_* "
            "teardowns; this mutator would silently desync the "
            "connection index"
        )

    update = setdefault = popitem = __ior__ = _unsupported

    def drop_conn(self, pid: int, fd: int) -> int:
        """Delete every statement cached for one (pid, fd)."""
        keys = self._by_conn.pop((pid, fd), None)
        if not keys:
            return 0
        for k in keys:
            super().__delitem__(k)
        fds = self._fds_of_pid.get(pid)
        if fds is not None:
            fds.discard(fd)
            if not fds:
                del self._fds_of_pid[pid]
        return len(keys)

    def drop_pid(self, pid: int) -> int:
        """Delete every statement cached for any fd of one pid."""
        n = 0
        for fd in list(self._fds_of_pid.get(pid, ())):
            n += self.drop_conn(pid, fd)
        return n


class AggregatorStats:
    def __init__(self) -> None:
        self.l7_in = 0
        self.l7_joined = 0
        self.l7_dropped_no_socket = 0
        self.l7_dropped_not_pod = 0
        self.l7_requeued = 0
        # single-writer stream counters: each is incremented by exactly
        # one worker (tcp/proc/k8s consume loops); readers are /stats
        # gauges where an off-by-one-batch read is fine
        self.tcp_in = 0  # lockless-ok: single-writer GIL-atomic int counter (tcp worker); racy reads are stats gauges
        self.proc_in = 0  # lockless-ok: single-writer GIL-atomic int counter (proc worker); racy reads are stats gauges
        self.k8s_in = 0  # lockless-ok: single-writer GIL-atomic int counter (k8s fold thread); racy reads are stats gauges
        self.edges_out = 0
        self.kafka_out = 0
        self.l7_rate_limited = 0

    def as_dict(self) -> dict:
        return dict(self.__dict__)


class Aggregator:
    def __init__(
        self,
        ds: DataStore,
        interner: Optional[Interner] = None,
        config: Optional[RuntimeConfig] = None,
        cluster: Optional[ClusterInfo] = None,
        proc_root: str | None = None,
        ledger=None,
        recorder=None,
    ):
        self.ds = ds
        # optional flight recorder (ISSUE 9, alaz_tpu/obs): rare
        # structural events — zombie-reap sweeps tearing down join
        # state — become ring events a post-incident dump replays.
        # Per-sweep, never per row.
        self.recorder = recorder
        # unified loss accounting (ISSUE 8): the join/attribution stage's
        # semantic drops (no socket after retries, non-pod source, rate
        # limit) land in the shared ledger's `filtered` cause, so
        # pushed == emitted + ledger.total holds with no side-channel
        # "semantic" term. A private ledger when the caller has none —
        # the stats counters remain the per-reason observability surface.
        if ledger is None:
            from alaz_tpu.utils.ledger import DropLedger

            ledger = DropLedger()
        self.ledger = ledger
        self.interner = interner if interner is not None else Interner()
        self.config = config if config is not None else RuntimeConfig()
        # where tracked pids live: /proc by default, /host/proc when the
        # service runs containerized with the host procfs mounted. All
        # liveness probes go through this root, never the service's own
        # pid namespace (see reap_zombies). Derives from config unless a
        # caller overrides it directly (tests).
        self.proc_root = (
            proc_root if proc_root is not None else self.config.proc_root
        )
        self.cluster = cluster if cluster is not None else ClusterInfo(self.interner)
        self.socket_lines = SocketLineStore()
        self.h2 = Http2Assembler()
        self.stats = AggregatorStats()
        self.live_pids: set[int] = set()
        # prepared-statement caches (pgStmts / mySqlStmts analogs),
        # conn-indexed so teardown never scans the whole cache
        self.pg_stmts: ConnStmtCache = ConnStmtCache()
        self.mysql_stmts: ConnStmtCache = ConnStmtCache()
        # retry queue of (l7 rows, attempts, not_before_ns)
        self._retries: deque[tuple[np.ndarray, int, int]] = deque()  # guarded-by: self._l7_lock
        # L7 processing is single-logical-threaded, but the housekeeping
        # ticker also fires flush_retries (ADVICE: retries must not wait
        # for the next L7 batch); reentrant because process_l7 flushes too
        self._l7_lock = threading.RLock()
        # payload-hash → interned path id, per protocol (cross-batch cache)
        self._path_cache: dict[int, dict[int, int]] = {}
        self.reverse_dns = ReverseDnsCache()
        # per-pid rate limiting (100/s burst 1000, data.go:339-353) — the
        # reference applies it on the trace path; gated off by default
        self.rate_limit: tuple[float, float] | None = None
        self._pid_buckets: dict[int, TokenBucket] = {}  # guarded-by: self._l7_lock
        # native L7 engine (ISSUE 16): per-aggregator handle, owns the
        # socket-line snapshot cache. Resolved lazily on the first batch
        # so set_native_engine flips after construction still take effect;
        # _native_l7_failed latches the miss so an absent .so logs once.
        self._native_l7 = None  # guarded-by: self._l7_lock
        self._native_l7_failed = False  # guarded-by: self._l7_lock

    def backfill_from_proc(
        self,
        pids: list[int] | None = None,
        proc_root: str | None = None,
        now_ns: int | None = None,
    ) -> int:
        """Cold-start: seed socket lines for connections that predate this
        agent from /proc/<pid>/fd + /proc/<pid>/net/tcp
        (sock_num_line.go:223-269,352-429). Returns lines created. Called
        once at startup so V1-joined L7 events on long-lived connections
        attribute immediately instead of dropping until fresh TCP events
        arrive."""
        from alaz_tpu.aggregator.procfs import backfill_socket_lines

        proc_root = proc_root if proc_root is not None else self.proc_root
        now_ns = now_ns if now_ns is not None else time.time_ns()
        created = backfill_socket_lines(
            self.socket_lines, pids=pids, proc_root=proc_root, now_ns=now_ns
        )
        if created:
            log.info(f"cold-start backfill: {created} socket lines from {proc_root}")
        return created

    # ------------------------------------------------------------------
    # TCP events
    # ------------------------------------------------------------------

    def process_tcp(self, events: np.ndarray, now_ns: int | None = None) -> None:
        """Fold a TCP_EVENT_DTYPE batch into socket lines."""
        self.stats.tcp_in += events.shape[0]
        interesting = (events["type"] == TcpEventType.ESTABLISHED) | (
            events["type"] == TcpEventType.CLOSED
        )
        events = events[interesting]  # alazlint: disable=ALZ040 -- TCP state events are control plane, not request rows; conservation counts L7 rows only and non-ESTABLISHED/CLOSED types carry no join state
        if events.shape[0] == 0:
            return
        _, starts, inverse = np.unique(
            _conn_keys(events["pid"], events["fd"]), return_index=True, return_inverse=True
        )
        alive_rows = []
        closed_pairs: set[tuple[int, int]] = set()
        for g, start in enumerate(starts):
            rows = events[inverse == g]  # alazlint: disable=ALZ040 -- per-connection grouping: every group is visited, no event leaves the loop unprocessed
            pid = int(rows["pid"][0])
            fd = int(rows["fd"][0])
            line = self.socket_lines.get_or_create(pid, fd)
            self.live_pids.add(pid)  # alazlint: disable=ALZ051 -- idempotent element op: liveness set tolerates ingest/reap interleaving; add/discard are single container ops, never check-then-act
            for r in rows:
                if r["type"] == TcpEventType.ESTABLISHED:
                    line.add_value(
                        int(r["timestamp_ns"]),
                        SockInfo(
                            pid=pid,
                            fd=fd,
                            saddr=int(r["saddr"]),
                            sport=int(r["sport"]),
                            daddr=int(r["daddr"]),
                            dport=int(r["dport"]),
                        ),
                    )
                    alive_rows.append(r)
                else:
                    line.add_value(int(r["timestamp_ns"]), None)
                    closed_pairs.add((pid, fd))
        if closed_pairs:
            self._teardown_conns(closed_pairs)
        if self.config.send_alive_tcp_connections and alive_rows:
            self._persist_alive(np.array(alive_rows, dtype=events.dtype))

    def _teardown_conns(self, closed_pairs: set[tuple[int, int]]) -> None:
        """Per-connection state teardown on TCP CLOSED: h2 parsers and
        prepared-statement caches must not survive a (pid, fd) reuse
        (reference deletes both on close, data.go:363-380,496-500). Runs
        on the TCP worker; the stmt caches are mutated by the L7 worker
        under _l7_lock, so take it here too."""
        for pid, fd in closed_pairs:
            self.h2.remove_conn(pid, fd)
        with self._l7_lock:
            for pid, fd in closed_pairs:
                self.pg_stmts.drop_conn(pid, fd)
                self.mysql_stmts.drop_conn(pid, fd)

    def _persist_alive(self, rows: np.ndarray) -> None:
        out = np.zeros(rows.shape[0], dtype=ALIVE_CONNECTION_DTYPE)
        out["check_time_ms"] = rows["timestamp_ns"] // 1_000_000
        out["from_ip"] = rows["saddr"]
        out["from_port"] = rows["sport"]
        out["to_ip"] = rows["daddr"]
        out["to_port"] = rows["dport"]
        ft, fu = self.cluster.attribute(rows["saddr"])
        tt, tu = self.cluster.attribute(rows["daddr"])
        out["from_type"], out["from_uid"] = ft, fu
        out["to_type"], out["to_uid"] = tt, tu
        self.ds.persist_alive_connections(out)

    def reap_zombies(self, kill_fn=None) -> list[int]:
        """Tear down the state of processes that died without an EXIT
        event — the 2-minute zombie reaper (data.go:192-219). The
        default probe is existence of ``<proc_root>/<pid>``, NOT
        ``kill(pid, 0)``: tracked pids come from agents on the node and
        are host pids, while this service may run in a container with
        its own pid namespace — kill() would consult the wrong process
        table and reap every live pid. ``kill_fn`` is injectable for
        tests and for callers that really do share a pid namespace."""
        import os as os_mod

        if kill_fn is None:
            root = self.proc_root
            if not os_mod.path.isdir(root):
                # an unmounted/typoed proc root would read as "every pid
                # is dead" and tear down ALL join state each sweep — a
                # destructive misconfiguration that must be loud, not a
                # silent purge
                log.error(
                    f"zombie reaper: proc root {root!r} does not exist; "
                    "skipping sweep (check PROC_ROOT / the procfs mount)"
                )
                return []

            def kill_fn(pid, _sig, _root=root):
                if not os_mod.path.isdir(os_mod.path.join(_root, str(pid))):
                    raise ProcessLookupError(pid)

        dead: list[int] = []
        for pid in list(self.live_pids):
            try:
                kill_fn(pid, 0)
            except ProcessLookupError:
                dead.append(pid)
            except PermissionError:
                pass  # exists but owned elsewhere: alive
            except OSError:
                pass
        if dead:
            ev = np.zeros(len(dead), dtype=PROC_EVENT_DTYPE)
            ev["pid"] = dead
            ev["type"] = ProcEventType.EXIT
            self.process_proc(ev)
            if self.recorder is not None:
                # a reap tears down join state for every dead pid — the
                # kind of rare structural event a flight-recorder dump
                # needs to explain "why did attribution drop at t"
                self.recorder.record(
                    "zombie_reap", pids=len(dead),
                    live_pids=len(self.live_pids),
                )
        return dead

    # ------------------------------------------------------------------
    # Proc events
    # ------------------------------------------------------------------

    def process_proc(self, events: np.ndarray) -> None:
        self.stats.proc_in += events.shape[0]
        for r in events:
            pid = int(r["pid"])
            if r["type"] == ProcEventType.EXIT:
                self.live_pids.discard(pid)  # alazlint: disable=ALZ051 -- idempotent element op: liveness set tolerates ingest/reap interleaving; add/discard are single container ops, never check-then-act
                self.socket_lines.remove_pid(pid)
                self.h2.remove_pid(pid)
                with self._l7_lock:  # stmt caches belong to the L7 worker
                    self.pg_stmts.drop_pid(pid)
                    self.mysql_stmts.drop_pid(pid)
                    # a reused pid must start with a fresh burst
                    # allowance. Under the same lock as the L7 worker's
                    # bucket inserts (alazrace ALZ050: this pop used to
                    # ride bare on dict-op GIL atomicity while
                    # _apply_rate_limit inserted concurrently)
                    self._pid_buckets.pop(pid, None)
            elif r["type"] == ProcEventType.EXEC:
                self.live_pids.add(pid)  # alazlint: disable=ALZ051 -- idempotent element op: liveness set tolerates ingest/reap interleaving; add/discard are single container ops, never check-then-act

    # ------------------------------------------------------------------
    # K8s events
    # ------------------------------------------------------------------

    def process_k8s(self, msg: K8sResourceMessage) -> None:
        self.stats.k8s_in += 1
        self.cluster.handle_msg(msg)
        self.ds.persist_resource(msg.resource_type, msg.event_type, msg.object)

    # ------------------------------------------------------------------
    # L7 events
    # ------------------------------------------------------------------

    def process_l7(self, events: np.ndarray, now_ns: int | None = None) -> np.ndarray:
        """Join + attribute an L7_EVENT_DTYPE batch. Returns the emitted
        REQUEST_DTYPE rows (also persisted to the datastore)."""
        now_ns = now_ns if now_ns is not None else time.time_ns()
        with self._l7_lock:
            self.stats.l7_in += events.shape[0]
            if self.rate_limit is not None and events.shape[0]:
                events = self._apply_rate_limit(events, now_ns)
            emitted = self._process_l7_inner(events, attempts=0, now_ns=now_ns)
            retried = self.flush_retries(now_ns)
        if retried is not None and retried.shape[0]:
            emitted = np.concatenate([emitted, retried])
        return emitted

    def _apply_rate_limit(self, events: np.ndarray, now_ns: int) -> np.ndarray:
        """Per-pid token buckets (rate.Limiter semantics, data.go:339-353).
        The only Python walk left is over UNIQUE pids — one dict lookup
        each to fetch/create the bucket; the admit math runs as one array
        pass (``admit_batch``) and the keep mask scatters back without
        per-group slicing. Drops, ledger attribution and post-batch bucket
        state are bit-identical to ``_scalar_apply_rate_limit`` below."""
        rate, burst = self.rate_limit
        now_s = now_ns / 1e9
        n = events.shape[0]
        pids, inverse = np.unique(events["pid"], return_inverse=True)
        # group rows per pid in O(n log n): one sort, contiguous slices
        order = np.argsort(inverse, kind="stable")
        boundaries = np.searchsorted(inverse[order], np.arange(pids.shape[0] + 1))
        sizes = np.diff(boundaries)
        buckets = []
        for pid in pids:
            bucket = self._pid_buckets.get(int(pid))  # alazlint: disable=ALZ010 -- _l7_lock IS held here: _apply_rate_limit's only caller is process_l7 inside `with self._l7_lock` (the per-file rule can't see caller-held locks; alazrace's interprocedural lockset can and agrees)
            if bucket is None:
                bucket = TokenBucket(rate, burst, now_s=now_s)
                self._pid_buckets[int(pid)] = bucket  # alazlint: disable=ALZ010 -- same caller-held _l7_lock as the get above
            buckets.append(bucket)
        admitted = admit_batch(buckets, sizes, now_s)
        # keep the first admitted[g] rows of each pid group in ORIGINAL row
        # order (argsort is stable, so within a group `order` ascends by
        # original index): rank-within-group < allowance, scattered back
        rank = np.arange(n, dtype=np.int64) - np.repeat(boundaries[:-1], sizes)
        keep = np.empty(n, dtype=bool)
        keep[order] = rank < np.repeat(admitted, sizes)
        dropped = int(n - int(keep.sum()))
        if dropped:
            self.stats.l7_rate_limited += dropped
            self.ledger.add("filtered", dropped, reason="rate_limit")
            events = events[keep]
        return events

    def _scalar_apply_rate_limit(self, events: np.ndarray, now_ns: int) -> np.ndarray:
        """Pre-vectorization reference (one ``bucket.admit`` per pid group)
        — kept for the equivalence property tests."""
        rate, burst = self.rate_limit
        now_s = now_ns / 1e9
        keep = np.ones(events.shape[0], dtype=bool)
        pids, inverse = np.unique(events["pid"], return_inverse=True)
        order = np.argsort(inverse, kind="stable")
        boundaries = np.searchsorted(inverse[order], np.arange(pids.shape[0] + 1))
        for g, pid in enumerate(pids):
            bucket = self._pid_buckets.get(int(pid))  # alazlint: disable=ALZ010 -- same caller-held _l7_lock contract as _apply_rate_limit
            if bucket is None:
                bucket = TokenBucket(rate, burst, now_s=now_s)
                self._pid_buckets[int(pid)] = bucket  # alazlint: disable=ALZ010 -- same caller-held _l7_lock as the get above
            idx = order[boundaries[g] : boundaries[g + 1]]
            admitted = bucket.admit(idx.shape[0], now_s)
            if admitted < idx.shape[0]:
                keep[idx[admitted:]] = False
        dropped = int((~keep).sum())
        if dropped:
            self.stats.l7_rate_limited += dropped
            self.ledger.add("filtered", dropped, reason="rate_limit")
            events = events[keep]
        return events

    @property
    def pending_retries(self) -> int:
        with self._l7_lock:  # stat probe races the L7 worker's requeues
            return len(self._retries)

    def flush_retries(self, now_ns: int) -> np.ndarray | None:
        """Re-run due retry entries (the signal-and-requeue path). Safe to
        call from the housekeeping ticker — the reference's retry is
        timer-driven, not gated on the next L7 batch."""
        out = []
        with self._l7_lock:
            pending = len(self._retries)
            for _ in range(pending):
                rows, attempts, not_before = self._retries.popleft()
                if not_before > now_ns:
                    self._retries.append((rows, attempts, not_before))
                    continue
                out.append(self._process_l7_inner(rows, attempts, now_ns))
        if not out:
            return None
        return np.concatenate(out) if len(out) > 1 else out[0]

    def _use_native_engine(self) -> bool:
        if _native_engine_override is not None:
            return _native_engine_override
        return getattr(self.config, "engine_backend", "python") == "native"

    def _native_l7_engine(self):
        """Lazy per-aggregator NativeL7Engine, or None (fallback). The
        miss latches so an unbuildable .so logs one warning, not one per
        batch."""
        if self._native_l7 is None and not self._native_l7_failed:  # alazlint: disable=ALZ010 -- _l7_lock IS held on every concurrent path (process_l7/flush_retries callers); the remaining callers are single-threaded construction-time prewarms (sharded pool init, shm worker pre-ready) before any traffic thread exists
            from alaz_tpu.aggregator import native_l7

            self._native_l7 = native_l7.make_engine()  # alazlint: disable=ALZ010 -- same caller-held _l7_lock / pre-traffic prewarm contract as the check above
            if self._native_l7 is None:  # alazlint: disable=ALZ010 -- same caller-held _l7_lock / pre-traffic prewarm contract as the check above
                self._native_l7_failed = True  # alazlint: disable=ALZ010 -- same caller-held _l7_lock / pre-traffic prewarm contract as the check above
                log.warning(
                    "engine_backend=native requested but libalaz_ingest.so "
                    "is unavailable; falling back to the python L7 engine"
                )
        return self._native_l7  # alazlint: disable=ALZ010 -- same caller-held _l7_lock / pre-traffic prewarm contract as the check above

    def _process_l7_inner(
        self, events: np.ndarray, attempts: int, now_ns: int
    ) -> np.ndarray:
        n = events.shape[0]
        if n == 0:
            return np.zeros(0, dtype=REQUEST_DTYPE)

        # join + attribution + REQUEST-row fill: one native pass when the
        # engine backend allows, else the numpy stage. Both do their own
        # requeue/drop bookkeeping; None means "did not run" (native
        # unavailable — no side effects yet, python fallback is safe),
        # _EMPTY_BATCH means "ran, every row dropped/requeued".
        prep = None
        if self._use_native_engine():
            eng = self._native_l7_engine()
            if eng is not None:
                prep = self._native_join_fill(eng, events, attempts, now_ns)
        if prep is None:
            prep = self._python_join_fill(events, attempts, now_ns)
        if prep is _EMPTY_BATCH:
            return np.zeros(0, dtype=REQUEST_DTYPE)
        events, out, protocol, proto_present = prep

        # outbound destinations: reverse-DNS name when the gated cache has
        # one, else the IP string (setFromToV2 fallback chain,
        # data.go:852-866). Vectorized per UNIQUE address: name_for takes
        # the cache lock and intern hashes a string — per-row they were
        # the single hottest Python loop in the V2 ingest path. Stays
        # Python on both backends (refusal surface: interner + DNS cache).
        outbound = out["to_type"] == np.uint8(EP_OUTBOUND)
        if outbound.any():
            out["to_uid"][outbound] = self._outbound_uids(
                np.ascontiguousarray(out["to_ip"][outbound])
            )

        # per-protocol payload enrichment
        self._enrich_paths(events, out, protocol, proto_present)

        # consume-side direction flips (AMQP DELIVER / Redis PUSHED_EVENT)
        if proto_present[int(L7Protocol.AMQP)] or proto_present[int(L7Protocol.REDIS)]:
            method = np.ascontiguousarray(events["method"])
            flip = (
                (protocol == L7Protocol.AMQP) & (method == AmqpMethod.DELIVER)
            ) | (
                (protocol == L7Protocol.REDIS) & (method == RedisMethod.PUSHED_EVENT)
            )
            if flip.any():
                reverse_direction(out, flip)

        # HTTP2 frames & Kafka payloads detour through their assemblers;
        # the common all-plain batch skips the masks AND the row copy
        has_h2 = bool(proto_present[int(L7Protocol.HTTP2)])
        has_kafka = bool(proto_present[int(L7Protocol.KAFKA)])
        if has_h2 or has_kafka:
            h2_mask = protocol == L7Protocol.HTTP2
            kafka_mask = protocol == L7Protocol.KAFKA
            if has_h2:
                h2_out = self._process_h2(events[h2_mask], out[h2_mask])
                if h2_out is not None and h2_out.shape[0]:
                    self.ds.persist_requests(h2_out)
                    self.stats.edges_out += h2_out.shape[0]
            if has_kafka:
                self._process_kafka(events[kafka_mask], out[kafka_mask])
            result = out[~h2_mask & ~kafka_mask]
        else:
            result = out
        if result.shape[0]:
            self.ds.persist_requests(result)
            self.stats.edges_out += result.shape[0]
            self.stats.l7_joined += result.shape[0]
        return result

    def _native_join_fill(self, eng, events: np.ndarray, attempts: int, now_ns: int):
        """Native join/fill stage: hand the batch plus socket-line snapshot
        and attribution tables to ``alz_process_l7``, then fold the drop
        counts into the SAME requeue/stats/ledger bookkeeping the python
        stage does (order pinned by ``L7_ENGINE_DROP_CAUSES``:
        counts[0]=no_socket-or-retry, counts[1]=not_pod). Returns the
        (events, out, protocol, proto_present) stage tuple, _EMPTY_BATCH
        when everything dropped, or None when the call could not run (no
        side effects — python fallback is exact)."""
        res = eng.process(
            events, now_ns, self.socket_lines, *self.cluster.compiled_tables()
        )
        if res is None:
            return None
        out, kept_idx, unmatched_idx, n_not_pod = res
        if unmatched_idx.shape[0]:
            if attempts + 1 < RETRY_ATTEMPT_LIMIT:
                rows = events[unmatched_idx]  # fancy index -> fresh copy
                backoff = RETRY_INTERVAL_NS * (1 << attempts)  # 20ms, 40ms
                self._retries.append((rows, attempts + 1, now_ns + backoff))  # alazlint: disable=ALZ010 -- _l7_lock IS held: every _process_l7_inner caller (process_l7, flush_retries) wraps the call in the lock
                self.stats.l7_requeued += rows.shape[0]
            else:
                lost = int(unmatched_idx.shape[0])
                self.stats.l7_dropped_no_socket += lost
                self.ledger.add("filtered", lost, reason="no_socket")
        if n_not_pod:
            self.stats.l7_dropped_not_pod += n_not_pod
            self.ledger.add("filtered", n_not_pod, reason="not_pod")
        if out.shape[0] == 0:
            return _EMPTY_BATCH
        if kept_idx.shape[0] != events.shape[0]:
            events = events[kept_idx]
        # else: every row survived — kept_idx is ascending-unique, so it
        # is the identity, and the 331-byte-per-row gather is pure waste;
        # the python stage leaves `events` un-copied on this path too, so
        # aliasing the caller's view is the established contract
        protocol = np.ascontiguousarray(events["protocol"])
        proto_present = np.bincount(protocol, minlength=256)
        return events, out, protocol, proto_present

    def _python_join_fill(self, events: np.ndarray, attempts: int, now_ns: int):
        """Numpy join/fill stage (the pre-ISSUE-16 `_process_l7_inner`
        body, verbatim): V1 socket-line join, retry requeue, pod/outbound
        attribution, REQUEST row fill. Returns (events, out, protocol,
        proto_present) or _EMPTY_BATCH when every row dropped/requeued."""
        saddr = events["saddr"]
        sport = events["sport"]
        daddr = events["daddr"]
        dport = events["dport"]

        # V1 fallback: rows without embedded addresses join via socket lines
        # keyed (pid, fd) at the write timestamp (findRelatedSocket).
        need_join = daddr == 0
        matched = ~need_join
        if need_join.any():
            # the join writes resolved addresses in place — detach from
            # the events array first. The all-V2 hot path (every row
            # carries addresses) skips these four copies entirely.
            saddr, sport = saddr.copy(), sport.copy()
            daddr, dport = daddr.copy(), dport.copy()
            j_idx = np.flatnonzero(need_join)
            sub = events[j_idx]
            _, starts, inverse = np.unique(
                _conn_keys(sub["pid"], sub["fd"]), return_index=True, return_inverse=True
            )
            for g, start in enumerate(starts):
                sel = j_idx[inverse == g]
                pid = int(events["pid"][sel[0]])
                fd = int(events["fd"][sel[0]])
                line = self.socket_lines.get(pid, fd)
                if line is None or len(line) == 0:
                    continue
                found, s_a, s_p, d_a, d_p = line.get_values(
                    events["write_time_ns"][sel], now_ns
                )
                hit = sel[found]
                saddr[hit] = s_a[found]
                sport[hit] = s_p[found]
                daddr[hit] = d_a[found]
                dport[hit] = d_p[found]
                matched[hit] = True

        # requeue unmatched rows (socket state may lag the L7 event)
        unmatched = ~matched
        if unmatched.any():
            if attempts + 1 < RETRY_ATTEMPT_LIMIT:
                rows = events[unmatched].copy()
                backoff = RETRY_INTERVAL_NS * (1 << attempts)  # 20ms, 40ms
                self._retries.append((rows, attempts + 1, now_ns + backoff))  # alazlint: disable=ALZ010 -- _l7_lock IS held: every _process_l7_inner caller (process_l7, flush_retries) wraps the call in the lock
                self.stats.l7_requeued += rows.shape[0]
            else:
                lost = int(unmatched.sum())
                self.stats.l7_dropped_no_socket += lost
                self.ledger.add("filtered", lost, reason="no_socket")
            events = events[matched]
            saddr, sport = saddr[matched], sport[matched]
            daddr, dport = daddr[matched], dport[matched]
            if events.shape[0] == 0:
                return _EMPTY_BATCH

        # attribution: From must be a pod, else drop (setFromToV2 contract)
        from_type, from_uid = self.cluster.attribute(saddr)
        is_pod = from_type == EP_POD
        if not is_pod.all():
            lost = int((~is_pod).sum())
            self.stats.l7_dropped_not_pod += lost
            self.ledger.add("filtered", lost, reason="not_pod")
            events = events[is_pod]
            if events.shape[0] == 0:
                return _EMPTY_BATCH
            saddr, sport = saddr[is_pod], sport[is_pod]
            daddr, dport = daddr[is_pod], dport[is_pod]
            from_type, from_uid = from_type[is_pod], from_uid[is_pod]
        to_type, to_uid = self.cluster.attribute(daddr)

        # one contiguous copy of the protocol column: it is scanned many
        # times below (enrichment masks, direction flips, h2/kafka
        # routing), and every scan of the strided 320-byte-record view
        # costs ~70× the contiguous compare. The presence bincount then
        # gates every protocol-specific pass to protocols actually in
        # the batch — an all-HTTP chunk computes no AMQP/Redis/h2/kafka
        # masks at all.
        protocol = np.ascontiguousarray(events["protocol"])
        proto_present = np.bincount(protocol, minlength=256)

        out = np.zeros(events.shape[0], dtype=REQUEST_DTYPE)
        out["start_time_ms"] = (events["write_time_ns"] // 1_000_000).astype(np.int64)
        out["latency_ns"] = events["duration_ns"]
        out["from_ip"] = saddr
        out["from_type"] = from_type
        out["from_uid"] = from_uid
        out["from_port"] = sport
        out["to_ip"] = daddr
        out["to_type"] = to_type
        out["to_uid"] = to_uid
        out["to_port"] = dport
        out["protocol"] = protocol
        out["tls"] = events["tls"]
        out["completed"] = True
        out["status_code"] = events["status"]
        out["method"] = events["method"]
        return events, out, protocol, proto_present

    # -- outbound naming ----------------------------------------------------

    def _outbound_uids(self, daddrs: np.ndarray) -> np.ndarray:
        """Interned name ids for a column of outbound destination
        addresses: one reverse-DNS probe + one intern per UNIQUE address
        (in first-occurrence order, so id assignment matches the scalar
        reference exactly); rows resolve by vectorized take."""
        uniq, first_idx, inverse = np.unique(
            daddrs, return_index=True, return_inverse=True
        )
        # first-occurrence order (np.unique sorts by value)
        order = np.argsort(first_idx, kind="stable")
        name_for = self.reverse_dns.name_for
        names = [name_for(a) for a in uniq[order].tolist()]
        ids = np.empty(uniq.shape[0], dtype=np.int32)
        ids[order] = self.interner.intern_many(names)
        return ids[inverse]

    def _scalar_outbound_uids(self, daddrs: np.ndarray) -> np.ndarray:
        """Pre-vectorization reference (one name_for + intern per ROW) —
        kept for the equivalence property tests."""
        return np.fromiter(
            (
                self.interner.intern(self.reverse_dns.name_for(int(a)))
                for a in daddrs
            ),
            dtype=np.int32,
            count=daddrs.shape[0],
        )

    # -- payload enrichment -------------------------------------------------

    def _enrich_paths(
        self,
        events: np.ndarray,
        out: np.ndarray,
        protocol: np.ndarray | None = None,
        proto_present: np.ndarray | None = None,
    ) -> None:
        """Fill ``out['path']`` per protocol. Amortized by payload hashing:
        identical payload prefixes parse once *ever* (cross-batch cache).
        ``protocol``/``proto_present`` are the caller's contiguous column
        + presence bincount when it already has them — absent protocols
        then cost nothing, not even a mask compare."""
        if protocol is None:
            protocol = np.ascontiguousarray(events["protocol"])
        if proto_present is None:
            proto_present = np.bincount(protocol, minlength=256)
        if proto_present[int(L7Protocol.HTTP)]:
            idx = np.flatnonzero(protocol == L7Protocol.HTTP)
            self._hashed_parse(events, out, idx, int(L7Protocol.HTTP), self._parse_http_row)
        for proto, parser in (
            (L7Protocol.POSTGRES, self._parse_pg_row),
            (L7Protocol.MYSQL, self._parse_mysql_row),
            (L7Protocol.MONGO, self._parse_mongo_row),
            (L7Protocol.REDIS, self._parse_redis_row),
        ):
            if proto_present[int(proto)]:
                idx = np.flatnonzero(protocol == proto)
                if proto in (L7Protocol.POSTGRES, L7Protocol.MYSQL):
                    # stateful (stmt caches) — parse per row
                    for i in idx:
                        out["path"][i] = parser(events[i])
                else:
                    self._hashed_parse(events, out, idx, int(proto), parser)

    @staticmethod
    def _payload_hashes(window: np.ndarray) -> np.ndarray:
        """Cheap 64-bit mix over the payload window (FNV-ish, vectorized).

        The window is [N, _PATH_WINDOW] uint8 viewed as uint64 lanes; each
        lane is multiplied by a distinct odd constant and xor-folded, so
        identical prefixes collide on purpose and different ones don't in
        any practical batch."""
        lanes = window.view(np.uint64).reshape(window.shape[0], -1)
        mult = (
            np.uint64(0x9E3779B97F4A7C15)
            * (np.arange(1, lanes.shape[1] + 1, dtype=np.uint64) | np.uint64(1))
        )
        with np.errstate(over="ignore"):
            mixed = lanes * mult[None, :]
            h = np.bitwise_xor.reduce(mixed, axis=1)
            h ^= h >> np.uint64(33)
            h *= np.uint64(0xFF51AFD7ED558CCD)
            h ^= h >> np.uint64(33)
        return h

    def _hashed_parse(self, events, out, idx, proto_key: int, row_parser) -> None:
        cache = self._path_cache.setdefault(proto_key, {})
        # hash every captured byte any row's parser can read, plus
        # payload_size: two payloads identical in a prefix but differing
        # beyond (long paths/SQL) must not share the first-seen interned
        # path. The hashed span is the batch's max payload_size rounded
        # up to a power-of-two lane count (few distinct spans → stable
        # cross-batch cache keys): lanes past a row's own size are zeros
        # by the capture contract, parsers never read past size, so
        # dropping all-zero tail lanes cannot merge distinct payloads —
        # typical sub-128-byte HTTP batches hash 8 lanes, not 32.
        sizes = events["payload_size"][idx]
        span = min(int(sizes.max()) if idx.shape[0] else 0, events["payload"].shape[1])
        lanes = 1
        while lanes * 8 < span:
            lanes *= 2
        nbytes = min(lanes * 8, events["payload"].shape[1])
        # single-protocol batches (the common case) take the strided-copy
        # path, not a gather
        if idx.shape[0] == events.shape[0]:
            window = np.ascontiguousarray(events["payload"][:, :nbytes])
        else:
            window = np.ascontiguousarray(events["payload"][idx, :nbytes])
        hashes = self._payload_hashes(window)
        with np.errstate(over="ignore"):
            hashes ^= sizes.astype(np.uint64) * np.uint64(0xD6E8FEB86659FD93)
        uniq, starts, inverse = np.unique(hashes, return_index=True, return_inverse=True)
        path_ids = np.zeros(uniq.shape[0], dtype=np.int32)
        for u in range(uniq.shape[0]):
            key = int(uniq[u])
            pid_cached = cache.get(key)
            if pid_cached is None:
                pid_cached = row_parser(events[idx[starts[u]]])
                cache[key] = pid_cached
            path_ids[u] = pid_cached
        out["path"][idx] = path_ids[inverse]

    def _payload_bytes(self, row) -> bytes:
        size = int(row["payload_size"])
        return bytes(row["payload"][: min(size, row["payload"].shape[0])])

    def _parse_http_row(self, row) -> int:
        _, path, _, _host = http_proto.parse_payload(self._payload_bytes(row))
        return self.interner.intern(path)

    def _parse_pg_row(self, row) -> int:
        cmd = postgres_proto.parse_command(
            self._payload_bytes(row),
            int(row["method"]),
            self.pg_stmts,
            int(row["pid"]),
            int(row["fd"]),
        )
        return self.interner.intern(cmd or "")

    def _parse_mysql_row(self, row) -> int:
        cmd = mysql_proto.parse_command(
            self._payload_bytes(row),
            int(row["method"]),
            self.mysql_stmts,
            int(row["pid"]),
            int(row["fd"]),
            int(row["mysql_prep_stmt_id"]),
        )
        return self.interner.intern(cmd or "")

    def _parse_mongo_row(self, row) -> int:
        summary = mongo_proto.parse_summary(self._payload_bytes(row))
        return self.interner.intern(summary or "")

    def _parse_redis_row(self, row) -> int:
        # raw payload is the query (processRedisEvent, data.go:1120-1160)
        return self.interner.intern(
            self._payload_bytes(row).decode("latin-1", "replace")
        )

    # -- HTTP/2 -------------------------------------------------------------

    def _process_h2(self, events: np.ndarray, out_rows: np.ndarray) -> np.ndarray | None:
        done = []
        for i, row in enumerate(events):
            completed = self.h2.feed(
                pid=int(row["pid"]),
                fd=int(row["fd"]),
                is_client=int(row["method"]) == Http2Method.CLIENT_FRAME,
                payload=self._payload_bytes(row),
                write_time_ns=int(row["write_time_ns"]),
                tls=bool(row["tls"]),
            )
            for c in completed:
                r = out_rows[i : i + 1].copy()
                r["start_time_ms"] = c.start_time_ns // 1_000_000
                r["latency_ns"] = c.latency_ns
                r["status_code"] = c.grpc_status if c.is_grpc and c.grpc_status is not None else c.status
                r["path"] = self.interner.intern(c.path)
                r["completed"] = True
                done.append(r)
        if not done:
            return None
        return np.concatenate(done)

    # -- Kafka --------------------------------------------------------------

    def _process_kafka(self, events: np.ndarray, out_rows: np.ndarray) -> None:
        """Decode Kafka payloads → KAFKA_EVENT_DTYPE batch
        (processKafkaEvent, data.go:929-1017 + aggregator/kafka)."""
        from alaz_tpu.events.schema import KafkaMethod

        rows = []
        for i, row in enumerate(events):
            payload = self._payload_bytes(row)
            method = int(row["method"])
            msgs: list[kafka_proto.KafkaMessage] = []
            # dispatch on the kernel-assigned method like the reference
            # (data.go:953,975); the payload is often truncated to the
            # capture window so the kernel's exact-size check can't re-run
            try:
                if method == KafkaMethod.PRODUCE_REQUEST:
                    _, api_version, _, body = kafka_proto.split_request_header(payload)
                    msgs = kafka_proto.decode_produce_request(body, api_version)
                elif method == KafkaMethod.FETCH_RESPONSE:
                    api_version = int(row["kafka_api_version"])
                    if len(payload) >= 8:
                        msgs = kafka_proto.decode_fetch_response(payload[8:], api_version)
                else:
                    # unclassified: sniff a request header, else try fetch
                    ok, _corr, api_key, api_version = kafka_proto.parse_request_header(payload)
                    if ok and api_key == kafka_proto.API_KEY_PRODUCE:
                        _, _, _, body = kafka_proto.split_request_header(payload)
                        msgs = kafka_proto.decode_produce_request(body, api_version)
                    elif len(payload) >= 8:
                        msgs = kafka_proto.decode_fetch_response(
                            payload[8:], int(row["kafka_api_version"])
                        )
            except Exception:
                msgs = []
            for m in msgs:
                kv = np.zeros(1, dtype=KAFKA_EVENT_DTYPE)
                o = out_rows[i]
                kv["start_time_ms"] = o["start_time_ms"]
                kv["latency_ns"] = o["latency_ns"]
                kv["from_ip"], kv["from_type"], kv["from_uid"], kv["from_port"] = (
                    o["from_ip"], o["from_type"], o["from_uid"], o["from_port"],
                )
                kv["to_ip"], kv["to_type"], kv["to_uid"], kv["to_port"] = (
                    o["to_ip"], o["to_type"], o["to_uid"], o["to_port"],
                )
                kv["topic"] = self.interner.intern(m.topic)
                kv["partition"] = m.partition
                kv["key"] = self.interner.intern(m.key)
                kv["value"] = self.interner.intern(m.value)
                kv["type"] = KAFKA_PUBLISH if m.type == kafka_proto.PUBLISH else KAFKA_CONSUME
                kv["tls"] = o["tls"]
                if m.type == kafka_proto.CONSUME:
                    reverse_direction(kv)
                rows.append(kv)
        if rows:
            batch = np.concatenate(rows)
            self.ds.persist_kafka_events(batch)
            self.stats.kafka_out += batch.shape[0]

    # ------------------------------------------------------------------

    def gc(self, now_ns: int | None = None) -> None:
        """Periodic housekeeping: socket-line GC + h2 stream reaping
        (the 10-worker sockline GC loop, data.go:1688; reaper 551-571)."""
        self.socket_lines.gc()
        self.h2.reap(now_ns if now_ns is not None else time.time_ns())
        self.reverse_dns.purge()  # the 10-minute purge sweep analog
        # bound the parsed-path caches: high-cardinality paths (unique
        # URLs/query strings) must not grow them without limit. The caches
        # belong to the L7 worker — clear under its lock.
        with self._l7_lock:
            for cache in list(self._path_cache.values()):
                if len(cache) > _PATH_CACHE_MAX:
                    cache.clear()
        # prune idle rate-limit buckets (deployments without proc events
        # never hit the EXIT cleanup; idle = 10min behind the newest pid).
        # Under the L7 lock like every other bucket access (alazrace
        # ALZ050: the snapshot+pop used to race the L7 worker's inserts
        # on GIL atomicity alone); the sweep is 10-minute housekeeping,
        # so holding the RLock for the scan costs nothing measurable.
        with self._l7_lock:
            buckets = list(self._pid_buckets.items())
            if buckets:
                newest = max(b._last for _, b in buckets)
                for p, b in buckets:
                    if newest - b._last > 600:
                        self._pid_buckets.pop(p, None)
