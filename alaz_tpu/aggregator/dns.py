"""Reverse-DNS cache for outbound destination naming.

The reference names third-party destinations via reverse DNS with a
5-minute cache / 10-minute purge (getHostnameFromIP + reverseDnsCache,
aggregator/data.go:113-122,1390-1405), falling back to the IP string.
Lookups are gated (off by default — zero-egress test environments, and the
reference itself treats DNS failure as routine) and never block the hot
path: misses resolve to the IP string immediately and a background thread
fills the cache for later batches.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Dict, Optional

from alaz_tpu.config import env_bool
from alaz_tpu.events.net import u32_to_ip

DEFAULT_TTL_S = 300.0  # defaultExpiration (data.go:113)


def enabled() -> bool:
    return env_bool("REVERSE_DNS_ENABLED", False)


class ReverseDnsCache:
    def __init__(self, ttl_s: float = DEFAULT_TTL_S, do_lookups: Optional[bool] = None):
        self.ttl_s = ttl_s
        self.do_lookups = enabled() if do_lookups is None else do_lookups
        self._cache: Dict[int, tuple[str, float]] = {}  # guarded-by: self._lock
        self._pending: set[int] = set()  # guarded-by: self._lock
        self._queue: "queue.Queue[int]" = queue.Queue()  # internally synchronized
        self._lock = threading.Lock()
        # worker handle: checked/respawned under the lock in name_for so
        # two hot-path callers can't both spawn one
        self._worker: Optional[threading.Thread] = None  # guarded-by: self._lock

    def name_for(self, ip_u32: int, now_s: Optional[float] = None) -> str:
        """Best current name: cached hostname, else the dotted IP (a single
        background worker fills the cache when lookups are on — never one
        thread per IP, never blocking this call)."""
        now_s = time.monotonic() if now_s is None else now_s
        with self._lock:
            hit = self._cache.get(ip_u32)
            if hit is not None and now_s - hit[1] < self.ttl_s:
                return hit[0]
            if self.do_lookups and ip_u32 not in self._pending:
                self._pending.add(ip_u32)
                self._queue.put(ip_u32)
                if self._worker is None or not self._worker.is_alive():
                    self._worker = threading.Thread(
                        target=self._worker_loop, name="alaz-rdns", daemon=True
                    )
                    self._worker.start()
        return u32_to_ip(ip_u32)

    def _worker_loop(self) -> None:
        while True:
            try:
                ip_u32 = self._queue.get(timeout=30)
            except queue.Empty:
                return  # worker retires when idle; respawned on demand
            ip = u32_to_ip(ip_u32)
            try:
                host = socket.gethostbyaddr(ip)[0]
            except OSError:
                host = ip  # negative-cache the failure as the IP itself
            with self._lock:
                self._cache[ip_u32] = (host, time.monotonic())
                self._pending.discard(ip_u32)

    def put(self, ip_u32: int, name: str, now_s: Optional[float] = None) -> None:
        with self._lock:
            self._cache[ip_u32] = (name, time.monotonic() if now_s is None else now_s)

    def purge(self, now_s: Optional[float] = None) -> int:
        """Drop expired entries (the 10-minute purgeTime sweep)."""
        now_s = time.monotonic() if now_s is None else now_s
        with self._lock:
            dead = [k for k, (_, t) in self._cache.items() if now_s - t >= self.ttl_s]
            for k in dead:
                del self._cache[k]
            return len(dead)
