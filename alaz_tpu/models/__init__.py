"""GNN anomaly scorers over service-graph batches.

Model families per BASELINE.json:
- ``graphsage`` — 2-layer GraphSAGE, static snapshots (config 2)
- ``gat``       — attention + edge-type embeddings (config 3)
- ``experts``   — per-edge-type expert MLPs, the EP surface (SURVEY §2.3 P5)
- ``tgn``       — temporal memory over 1s windows (config 4)

All models are functional: ``init(key, cfg) -> params`` pytrees and
``apply(params, graph, cfg) -> {"node_h", "edge_logits", "node_logits"}``,
so sharding is just PartitionSpecs over the params pytree.
"""

from alaz_tpu.models import graphsage, gat, tgn
from alaz_tpu.models.registry import get_model

__all__ = ["graphsage", "gat", "tgn", "get_model"]
