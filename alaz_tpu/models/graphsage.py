"""GraphSAGE anomaly scorer — BASELINE.json config 2's flagship model.

Mean-aggregator GraphSAGE with edge-feature/edge-type-conditioned messages:

    m_e   = W_msg·h[src_e] + W_ef·e_e + T[type_e]
    agg_d = Σ_{e:dst=d} m_e / deg_d          (Pallas scatter on TPU)
    h'_d  = GELU(LN(W_self·h_d + W_neigh·agg_d)) + h_d

plus per-edge and per-node anomaly heads. Compute runs in bf16, params and
scatter accumulation in f32 (MXU-friendly; see SURVEY §7.6 roofline note).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from alaz_tpu.config import ModelConfig
from alaz_tpu.models.common import (
    compute_dtype,
    dense,
    dense_init,
    edge_head,
    edge_head_init,
    layernorm,
    layernorm_init,
    graph_block_starts,
    maybe_znorm_graph,
    mlp,
    graph_degree,
    mlp_init,
    scatter_messages,
)
from alaz_tpu.ops.segment import gather_src

Params = Dict[str, Any]


def init(key: jax.Array, cfg: ModelConfig) -> Params:
    h = cfg.hidden_dim
    keys = jax.random.split(key, 4 + 4 * cfg.num_layers)
    params: Params = {
        "embed": dense_init(keys[0], cfg.node_feature_dim, h),
        "edge_head": edge_head_init(keys[2], h, cfg.edge_feat_dim_in),
        "node_head": mlp_init(keys[3], [h, h, 1]),
        "layers": [],
    }
    for l in range(cfg.num_layers):
        k = keys[4 + 4 * l : 8 + 4 * l]
        params["layers"].append(
            {
                "msg": dense_init(k[0], h, h),
                "edge_proj": dense_init(k[1], cfg.edge_feat_dim_in, h),
                "self": dense_init(k[2], h, h),
                "neigh": dense_init(k[3], h, h),
                "ln": layernorm_init(h),
            }
        )
    return params


def apply(params: Params, graph: dict, cfg: ModelConfig, h_bias=None) -> dict:
    """Forward pass. ``h_bias`` ([N, H], optional) is added to the embedded
    node state before message passing — the hook the temporal model (tgn)
    uses to condition on its node memory."""
    dtype = compute_dtype(cfg)
    graph = maybe_znorm_graph(graph, cfg)
    n = graph["node_feats"].shape[0]
    node_mask = graph["node_mask"].astype(jnp.float32)
    edge_mask = graph["edge_mask"]

    h = dense(params["embed"], graph["node_feats"].astype(dtype))
    if h_bias is not None:
        h = h + h_bias.astype(dtype)
    # the residual stream rides in f32 (matmuls stay in the compute
    # dtype): a bf16 carry makes the remat'd backward recompute round
    # differently from the saved activations (grad drift up to ~5%
    # relative under jax.checkpoint); f32 elementwise accumulation is
    # VPU-cheap next to the MXU matmuls and keeps remat grad-exact
    h = h.astype(jnp.float32) * node_mask[:, None]

    # edge-type conditioning rides the protocol one-hot in edge_feats
    # slots 7..15 (builder.py): the edge_proj matmul learns type offsets,
    # so no per-edge [E]-row embedding gather is needed (row-op bound at
    # ~9ns/row on TPU — it would cost as much as the whole scatter).
    ef = graph["edge_feats"].astype(dtype)
    # degree is layer-invariant AND a window invariant: shipped with
    # the batch (host bincount) — the in-graph fallback covers
    # hand-built graph dicts (models/common.py graph_degree)
    deg = graph_degree(graph, jnp.float32, n)
    # blocked layout: the host-shipped dst-block extents (None under COO)
    block_starts = graph_block_starts(graph, cfg)

    def layer_fn(layer, h32):
        h = h32.astype(dtype)
        # dense-before-gather: (h @ W)[src] == (h[src]) @ W, but the
        # matmul runs over N node rows instead of E edge rows (8× fewer
        # FLOPs at config-5 fan-in) and the gather moves the same bytes
        msgs = gather_src(
            dense(layer["msg"], h), graph["edge_src"], n, cfg.src_gather
        ) + dense(layer["edge_proj"], ef)
        agg, _ = scatter_messages(
            msgs, graph["edge_dst"], edge_mask, n, cfg.use_pallas, deg=deg,
            block_starts=block_starts,
        )
        agg = agg / jnp.maximum(deg, 1.0)[:, None]
        h_new = dense(layer["self"], h) + dense(layer["neigh"], agg.astype(dtype))
        h_new = jax.nn.gelu(layernorm(layer["ln"], h_new.astype(jnp.float32)))
        return (h32 + h_new) * node_mask[:, None]

    if cfg.remat:
        # rematerialize per layer: trade recompute for activation memory
        # (the jax.checkpoint lever for deep GNNs / big buckets)
        layer_fn = jax.checkpoint(layer_fn)
    for layer in params["layers"]:
        h = layer_fn(layer, h)
    h = h.astype(dtype)

    edge_logits = edge_head(params["edge_head"], h, graph, dtype, cfg.use_pallas, cfg.src_gather)
    node_logits = mlp(params["node_head"], h)[:, 0]
    return {
        "node_h": h,
        "edge_logits": edge_logits.astype(jnp.float32),
        "node_logits": node_logits.astype(jnp.float32),
    }
