"""Temporal GNN over 1s windows — BASELINE.json config 4 (TGN-style
latency-spike forecasting).

A persistent per-node memory (node slots are stable across windows thanks
to the builder's NodeTable) is combined with each window's snapshot
encoding and updated with a GRU cell:

    h_t   = GraphSAGE(x_t ; h_bias = W_m·m_{t-1})
    m_t   = GRU(m_{t-1}, h_t)        (active nodes only)

Scores are read from h_t. Memory is an [M, H] array; when a window's node
bucket outgrows M the memory is zero-extended to the new bucket, so
streaming callers can size it from the first window and let it grow.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

from alaz_tpu.config import ModelConfig
from alaz_tpu.models import graphsage
from alaz_tpu.models.common import compute_dtype, dense, dense_init

Params = Dict[str, Any]


@functools.lru_cache(maxsize=None)
def make_step_fn(cfg: ModelConfig):
    """Jitted ``step`` closed over a ModelConfig, cached per config so
    every streaming caller (the scoring service, the eval CLI) shares ONE
    trace cache — constructing a fresh ``jax.jit(lambda ...)`` per caller
    re-traces per (caller, bucket) instead of per bucket (ALZ006, retrace
    budget). ModelConfig is a frozen dataclass, so equal configs hit."""

    def tgn_step(params, graph, memory):
        return step(params, graph, memory, cfg)

    return jax.jit(tgn_step)


def init(key: jax.Array, cfg: ModelConfig) -> Params:
    h = cfg.hidden_dim
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    gru_z = dense_init(k4, 2 * h, h)
    # bias the update gate toward the fresh encoding at init (z ≈ 0.12) so
    # early training isn't dominated by stale memory
    gru_z["b"] = gru_z["b"] - 2.0
    return {
        "encoder": graphsage.init(k1, cfg),
        "mem_in": dense_init(k2, h, h),
        "gru_r": dense_init(k3, 2 * h, h),
        "gru_z": gru_z,
        "gru_n": dense_init(k5, 2 * h, h),
    }


def init_memory(cfg: ModelConfig, max_nodes: int) -> jnp.ndarray:
    return jnp.zeros((max_nodes, cfg.hidden_dim), dtype=jnp.float32)


def apply(params: Params, graph: dict, cfg: ModelConfig) -> dict:
    """Memoryless single-window forward (cold-start memory): the 3-arg
    apply surface the registry/score paths expect. Streaming callers
    thread temporal memory via ``step`` (runtime/service.py does), and
    TRAINING must use ``train_tgn_unrolled`` — through this cold-start
    path the GRU/memory parameters receive no gradient (the updated
    memory is discarded), so only the snapshot encoder would learn."""
    memory = init_memory(cfg, max_nodes=graph["node_feats"].shape[0])
    out, _ = step(params, graph, memory, cfg)
    return out


def step(params: Params, graph: dict, memory: jnp.ndarray, cfg: ModelConfig) -> tuple[dict, jnp.ndarray]:
    """One window: encode snapshot conditioned on memory, emit scores,
    return updated memory (zero-extended if the node bucket grew)."""
    dtype = compute_dtype(cfg)
    n_pad = graph["node_feats"].shape[0]
    if memory.shape[0] < n_pad:
        memory = jnp.pad(memory, ((0, n_pad - memory.shape[0]), (0, 0)))
    mem = memory[:n_pad]

    out = graphsage.apply(
        params["encoder"],
        graph,
        cfg,
        h_bias=dense(params["mem_in"], mem.astype(dtype)),
    )
    h = out["node_h"].astype(jnp.float32)

    # GRU memory update for active nodes
    m_prev = memory[:n_pad]
    hz = jnp.concatenate([m_prev.astype(dtype), h.astype(dtype)], axis=-1)
    r = jax.nn.sigmoid(dense(params["gru_r"], hz)).astype(jnp.float32)
    z = jax.nn.sigmoid(dense(params["gru_z"], hz)).astype(jnp.float32)
    hn = jnp.concatenate([(r * m_prev).astype(dtype), h.astype(dtype)], axis=-1)
    n_t = jnp.tanh(dense(params["gru_n"], hn)).astype(jnp.float32)
    m_new = (1 - z) * n_t + z * m_prev

    active = graph["node_mask"][:, None]
    m_next = jnp.where(active, m_new, m_prev)
    memory = memory.at[:n_pad].set(m_next)
    return out, memory
