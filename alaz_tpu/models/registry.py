"""Model registry: name → (init, apply)."""

from __future__ import annotations

from alaz_tpu.models import gat, graphsage

# Every registered single-device model (get_model names), and the subset
# with node-sharded shard_map twins (parallel/sharded_model.py makers).
# alazspec generates one golden specfile per (name, bucket) for all of
# these — keep both tuples in sync with get_model / the makers.
REGISTERED_MODELS = ("graphsage", "gat", "tgn", "experts")
NODE_SHARDED_TWINS = ("graphsage", "gat")


def get_model(name: str):
    if name == "graphsage":
        return graphsage.init, graphsage.apply
    if name == "gat":
        return gat.init, gat.apply
    if name == "tgn":
        from alaz_tpu.models import tgn

        # 3-arg apply (cold memory); temporal callers use tgn.step directly
        return tgn.init, tgn.apply
    if name == "experts":
        from alaz_tpu.models import experts

        return experts.init, experts.apply
    raise ValueError(f"unknown model {name!r} ({'|'.join(REGISTERED_MODELS)})")
