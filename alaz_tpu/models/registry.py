"""Model registry: name → (init, apply)."""

from __future__ import annotations

from alaz_tpu.models import gat, graphsage


def get_model(name: str):
    if name == "graphsage":
        return graphsage.init, graphsage.apply
    if name == "gat":
        return gat.init, gat.apply
    if name == "tgn":
        from alaz_tpu.models import tgn

        # 3-arg apply (cold memory); temporal callers use tgn.step directly
        return tgn.init, tgn.apply
    if name == "experts":
        from alaz_tpu.models import experts

        return experts.init, experts.apply
    raise ValueError(f"unknown model {name!r} (graphsage|gat|tgn|experts)")
