"""Shared model building blocks (functional, no framework)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alaz_tpu.config import ModelConfig
from alaz_tpu.ops.segment import (  # noqa: F401
    gather_scatter_sum,
    pallas_enabled,
    segment_mean,
    segment_sum_sorted_dispatch,
)


def dense_init(key, in_dim: int, out_dim: int) -> dict:
    k1, _ = jax.random.split(key)
    scale = (2.0 / in_dim) ** 0.5
    return {
        "w": jax.random.normal(k1, (in_dim, out_dim), dtype=jnp.float32) * scale,
        "b": jnp.zeros((out_dim,), dtype=jnp.float32),
    }


def dense(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    return x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)


def layernorm_init(dim: int) -> dict:
    return {"g": jnp.ones((dim,), jnp.float32), "b": jnp.zeros((dim,), jnp.float32)}


def layernorm(params: dict, x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    return y * params["g"].astype(x.dtype) + params["b"].astype(x.dtype)


def mlp_init(key, dims: list[int]) -> list[dict]:
    keys = jax.random.split(key, len(dims) - 1)
    return [dense_init(k, dims[i], dims[i + 1]) for i, k in enumerate(keys)]


def mlp(params: list[dict], x: jnp.ndarray) -> jnp.ndarray:
    for i, layer in enumerate(params):
        x = dense(layer, x)
        if i + 1 < len(params):
            x = jax.nn.gelu(x)
    return x


def compute_dtype(cfg: ModelConfig):
    return jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32


# leading edge-feature columns that carry window STATS (count, mean/max
# latency, 5xx/4xx rates, tls share, request rate — graph/builder.py
# ef[:, 0:7]); the z-norm augmentation scores exactly these
EDGE_STAT_COLS = 7


def znorm_edge_feats(
    ef: jnp.ndarray,
    edge_mask: jnp.ndarray,
    axis: str | None = None,
    eps: float = 1e-8,
    clip: float = 8.0,
) -> jnp.ndarray:
    """[E, F] → [E, F + EDGE_STAT_COLS]: append per-window z-scores of
    the stat columns, each edge measured against the window's fleet
    baseline. An edge whose latency drifts 2-4x reads as a shift of
    ~1e-2 in absolute log-latency (lost next to node-embedding
    variance) but tens of σ in the z-scored copy — the representation
    that makes sub-threshold drift (and thus next-window forecasting,
    BASELINE config 4) learnable. Stats accumulate in f32 whatever the
    feature dtype; ``axis`` psums them across node shards inside
    shard_map so sharded and single-device forwards agree; z of padded
    edges is forced to 0."""
    m = edge_mask.astype(jnp.float32)[:, None]
    stats = ef[:, :EDGE_STAT_COLS].astype(jnp.float32)
    cnt = m.sum()
    s1 = (stats * m).sum(0)
    s2 = (stats * stats * m).sum(0)
    if axis is not None:
        cnt = jax.lax.psum(cnt, axis)
        s1 = jax.lax.psum(s1, axis)
        s2 = jax.lax.psum(s2, axis)
    cnt = jnp.maximum(cnt, 1.0)
    mean = s1 / cnt
    var = jnp.maximum(s2 / cnt - mean * mean, 0.0)
    z = (stats - mean) * jax.lax.rsqrt(var + eps)
    z = jnp.clip(z, -clip, clip) * m
    return jnp.concatenate([ef, z.astype(ef.dtype)], axis=1)


def maybe_znorm_graph(graph: dict, cfg: ModelConfig, axis: str | None = None) -> dict:
    """Model-entry hook: returns ``graph`` with augmented edge_feats when
    cfg.edge_feat_znorm (idempotence guard: skips if the width already
    matches edge_feat_dim_in, so wrappers can call it defensively)."""
    if not cfg.edge_feat_znorm:
        return graph
    if graph["edge_feats"].shape[1] >= cfg.edge_feat_dim_in:
        return graph
    return dict(
        graph,
        edge_feats=znorm_edge_feats(graph["edge_feats"], graph["edge_mask"], axis=axis),
    )


def graph_block_starts(graph: dict, cfg: ModelConfig) -> jnp.ndarray | None:
    """The blocked layout's per-128-dst extents for this batch, or None
    under COO — the ONE model-entry selection point (ISSUE 20). A plain
    Python branch on the config string plus a dict-key lookup, so the
    choice is static under jit: per layout the traced pytree is fixed
    and selection costs zero retraces (alazjit-pinned). A blocked
    config over a batch that never shipped extents raises instead of
    silently scoring the COO path — a quiet fallback would poison every
    '[blocked]'-tagged benchmark series."""
    if cfg.edge_layout != "blocked":
        return None
    bs = graph.get("edge_block_starts")
    if bs is None:
        raise ValueError(
            "edge_layout='blocked' but the graph carries no "
            "edge_block_starts — ship batches via "
            "GraphBatch.device_arrays(edge_layout='blocked')"
        )
    return bs


def scatter_messages(
    msgs: jnp.ndarray,
    edge_dst: jnp.ndarray,
    edge_mask: jnp.ndarray,
    num_nodes: int,
    use_pallas: bool | str,
    deg: jnp.ndarray | None = None,
    block_starts: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Masked message scatter → (sum [N,H], degree [N]). Dispatches like
    ``segment_sum_sorted_dispatch`` (Pallas dst-sorted kernel on TPU /
    forced ``"interpret"``, XLA segment_sum elsewhere); ``block_starts``
    routes both paths through the blocked layout's extent-aware
    variants (bit-exact — ops/segment.py blocked_segment_sum)."""
    mask_col = edge_mask[:, None].astype(msgs.dtype)
    m = msgs * mask_col
    if deg is None and pallas_enabled(use_pallas) and msgs.shape[1] % 128 != 0:
        # the kernel pads features to the next 128-lane tile anyway, so
        # the degree column rides in the pad slack for free (and the MXU
        # accumulates the counts in f32)
        from alaz_tpu.ops.pallas_segment import scatter_sum_sorted

        out = scatter_sum_sorted(
            jnp.concatenate([m, mask_col], axis=1), edge_dst, num_nodes,
            None, block_starts,
        )
        return out[:, :-1], out[:, -1]
    agg = segment_sum_sorted_dispatch(
        m, edge_dst, num_nodes, use_pallas, block_starts=block_starts
    )
    if deg is None:
        # models hoist this via masked_degree (edge_dst/edge_mask are
        # layer-invariant); recomputed here only for direct callers
        deg = masked_degree(edge_mask, edge_dst, num_nodes, msgs.dtype)
    return agg, deg


def masked_degree(edge_mask, edge_dst, num_nodes: int, dtype) -> jnp.ndarray:
    """deg[d] = Σ_{e: dst[e]=d} mask[e] — layer-invariant, so models
    compute it ONCE per forward and thread it through every
    scatter_messages call instead of re-scattering [E] per layer."""
    return jax.ops.segment_sum(
        edge_mask.astype(dtype), edge_dst, num_segments=num_nodes
    )


def graph_degree(graph: dict, dtype, num_nodes: int) -> jnp.ndarray:
    """The per-forward in-degree: the host-shipped window invariant when
    the batch carries it (GraphBatch.device_arrays ``node_deg`` — one
    bincount at close time), else the in-graph segment_sum. The device
    fallback is what XLA lowers to a [E]-pair sort + reduce on TPU
    (~10 ms/window at the 1M-edge bucket, r03 trace) — every dispatch
    path that can ship the invariant should."""
    deg = graph.get("node_deg")
    if deg is not None:
        return deg.astype(dtype)
    return masked_degree(graph["edge_mask"], graph["edge_dst"], num_nodes, dtype)


def edge_head_init(key, hidden: int, edge_feat_dim: int) -> list[dict]:
    return mlp_init(key, [2 * hidden + edge_feat_dim, hidden, 1])


def edge_head(
    params, h, graph, dtype, use_pallas: bool | str = False,
    src_gather_mode: str = "xla",
) -> jnp.ndarray:
    """Per-edge anomaly logit from [h_src, h_dst, edge_feats].

    Computed as the split form of ``mlp(params, concat([h[src], h[dst],
    ef]))``: the first layer's weight rows are partitioned into
    (src, dst, ef) blocks, the node-side products run on [N, H] node
    states *before* the per-edge gathers, and no [E, 2H+F] concat is ever
    materialized — identical math and identical params, but the E-row
    matmul (the step's FLOP peak) becomes two N-row matmuls. The dst-side
    expand additionally rides the sorted-segment Pallas kernel (edges are
    dst-sorted), dodging a row-op-bound XLA gather."""
    w1 = params[0]["w"].astype(dtype)
    hdim = h.shape[-1]
    u = h @ w1[:hdim]  # [N, H'] src-side projection
    v = h @ w1[hdim : 2 * hdim]  # [N, H'] dst-side projection
    efp = graph["edge_feats"].astype(dtype) @ w1[2 * hdim :]
    from alaz_tpu.ops.segment import expand_dst, gather_src

    v_e = expand_dst(v, graph["edge_dst"], h.shape[0], use_pallas)
    u_e = gather_src(u, graph["edge_src"], h.shape[0], src_gather_mode)
    z = u_e + v_e + efp + params[0]["b"].astype(dtype)
    return mlp(params[1:], jax.nn.gelu(z))[:, 0]
