"""Shared model building blocks (functional, no framework)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alaz_tpu.config import ModelConfig
from alaz_tpu.ops.segment import gather_scatter_sum, segment_mean  # noqa: F401


def dense_init(key, in_dim: int, out_dim: int) -> dict:
    k1, _ = jax.random.split(key)
    scale = (2.0 / in_dim) ** 0.5
    return {
        "w": jax.random.normal(k1, (in_dim, out_dim), dtype=jnp.float32) * scale,
        "b": jnp.zeros((out_dim,), dtype=jnp.float32),
    }


def dense(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    return x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)


def layernorm_init(dim: int) -> dict:
    return {"g": jnp.ones((dim,), jnp.float32), "b": jnp.zeros((dim,), jnp.float32)}


def layernorm(params: dict, x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    return y * params["g"].astype(x.dtype) + params["b"].astype(x.dtype)


def mlp_init(key, dims: list[int]) -> list[dict]:
    keys = jax.random.split(key, len(dims) - 1)
    return [dense_init(k, dims[i], dims[i + 1]) for i, k in enumerate(keys)]


def mlp(params: list[dict], x: jnp.ndarray) -> jnp.ndarray:
    for i, layer in enumerate(params):
        x = dense(layer, x)
        if i + 1 < len(params):
            x = jax.nn.gelu(x)
    return x


def compute_dtype(cfg: ModelConfig):
    return jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32


def scatter_messages(
    msgs: jnp.ndarray,
    edge_dst: jnp.ndarray,
    edge_mask: jnp.ndarray,
    num_nodes: int,
    use_pallas: bool | str,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Masked message scatter → (sum [N,H], degree [N]). Uses the Pallas
    dst-sorted kernel on TPU, XLA segment_sum elsewhere. ``use_pallas``
    may be the string ``"interpret"`` to force the Pallas path off-TPU
    (pl.pallas_call interpret mode) — how the sharding tests exercise the
    kernel+shard_map interaction on a CPU mesh."""
    m = msgs * edge_mask[:, None].astype(msgs.dtype)
    if (use_pallas and jax.default_backend() == "tpu") or use_pallas == "interpret":
        from alaz_tpu.ops.pallas_segment import scatter_sum_sorted

        agg = scatter_sum_sorted(m, edge_dst, num_nodes)
    else:
        agg = jax.ops.segment_sum(m, edge_dst, num_segments=num_nodes)
    deg = jax.ops.segment_sum(
        edge_mask.astype(msgs.dtype), edge_dst, num_segments=num_nodes
    )
    return agg, deg


def edge_head_init(key, hidden: int, edge_feat_dim: int) -> list[dict]:
    return mlp_init(key, [2 * hidden + edge_feat_dim, hidden, 1])


def edge_head(params, h, graph, dtype) -> jnp.ndarray:
    """Per-edge anomaly logit from [h_src, h_dst, edge_feats]."""
    z = jnp.concatenate(
        [
            h[graph["edge_src"]],
            h[graph["edge_dst"]],
            graph["edge_feats"].astype(dtype),
        ],
        axis=-1,
    )
    return mlp(params, z)[:, 0]
