"""Edge-type expert model (EP) — per-protocol expert MLPs.

The reference dispatches each event to a per-protocol handler
(data.go:1364-1383); here that becomes per-edge-type expert message
transforms (SURVEY §2.3 P5): each L7 protocol gets its own message weight
``W_t``, computed as a masked sum of T dense matmuls (T is small and
static, so every matmul is MXU-shaped and the routing is branch-free):

    m_e = Σ_t 1[type_e = t] · (h[src_e] @ W_t + b_t)

Expert tables are stacked ``[T, H, H]``; under pjit the T axis shards over
the ``ep`` mesh axis and XLA turns the masked sum into compute-where-
resident + all-reduce.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from alaz_tpu.config import ModelConfig
from alaz_tpu.models.common import (
    compute_dtype,
    dense,
    dense_init,
    edge_head,
    edge_head_init,
    layernorm,
    layernorm_init,
    mlp,
    mlp_init,
    scatter_messages,
)
from alaz_tpu.ops.segment import gather_src

Params = Dict[str, Any]


def init(key: jax.Array, cfg: ModelConfig) -> Params:
    h = cfg.hidden_dim
    t = cfg.num_edge_types
    keys = jax.random.split(key, 4 + 4 * cfg.num_layers)
    params: Params = {
        "embed": dense_init(keys[0], cfg.node_feature_dim, h),
        "edge_head": edge_head_init(keys[2], h, cfg.edge_feature_dim),
        "node_head": mlp_init(keys[3], [h, h, 1]),
        "layers": [],
    }
    for l in range(cfg.num_layers):
        k = jax.random.split(keys[4 + l], 5)
        scale = (2.0 / h) ** 0.5
        params["layers"].append(
            {
                # stacked experts: [T, H, H] + [T, H]
                "expert_w": jax.random.normal(k[0], (t, h, h), jnp.float32) * scale,
                "expert_b": jnp.zeros((t, h), jnp.float32),
                "edge_proj": dense_init(k[1], cfg.edge_feature_dim, h),
                "self": dense_init(k[2], h, h),
                "neigh": dense_init(k[3], h, h),
                "ln": layernorm_init(h),
            }
        )
    return params


def _expert_messages(layer: Params, h_src: jnp.ndarray, edge_type: jnp.ndarray, dtype) -> jnp.ndarray:
    """Masked sum over experts — T static matmuls, no gather of weights."""
    t = layer["expert_w"].shape[0]
    out = jnp.zeros_like(h_src)
    for ti in range(t):
        w = layer["expert_w"][ti].astype(dtype)
        b = layer["expert_b"][ti].astype(dtype)
        mask = (edge_type == ti).astype(dtype)[:, None]
        out = out + mask * (h_src @ w + b)
    return out


def apply(params: Params, graph: dict, cfg: ModelConfig) -> dict:
    dtype = compute_dtype(cfg)
    n = graph["node_feats"].shape[0]
    node_mask = graph["node_mask"].astype(dtype)
    edge_mask = graph["edge_mask"]

    h = dense(params["embed"], graph["node_feats"].astype(dtype)) * node_mask[:, None]
    ef = graph["edge_feats"].astype(dtype)

    for layer in params["layers"]:
        msgs = _expert_messages(
            layer,
            gather_src(h, graph["edge_src"], n, cfg.src_gather),
            graph["edge_type"],
            dtype,
        )
        msgs = msgs + dense(layer["edge_proj"], ef)
        agg, deg = scatter_messages(msgs, graph["edge_dst"], edge_mask, n, cfg.use_pallas)
        agg = agg / jnp.maximum(deg, 1.0)[:, None]
        h_new = dense(layer["self"], h) + dense(layer["neigh"], agg.astype(dtype))
        h_new = jax.nn.gelu(layernorm(layer["ln"], h_new))
        h = (h + h_new) * node_mask[:, None]

    edge_logits = edge_head(params["edge_head"], h, graph, dtype, cfg.use_pallas, cfg.src_gather)
    node_logits = mlp(params["node_head"], h)[:, 0]
    return {
        "node_h": h,
        "edge_logits": edge_logits.astype(jnp.float32),
        "node_logits": node_logits.astype(jnp.float32),
    }
