"""Edge-type expert model (EP) — per-protocol expert MLPs.

The reference dispatches each event to a per-protocol handler
(data.go:1364-1383); here that becomes per-edge-type expert message
transforms (SURVEY §2.3 P5): each L7 protocol gets its own message weight
``W_t``,

    m_e = h[src_e] @ W_{type_e} + b_{type_e}

computed in one of two equivalent forms selected by
``ModelConfig.expert_dispatch``:

- ``"table"`` (default): per-expert node tables ``u_t = h @ W_t`` (T
  MXU-shaped N-row matmuls) + ONE (type, src) row gather — the
  single-chip fast path.
- ``"masked"``: ``Σ_t 1[type_e = t] · (h[src_e] @ W_t + b_t)`` — T
  branch-free E-row matmuls whose stacked ``[T, H, H]`` expert axis
  shards over the ``ep`` mesh axis under pjit (compute-where-resident +
  all-reduce); the sharded train/score steps force this form when ep>1.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from alaz_tpu.config import ModelConfig
from alaz_tpu.models.common import (
    compute_dtype,
    dense,
    dense_init,
    edge_head,
    edge_head_init,
    layernorm,
    layernorm_init,
    maybe_znorm_graph,
    mlp,
    graph_degree,
    mlp_init,
    scatter_messages,
)
from alaz_tpu.ops.segment import gather_src

Params = Dict[str, Any]


def init(key: jax.Array, cfg: ModelConfig) -> Params:
    h = cfg.hidden_dim
    t = cfg.num_edge_types
    keys = jax.random.split(key, 4 + 4 * cfg.num_layers)
    params: Params = {
        "embed": dense_init(keys[0], cfg.node_feature_dim, h),
        "edge_head": edge_head_init(keys[2], h, cfg.edge_feat_dim_in),
        "node_head": mlp_init(keys[3], [h, h, 1]),
        "layers": [],
    }
    for l in range(cfg.num_layers):
        k = jax.random.split(keys[4 + l], 5)
        scale = (2.0 / h) ** 0.5
        params["layers"].append(
            {
                # stacked experts: [T, H, H] + [T, H]
                "expert_w": jax.random.normal(k[0], (t, h, h), jnp.float32) * scale,
                "expert_b": jnp.zeros((t, h), jnp.float32),
                "edge_proj": dense_init(k[1], cfg.edge_feat_dim_in, h),
                "self": dense_init(k[2], h, h),
                "neigh": dense_init(k[3], h, h),
                "ln": layernorm_init(h),
            }
        )
    return params


def _expert_messages_masked(
    layer: Params, h_src: jnp.ndarray, edge_type: jnp.ndarray, dtype
) -> jnp.ndarray:
    """Masked sum over experts — T static matmuls, no gather of weights.
    The T axis shards over 'ep' (each device computes its resident
    experts, psum completes the sum), but every expert reads and writes
    the full [E, H] edge axis: ~2·T·E·H bytes of mask traffic/layer."""
    t = layer["expert_w"].shape[0]
    out = jnp.zeros_like(h_src)
    for ti in range(t):
        w = layer["expert_w"][ti].astype(dtype)
        b = layer["expert_b"][ti].astype(dtype)
        mask = (edge_type == ti).astype(dtype)[:, None]
        out = out + mask * (h_src @ w + b)
    return out


def _expert_messages_table(
    layer: Params,
    h: jnp.ndarray,
    edge_src: jnp.ndarray,
    edge_type: jnp.ndarray,
    dtype,
) -> jnp.ndarray:
    """Dense-before-gather over experts: u_t = h @ W_t over N rows (T
    cheap matmuls), then ONE row gather from the stacked [T·N, H] table
    at (type, src) — same math as the masked sum with the edge-axis
    traffic collapsed to a single row-op pass. Single-chip fast path;
    under ep>1 sharding the [T, N, H] tables would all-gather, so the
    sharded steps force the masked form (parallel/sharding.py)."""
    t, hdim = layer["expert_w"].shape[0], h.shape[1]
    n = h.shape[0]
    w = layer["expert_w"].astype(dtype)  # [T, H, H]
    b = layer["expert_b"].astype(dtype)  # [T, H]
    u = jnp.einsum("nh,thk->tnk", h, w) + b[:, None, :]
    flat = u.reshape(t * n, hdim)
    idx = edge_type.astype(jnp.int32) * n + edge_src
    # protocol codes outside [0, T) got zero messages from the masked
    # form; clip + zero keeps that contract instead of clamp-gathering
    valid = ((edge_type >= 0) & (edge_type < t)).astype(dtype)[:, None]
    return flat[jnp.clip(idx, 0, t * n - 1)] * valid


def apply(params: Params, graph: dict, cfg: ModelConfig) -> dict:
    dtype = compute_dtype(cfg)
    graph = maybe_znorm_graph(graph, cfg)
    n = graph["node_feats"].shape[0]
    node_mask = graph["node_mask"].astype(dtype)
    edge_mask = graph["edge_mask"]

    h = dense(params["embed"], graph["node_feats"].astype(dtype)) * node_mask[:, None]
    ef = graph["edge_feats"].astype(dtype)
    # degree is layer-invariant AND a window invariant: shipped with
    # the batch (host bincount) — the in-graph fallback covers
    # hand-built graph dicts (models/common.py graph_degree)
    deg = graph_degree(graph, dtype, n)

    if cfg.expert_dispatch not in ("table", "masked"):
        # a typo (EXPERT_DISPATCH=tabel) silently running the slow form
        # would poison every '[experts]' benchmark row — same contract as
        # gather_src's mode check
        raise ValueError(
            f"expert_dispatch {cfg.expert_dispatch!r}; expected 'table' or 'masked'"
        )
    for layer in params["layers"]:
        if cfg.expert_dispatch == "table":
            msgs = _expert_messages_table(
                layer, h, graph["edge_src"], graph["edge_type"], dtype
            )
        else:
            msgs = _expert_messages_masked(
                layer,
                gather_src(h, graph["edge_src"], n, cfg.src_gather),
                graph["edge_type"],
                dtype,
            )
        msgs = msgs + dense(layer["edge_proj"], ef)
        agg, _ = scatter_messages(
            msgs, graph["edge_dst"], edge_mask, n, cfg.use_pallas, deg=deg
        )
        agg = agg / jnp.maximum(deg, 1.0)[:, None]
        h_new = dense(layer["self"], h) + dense(layer["neigh"], agg.astype(dtype))
        h_new = jax.nn.gelu(layernorm(layer["ln"], h_new))
        h = (h + h_new) * node_mask[:, None]

    edge_logits = edge_head(params["edge_head"], h, graph, dtype, cfg.use_pallas, cfg.src_gather)
    node_logits = mlp(params["node_head"], h)[:, 0]
    return {
        "node_h": h,
        "edge_logits": edge_logits.astype(jnp.float32),
        "node_logits": node_logits.astype(jnp.float32),
    }
