"""GAT with typed attention — BASELINE.json config 3 (10k-pod mixed
HTTP/gRPC/Postgres/Kafka edges).

Multi-head additive attention over incoming edges; attention logits are
conditioned on source, destination, and edge features — which carry the
protocol one-hot in slots 7..15 (the reference's per-protocol handler
dispatch, SURVEY §2.3 P5, re-expressed as typed attention; the one-hot
is folded into edge_feats at build time so no per-edge embedding gather
runs on device). Per-destination normalization uses masked segment
softmax with the sorted-expand kernel for its broadcasts.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from alaz_tpu.config import ModelConfig
from alaz_tpu.models.common import (
    compute_dtype,
    dense,
    dense_init,
    edge_head,
    edge_head_init,
    layernorm,
    layernorm_init,
    mlp,
    mlp_init,
    scatter_sum,
)
from alaz_tpu.ops.segment import expand_dst, gather_src, segment_softmax

Params = Dict[str, Any]


def init(key: jax.Array, cfg: ModelConfig) -> Params:
    h = cfg.hidden_dim
    nh = cfg.num_heads
    assert h % nh == 0, "num_heads must divide hidden_dim"
    keys = jax.random.split(key, 4 + 6 * cfg.num_layers)
    params: Params = {
        "embed": dense_init(keys[0], cfg.node_feature_dim, h),
        "edge_head": edge_head_init(keys[2], h, cfg.edge_feature_dim),
        "node_head": mlp_init(keys[3], [h, h, 1]),
        "layers": [],
    }
    for l in range(cfg.num_layers):
        k = keys[4 + 6 * l : 10 + 6 * l]
        params["layers"].append(
            {
                "q": dense_init(k[0], h, h),
                "kv": dense_init(k[1], h, h),
                "edge_proj": dense_init(k[2], cfg.edge_feature_dim, h),
                "attn": jax.random.normal(k[3], (nh, 3 * (h // nh)), jnp.float32) * 0.05,
                "out": dense_init(k[4], h, h),
                "ln": layernorm_init(h),
            }
        )
    return params


def apply(params: Params, graph: dict, cfg: ModelConfig) -> dict:
    dtype = compute_dtype(cfg)
    n = graph["node_feats"].shape[0]
    nh = cfg.num_heads
    hd = cfg.hidden_dim // nh
    node_mask = graph["node_mask"].astype(dtype)
    edge_mask = graph["edge_mask"]
    src, dst = graph["edge_src"], graph["edge_dst"]

    h = dense(params["embed"], graph["node_feats"].astype(dtype)) * node_mask[:, None]
    # edge-type conditioning rides the protocol one-hot in edge_feats
    # slots 7..15 (builder.py), learned through edge_proj — no per-edge
    # embedding gather (row-op bound on TPU)
    ef = graph["edge_feats"].astype(dtype)

    def layer_fn(layer, h):
        # attention logit = a·[q_dst, kv_src, e_feat] re-associated into
        # per-node/per-edge partial dot products: the dst-side partial
        # rides the sorted expand, only the src side stays a row gather
        attn = layer["attn"].astype(dtype)  # [nh, 3hd]
        a_q, a_k, a_e = attn[:, :hd], attn[:, hd : 2 * hd], attn[:, 2 * hd :]
        q = dense(layer["q"], h).reshape(n, nh, hd)
        kv = dense(layer["kv"], h).reshape(n, nh, hd)
        e_feat = dense(layer["edge_proj"], ef).reshape(-1, nh, hd)

        q_part = jnp.einsum("nhd,hd->nh", q, a_q)  # [N, nh]
        e_part = jnp.einsum("ehd,hd->eh", e_feat, a_e)  # [E, nh]
        # the one irreducible src gather per layer (flattened to 2D so
        # the banded kernel applies after a clustered layout)
        kv_src = gather_src(
            kv.reshape(n, nh * hd), src, n, cfg.src_gather
        ).reshape(-1, nh, hd)
        k_src = jnp.einsum("ehd,hd->eh", kv_src, a_k)
        logits = (
            expand_dst(q_part, dst, n, cfg.use_pallas) + k_src + e_part
        ).astype(jnp.float32)
        logits = jax.nn.leaky_relu(logits, 0.2)
        alpha = segment_softmax(
            logits, dst, n, mask=edge_mask, use_pallas=cfg.use_pallas
        ).astype(dtype)  # [E, nh]

        # attention weights already sum to 1 per dst — no degree
        # normalization, so no [E]-row degree scatter at all
        msgs = ((kv_src + e_feat) * alpha[:, :, None]).reshape(-1, nh * hd)
        agg = scatter_sum(msgs, dst, edge_mask, n, cfg.use_pallas)
        h_new = dense(layer["out"], agg.astype(dtype))
        return (h + jax.nn.gelu(layernorm(layer["ln"], h_new))) * node_mask[:, None]

    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)
    for layer in params["layers"]:
        h = layer_fn(layer, h)

    edge_logits = edge_head(params["edge_head"], h, graph, dtype, cfg.use_pallas, cfg.src_gather)
    node_logits = mlp(params["node_head"], h)[:, 0]
    return {
        "node_h": h,
        "edge_logits": edge_logits.astype(jnp.float32),
        "node_logits": node_logits.astype(jnp.float32),
    }
