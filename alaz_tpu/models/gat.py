"""GAT with typed attention — BASELINE.json config 3 (10k-pod mixed
HTTP/gRPC/Postgres/Kafka edges).

Multi-head additive attention over incoming edges; attention logits are
conditioned on source, destination, and edge features — which carry the
protocol one-hot in slots 7..15 (the reference's per-protocol handler
dispatch, SURVEY §2.3 P5, re-expressed as typed attention; the one-hot
is folded into edge_feats at build time so no per-edge embedding gather
runs on device). Per-destination normalization is a fused
softmax-aggregate: exp-weighted messages and the exp column share one
segment sum, normalized per node (see layer_fn) — two row-op passes per
layer instead of six.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from alaz_tpu.config import ModelConfig
from alaz_tpu.models.common import (
    compute_dtype,
    dense,
    dense_init,
    edge_head,
    edge_head_init,
    graph_block_starts,
    layernorm,
    layernorm_init,
    maybe_znorm_graph,
    mlp,
    mlp_init,
)
from alaz_tpu.ops.segment import (
    ATTENTION_LOGIT_CLAMP,
    expand_dst,
    gather_src,
    segment_sum_accurate,
)

Params = Dict[str, Any]


def init(key: jax.Array, cfg: ModelConfig) -> Params:
    h = cfg.hidden_dim
    nh = cfg.num_heads
    assert h % nh == 0, "num_heads must divide hidden_dim"
    keys = jax.random.split(key, 4 + 6 * cfg.num_layers)
    params: Params = {
        "embed": dense_init(keys[0], cfg.node_feature_dim, h),
        "edge_head": edge_head_init(keys[2], h, cfg.edge_feat_dim_in),
        "node_head": mlp_init(keys[3], [h, h, 1]),
        "layers": [],
    }
    for l in range(cfg.num_layers):
        k = keys[4 + 6 * l : 10 + 6 * l]
        params["layers"].append(
            {
                "q": dense_init(k[0], h, h),
                "kv": dense_init(k[1], h, h),
                "edge_proj": dense_init(k[2], cfg.edge_feat_dim_in, h),
                "attn": jax.random.normal(k[3], (nh, 3 * (h // nh)), jnp.float32) * 0.05,
                "out": dense_init(k[4], h, h),
                "ln": layernorm_init(h),
            }
        )
    return params


def apply(params: Params, graph: dict, cfg: ModelConfig) -> dict:
    dtype = compute_dtype(cfg)
    graph = maybe_znorm_graph(graph, cfg)
    n = graph["node_feats"].shape[0]
    nh = cfg.num_heads
    hd = cfg.hidden_dim // nh
    node_mask = graph["node_mask"].astype(jnp.float32)
    edge_mask = graph["edge_mask"]
    src, dst = graph["edge_src"], graph["edge_dst"]

    # f32 residual stream (matmuls stay in the compute dtype): a bf16
    # carry makes the remat'd backward recompute round differently from
    # the saved activations; see models/graphsage.py for the full note
    h = dense(params["embed"], graph["node_feats"].astype(dtype)).astype(
        jnp.float32
    ) * node_mask[:, None]
    # edge-type conditioning rides the protocol one-hot in edge_feats
    # slots 7..15 (builder.py), learned through edge_proj — no per-edge
    # embedding gather (row-op bound on TPU)
    ef = graph["edge_feats"].astype(dtype)
    # blocked layout: the host-shipped dst-block extents (None under COO)
    block_starts = graph_block_starts(graph, cfg)

    def layer_fn(layer, h32):
        h = h32.astype(dtype)
        # attention logit = a·[q_dst, kv_src, e_feat] re-associated into
        # per-node/per-edge partial dot products: the dst-side partial
        # rides the sorted expand, only the src side stays a row gather
        attn = layer["attn"].astype(dtype)  # [nh, 3hd]
        a_q, a_k, a_e = attn[:, :hd], attn[:, hd : 2 * hd], attn[:, 2 * hd :]
        q = dense(layer["q"], h).reshape(n, nh, hd)
        kv = dense(layer["kv"], h).reshape(n, nh, hd)
        e_feat = dense(layer["edge_proj"], ef).reshape(-1, nh, hd)

        q_part = jnp.einsum("nhd,hd->nh", q, a_q)  # [N, nh]
        e_part = jnp.einsum("ehd,hd->eh", e_feat, a_e)  # [E, nh]
        # the one irreducible src gather per layer (flattened to 2D so
        # the banded kernel applies after a clustered layout)
        kv_src = gather_src(
            kv.reshape(n, nh * hd), src, n, cfg.src_gather
        ).reshape(-1, nh, hd)
        k_src = jnp.einsum("ehd,hd->eh", kv_src, a_k)
        logits = (
            expand_dst(q_part, dst, n, cfg.use_pallas) + k_src + e_part
        ).astype(jnp.float32)
        logits = jax.nn.leaky_relu(logits, 0.2)

        # fused softmax-aggregate: scatter exp-weighted messages and the
        # exp column in ONE segment sum, normalize per NODE —
        # Σe^x·m/Σe^x ≡ Σsoftmax(x)·m, but the explicit-alpha form costs
        # a denominator scatter plus an [E]-row denominator broadcast
        # that the fusion deletes. The usual per-segment max subtraction
        # (another [E] scatter-max + [E] broadcast) is replaced by a
        # fixed ±30 clamp: exp(±30) is exact and overflow-free in the
        # f32 accumulators, and attention logits past ±30 only saturate
        # (post-leaky-relu magnitudes are O(1-10) in practice). Net: 6
        # row-op passes per layer → 2 (the src gather + this scatter).
        # saturation gauge: fraction of live logits at/past the clamp.
        # The O(1-10) magnitude assumption above is otherwise unchecked —
        # if training drifts logits past ±30 the softmax silently
        # flattens; this scalar makes that drift observable
        # (runtime/metrics.py model.attn_clamp_saturation).
        hit = (jnp.abs(logits) >= ATTENTION_LOGIT_CLAMP) & edge_mask[:, None]
        sat = jnp.sum(hit.astype(jnp.float32)) / jnp.maximum(
            jnp.sum(edge_mask.astype(jnp.float32)) * nh, 1.0
        )
        logits = jnp.clip(logits, -ATTENTION_LOGIT_CLAMP, ATTENTION_LOGIT_CLAMP)
        w = jnp.where(edge_mask[:, None], jnp.exp(logits), 0.0)  # [E, nh]
        msgs = ((kv_src + e_feat) * w[:, :, None].astype(dtype)).reshape(
            -1, nh * hd
        )
        # segment_sum_accurate: the denominator column must accumulate
        # in f32 (a bf16 running sum stagnates at hub fan-in ~256); the
        # kernel path still DMAs bf16 and accumulates f32 on the MXU
        fused = jnp.concatenate([msgs, w.astype(msgs.dtype)], axis=1)
        agg_all = segment_sum_accurate(
            fused, dst, n, cfg.use_pallas, block_starts=block_starts
        )
        num = agg_all[:, : nh * hd].reshape(n, nh, hd)
        denom = agg_all[:, nh * hd :]  # [N, nh]
        # double-where: nodes with no unmasked in-edges (pad slot, loners)
        # have denom 0 — guard the division so its backward cannot NaN
        # (ops/segment.py segment_softmax has the full story)
        nonempty = denom > 0.0
        agg = jnp.where(
            nonempty[:, :, None],
            num / jnp.where(nonempty, denom, 1.0)[:, :, None],
            0.0,
        ).reshape(n, nh * hd)
        h_new = dense(layer["out"], agg.astype(dtype))
        h_out = (
            h32 + jax.nn.gelu(layernorm(layer["ln"], h_new.astype(jnp.float32)))
        ) * node_mask[:, None]
        return h_out, sat

    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)
    sats = []
    for layer in params["layers"]:
        h, sat = layer_fn(layer, h)
        sats.append(sat)
    h = h.astype(dtype)

    edge_logits = edge_head(params["edge_head"], h, graph, dtype, cfg.use_pallas, cfg.src_gather)
    node_logits = mlp(params["node_head"], h)[:, 0]
    return {
        "node_h": h,
        "edge_logits": edge_logits.astype(jnp.float32),
        "node_logits": node_logits.astype(jnp.float32),
        "attn_clamp_saturation": jnp.stack(sats).max(),
    }
