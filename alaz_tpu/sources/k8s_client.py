"""Minimal from-scratch Kubernetes REST client: LIST + WATCH.

The reference vendors client-go for its informers (k8s/informer.go:67-157);
this repo's pattern is from-scratch protocol clients over the stdlib
(cf. sources/cri.py's gRPC/HTTP-2 stack). The surface here is exactly
what the informer loop needs and nothing more:

- ``KindEndpoint(client, path)`` — a lister: ``endpoint(timeout_seconds=30)``
  issues an all-namespaces LIST and returns the decoded object list.
- ``BuiltinWatch().stream(lister, resource_version=, timeout_seconds=)`` —
  a WATCH stream from a resourceVersion, yielding ``{"type", "object"}``
  events, the same shape kubernetes.watch.Watch yields.

Decoded JSON is wrapped in :class:`JsonObj`, an attribute shim that maps
the kubernetes-client snake_case attribute convention onto raw camelCase
API keys (``pod.status.pod_ip`` → ``status.podIP``) so the pure
translation layer in ``k8s_watch`` is client-agnostic.

In-cluster discovery follows the serviceaccount convention the reference
relies on via client-go's rest.InClusterConfig: KUBERNETES_SERVICE_HOST /
_PORT plus the mounted token and CA under
/var/run/secrets/kubernetes.io/serviceaccount.
"""

from __future__ import annotations

import json
import os
import socket as socket_module
import ssl
import threading
from dataclasses import dataclass
from http.client import HTTPConnection, HTTPSConnection
from pathlib import Path
from typing import Iterator, Optional
from urllib.parse import urlencode, urlsplit

from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.k8s_client")

SERVICEACCOUNT_ROOT = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiException(Exception):
    """HTTP or in-stream API error; ``status`` carries the code the
    informer loop dispatches on (410 Gone → immediate re-LIST)."""

    def __init__(self, status: int, reason: str = ""):
        super().__init__(f"k8s api error {status}: {reason}")
        self.status = status
        self.reason = reason


def _normalize(name: str) -> str:
    return name.replace("_", "").lower()


class JsonObj:
    """Attribute access over a decoded JSON dict, matching keys by
    case/underscore-insensitive name so both the kubernetes client's
    snake_case (``resource_version``, ``cluster_i_ps``) and the wire's
    camelCase (``resourceVersion``, ``clusterIPs``) resolve. Missing
    attributes are None — the translators treat absent fields as empty.
    """

    __slots__ = ("_data", "_keys")

    def __init__(self, data: dict):
        self._data = data
        self._keys = {_normalize(k): k for k in data}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        key = self._keys.get(_normalize(name))
        if key is None:
            return None
        return _wrap(self._data[key])

    def __repr__(self) -> str:  # debug aid only
        return f"JsonObj({self._data!r})"


def _wrap(value):
    if isinstance(value, dict):
        return JsonObj(value)
    if isinstance(value, list):
        return [_wrap(v) for v in value]
    return value


@dataclass
class ClusterConfig:
    base_url: str
    token: Optional[str] = None
    # read per-request, not once: bound serviceaccount tokens expire and
    # the kubelet rotates the file in place (client-go re-reads it too)
    token_file: Optional[str] = None
    ca_file: Optional[str] = None

    def bearer_token(self) -> Optional[str]:
        if self.token_file:
            try:
                return Path(self.token_file).read_text().strip()
            except OSError:
                return self.token
        return self.token

    @staticmethod
    def in_cluster(sa_root: str = SERVICEACCOUNT_ROOT) -> Optional["ClusterConfig"]:
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            return None
        token_path = Path(sa_root) / "token"
        ca_path = Path(sa_root) / "ca.crt"
        if ":" in host:  # IPv6 service host needs brackets in a URL
            host = f"[{host}]"
        return ClusterConfig(
            base_url=f"https://{host}:{port}",
            token_file=str(token_path) if token_path.exists() else None,
            ca_file=str(ca_path) if ca_path.exists() else None,
        )


class K8sRestClient:
    """One client per source; a fresh connection per request so the seven
    kind loops can share it across threads (http.client connections are
    not thread-safe, and at one LIST/WATCH per 30s per kind, connection
    reuse buys nothing)."""

    def __init__(self, config: ClusterConfig):
        self.config = config
        parts = urlsplit(config.base_url)
        self._https = parts.scheme == "https"
        self._host = parts.hostname or "localhost"
        self._port = parts.port or (443 if self._https else 80)
        # live LIST connections, so close_all() can interrupt a thread
        # parked in a blocking read at informer teardown
        self._live: set = set()
        self._live_lock = threading.Lock()
        self._closed = False

    def _track(self, conn) -> None:
        with self._live_lock:
            if self._closed:
                conn.close()
                raise ApiException(499, "client closed")
            self._live.add(conn)

    def _untrack(self, conn) -> None:
        with self._live_lock:
            self._live.discard(conn)

    def close_all(self) -> None:
        """Shut down every in-flight LIST so blocked reads unblock now;
        subsequent requests fail fast with status 499."""
        with self._live_lock:
            self._closed = True
            conns = list(self._live)
            self._live.clear()
        for conn in conns:
            sock = getattr(conn, "sock", None)
            if sock is not None:
                try:
                    sock.shutdown(socket_module.SHUT_RDWR)
                except OSError:
                    pass
            try:
                conn.close()
            except OSError:  # pragma: no cover - already dead
                pass

    def _connect(self, timeout_s: float):
        if self._https:
            # no ca_file → system trust store; never downgrade to
            # CERT_NONE — the bearer token rides these connections
            ctx = ssl.create_default_context(cafile=self.config.ca_file)
            return HTTPSConnection(self._host, self._port, timeout=timeout_s, context=ctx)
        return HTTPConnection(self._host, self._port, timeout=timeout_s)

    def _headers(self) -> dict:
        headers = {"Accept": "application/json"}
        token = self.config.bearer_token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers

    def list(self, path: str, timeout_seconds: int = 30) -> JsonObj:
        with self._live_lock:
            if self._closed:  # fail fast BEFORE dialing — close_all()
                raise ApiException(499, "client closed")  # can't interrupt a dial
        query = urlencode({"timeoutSeconds": timeout_seconds})
        conn = self._connect(timeout_seconds + 5)
        conn.auto_open = 0
        try:
            conn.connect()
            self._track(conn)
            try:
                conn.request("GET", f"{path}?{query}", headers=self._headers())
                resp = conn.getresponse()
                body = resp.read()
            finally:
                self._untrack(conn)
            if resp.status != 200:
                raise ApiException(resp.status, body[:200].decode("utf-8", "replace"))
            return JsonObj(json.loads(body))
        finally:
            conn.close()


class KindEndpoint:
    """Lister for one resource kind across all namespaces. Carries the
    path + client so BuiltinWatch can open the matching watch stream from
    the lister alone — the same introspection trick kubernetes.watch
    plays on its bound API methods."""

    def __init__(self, client: K8sRestClient, path: str):
        self.client = client
        self.path = path

    def __call__(self, timeout_seconds: int = 30) -> JsonObj:
        return self.client.list(self.path, timeout_seconds=timeout_seconds)


class BuiltinWatch:
    """One WATCH stream: chunked GET with ?watch=1, newline-delimited
    JSON events. ``stop()`` closes the socket from another thread, which
    unblocks a reader waiting on a quiet stream (informer teardown)."""

    def __init__(self):
        self._conn = None
        self._sock = None
        self._lock = threading.Lock()
        self._stopped = False

    def stream(
        self, lister: KindEndpoint, resource_version: str, timeout_seconds: int = 30
    ) -> Iterator[dict]:
        client = lister.client
        query = urlencode(
            {
                "watch": "1",
                "resourceVersion": resource_version or "",
                "timeoutSeconds": timeout_seconds,
                "allowWatchBookmarks": "false",
            }
        )
        with self._lock:
            if self._stopped:
                return
        conn = client._connect(timeout_seconds + 5)
        # without this, a stop() racing the dial is defeated by
        # http.client's auto_open: request() on the closed conn silently
        # re-dials and streams anyway
        conn.auto_open = 0
        try:
            conn.connect()  # outside the lock: a slow dial must not block stop()
        except Exception:
            if self._stopped:
                return
            raise
        with self._lock:
            if self._stopped:  # stop() ran while we were dialing
                conn.close()
                return
            self._conn = conn
            self._sock = conn.sock
        try:
            try:
                conn.request("GET", f"{lister.path}?{query}", headers=client._headers())
                resp = conn.getresponse()
            except Exception:
                if self._stopped:
                    return  # stop() raced the dial — orderly teardown
                raise
            if resp.status != 200:
                raise ApiException(
                    resp.status, resp.read()[:200].decode("utf-8", "replace")
                )
            while True:
                try:
                    line = resp.readline()
                except TimeoutError:
                    # quiet stream past the socket deadline: the server
                    # missed its own timeoutSeconds close. Treat as a
                    # stream end — the informer re-watches from the last
                    # rv instead of backing off.
                    return
                except Exception:
                    # stop() shut the socket down under us — orderly
                    # teardown, not a stream error
                    if self._stopped:
                        return
                    raise
                if not line:
                    return  # server closed the stream (watch timeout)
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                if event.get("type") == "ERROR":
                    status = (event.get("object") or {}).get("code", 0)
                    raise ApiException(
                        int(status or 500),
                        (event.get("object") or {}).get("message", "watch error"),
                    )
                obj = event.get("object")
                yield {
                    "type": event.get("type", ""),
                    "object": JsonObj(obj) if isinstance(obj, dict) else obj,
                }
        finally:
            self.stop()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._sock is not None:
                # close() alone does not unblock a recv() parked in
                # another thread; shutdown() does
                try:
                    self._sock.shutdown(socket_module.SHUT_RDWR)
                except OSError:
                    pass
                self._sock = None
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:  # pragma: no cover - already dead
                    pass
                self._conn = None
