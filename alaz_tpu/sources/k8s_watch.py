"""Kubernetes metadata source — the k8s/informer.go analog (G19).

Live mode uses the ``kubernetes`` client's list+watch per resource kind
with periodic full resync (informer.go:47: resync 120s), translating
watch events into :class:`K8sResourceMessage`. Without a cluster (or the
client library), the source runs in injected mode: tests and replay push
messages through ``inject``. Pods additionally fan out one CONTAINER
message per container (pod.go:48-87).
"""

from __future__ import annotations

import threading
from typing import Iterable, List, Optional

from alaz_tpu.events.k8s import (
    Container,
    EventType,
    K8sResourceMessage,
    Pod,
    ResourceType,
)
from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.k8s")

_WATCH_KINDS = (
    ResourceType.POD,
    ResourceType.SERVICE,
    ResourceType.REPLICASET,
    ResourceType.DEPLOYMENT,
    ResourceType.ENDPOINTS,
    ResourceType.DAEMONSET,
    ResourceType.STATEFULSET,
)


def fan_out_containers(msg: K8sResourceMessage) -> List[K8sResourceMessage]:
    """Pod message → [pod message, CONTAINER message per container]."""
    out = [msg]
    pod = msg.object
    if msg.resource_type == ResourceType.POD and isinstance(pod, Pod) and pod.image:
        out.append(
            K8sResourceMessage(
                ResourceType.CONTAINER,
                msg.event_type,
                Container(
                    name=pod.name, namespace=pod.namespace, pod_uid=pod.uid, image=pod.image
                ),
            )
        )
    return out


class K8sWatchSource:
    def __init__(
        self,
        exclude_namespaces: Iterable[str] = (),
        resync_interval_s: float = 120.0,
        in_cluster: bool = True,
    ):
        self.exclude = set(exclude_namespaces)
        self.resync_interval_s = resync_interval_s
        self.in_cluster = in_cluster
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._service = None
        self.live = False

    # -- injected mode (tests / replay) ------------------------------------

    def inject(self, msg: K8sResourceMessage) -> None:
        if self._service is None:
            return
        obj = msg.object
        ns = getattr(obj, "namespace", "")
        if ns and ns in self.exclude:
            return
        for m in fan_out_containers(msg):
            self._service.submit_k8s(m)

    # -- live mode ----------------------------------------------------------

    def start(self, service) -> None:
        self._service = service
        self._stop.clear()
        try:
            import kubernetes  # type: ignore # noqa: F401

            self.live = True
        except ImportError:
            log.info("kubernetes client unavailable; k8s source in injected mode")
            return
        self._thread = threading.Thread(target=self._watch_loop, name="alaz-k8s", daemon=True)
        self._thread.start()

    def _watch_loop(self) -> None:  # pragma: no cover - needs a cluster
        import kubernetes as k8s  # type: ignore

        if self.in_cluster:
            k8s.config.load_incluster_config()
        else:
            k8s.config.load_kube_config()
        v1 = k8s.client.CoreV1Api()
        apps = k8s.client.AppsV1Api()
        while not self._stop.is_set():
            try:
                self._resync_core(v1)
                self._resync_apps(apps)
            except Exception as exc:
                log.warning(f"k8s resync failed: {exc}")
            self._stop.wait(self.resync_interval_s)

    def _resync_core(self, v1) -> None:  # pragma: no cover - needs a cluster
        from alaz_tpu.events.k8s import Address, AddressIP, Endpoints, Service

        for pod in v1.list_pod_for_all_namespaces(timeout_seconds=30).items:
            self.inject(
                K8sResourceMessage(
                    ResourceType.POD,
                    EventType.UPDATE,
                    Pod(
                        uid=pod.metadata.uid,
                        name=pod.metadata.name,
                        namespace=pod.metadata.namespace,
                        ip=pod.status.pod_ip or "",
                        image=(pod.spec.containers[0].image if pod.spec.containers else ""),
                    ),
                )
            )
        for svc in v1.list_service_for_all_namespaces(timeout_seconds=30).items:
            self.inject(
                K8sResourceMessage(
                    ResourceType.SERVICE,
                    EventType.UPDATE,
                    Service(
                        uid=svc.metadata.uid,
                        name=svc.metadata.name,
                        namespace=svc.metadata.namespace,
                        type=svc.spec.type or "",
                        cluster_ip=svc.spec.cluster_ip or "",
                        cluster_ips=list(svc.spec.cluster_i_ps or []),
                        ports=[
                            (p.name or "", int(p.port), int(p.target_port or 0) if str(p.target_port or "").isdigit() else 0, p.protocol or "TCP")
                            for p in (svc.spec.ports or [])
                        ],
                    ),
                )
            )
        for ep in v1.list_endpoints_for_all_namespaces(timeout_seconds=30).items:
            addresses = []
            for subset in ep.subsets or []:
                ips = [
                    AddressIP(
                        type="pod" if a.target_ref and a.target_ref.kind == "Pod" else "external",
                        id=(a.target_ref.uid if a.target_ref else ""),
                        name=(a.target_ref.name if a.target_ref else ""),
                        namespace=ep.metadata.namespace,
                        ip=a.ip,
                    )
                    for a in (subset.addresses or [])
                ]
                addresses.append(Address(ips=ips))
            self.inject(
                K8sResourceMessage(
                    ResourceType.ENDPOINTS,
                    EventType.UPDATE,
                    Endpoints(
                        uid=ep.metadata.uid,
                        name=ep.metadata.name,
                        namespace=ep.metadata.namespace,
                        addresses=addresses,
                    ),
                )
            )

    def _resync_apps(self, apps) -> None:  # pragma: no cover - needs a cluster
        from alaz_tpu.events.k8s import DaemonSet, Deployment, ReplicaSet, StatefulSet

        kinds = [
            (apps.list_replica_set_for_all_namespaces, ResourceType.REPLICASET, ReplicaSet),
            (apps.list_deployment_for_all_namespaces, ResourceType.DEPLOYMENT, Deployment),
            (apps.list_daemon_set_for_all_namespaces, ResourceType.DAEMONSET, DaemonSet),
            (apps.list_stateful_set_for_all_namespaces, ResourceType.STATEFULSET, StatefulSet),
        ]
        for lister, rtype, cls in kinds:
            for obj in lister(timeout_seconds=30).items:
                kwargs = dict(
                    uid=obj.metadata.uid,
                    name=obj.metadata.name,
                    namespace=obj.metadata.namespace,
                )
                if cls in (ReplicaSet, Deployment) and getattr(obj.spec, "replicas", None) is not None:
                    kwargs["replicas"] = int(obj.spec.replicas)
                self.inject(K8sResourceMessage(rtype, EventType.UPDATE, cls(**kwargs)))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
