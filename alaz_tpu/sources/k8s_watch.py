"""Kubernetes metadata source — the k8s/informer.go analog (G19).

Live mode mirrors the reference's 7 SharedInformers (informer.go:67-157):
per kind, a LIST seeds the state (emitted as UPDATEs), then a WATCH
stream translates ADDED/MODIFIED/DELETED into EventType.ADD/UPDATE/DELETE
— so deletions reach the cluster IP maps immediately instead of going
stale forever, and adds are not up to 2 minutes late. A full re-LIST
every ``resync_interval_s`` (informer.go:47: 120s) remains the fallback
for missed watch events. The transport is the repo's own minimal REST
client (``k8s_client``: LIST + chunked WATCH over the stdlib — the
client-go analog), discovered in-cluster via the serviceaccount
convention or pointed at any apiserver with ``api_server=``; the whole
loop (seed, rv tracking, 410 resume, error backoff, reconcile-deletes)
is exercised against a local fake apiserver in
tests/test_k8s_apiserver.py. The object→DTO translation layer is pure
functions over duck-typed objects, unit-tested with stubs
(tests/test_sources.py). Without an apiserver the source runs in
injected mode: tests and replay push messages through ``inject``. Pods
additionally fan out one CONTAINER message per container (pod.go:48-87).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, List, Optional

from alaz_tpu.events.k8s import (
    Address,
    AddressIP,
    Container,
    DaemonSet,
    Deployment,
    Endpoints,
    EventType,
    K8sResourceMessage,
    Pod,
    ReplicaSet,
    ResourceType,
    Service,
    StatefulSet,
)
from alaz_tpu.logging import get_logger
from alaz_tpu.sources.k8s_client import (
    BuiltinWatch,
    ClusterConfig,
    K8sRestClient,
    KindEndpoint,
)

log = get_logger("alaz_tpu.k8s")

# all-namespaces collection paths, one per informer kind
KIND_PATHS = {
    ResourceType.POD: "/api/v1/pods",
    ResourceType.SERVICE: "/api/v1/services",
    ResourceType.ENDPOINTS: "/api/v1/endpoints",
    ResourceType.REPLICASET: "/apis/apps/v1/replicasets",
    ResourceType.DEPLOYMENT: "/apis/apps/v1/deployments",
    ResourceType.DAEMONSET: "/apis/apps/v1/daemonsets",
    ResourceType.STATEFULSET: "/apis/apps/v1/statefulsets",
}

_WATCH_KINDS = (
    ResourceType.POD,
    ResourceType.SERVICE,
    ResourceType.REPLICASET,
    ResourceType.DEPLOYMENT,
    ResourceType.ENDPOINTS,
    ResourceType.DAEMONSET,
    ResourceType.STATEFULSET,
)

# watch event type → EventType (informer Add/Update/Delete handlers)
WATCH_EVENT_MAP = {
    "ADDED": EventType.ADD,
    "MODIFIED": EventType.UPDATE,
    "DELETED": EventType.DELETE,
}


def fan_out_containers(msg: K8sResourceMessage) -> List[K8sResourceMessage]:
    """Pod message → [pod message, CONTAINER message per container]."""
    out = [msg]
    pod = msg.object
    if msg.resource_type == ResourceType.POD and isinstance(pod, Pod) and pod.image:
        out.append(
            K8sResourceMessage(
                ResourceType.CONTAINER,
                msg.event_type,
                Container(
                    name=pod.name, namespace=pod.namespace, pod_uid=pod.uid, image=pod.image
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# Pure translation layer (client object → DTO) — stub-testable
# ---------------------------------------------------------------------------


def pod_from_obj(pod) -> Pod:
    return Pod(
        uid=pod.metadata.uid,
        name=pod.metadata.name,
        namespace=pod.metadata.namespace,
        ip=(pod.status.pod_ip or "") if pod.status else "",
        image=(
            pod.spec.containers[0].image
            if pod.spec and pod.spec.containers
            else ""
        ),
    )


def service_from_obj(svc) -> Service:
    spec = svc.spec
    return Service(
        uid=svc.metadata.uid,
        name=svc.metadata.name,
        namespace=svc.metadata.namespace,
        type=(spec.type or "") if spec else "",
        cluster_ip=(spec.cluster_ip or "") if spec else "",
        cluster_ips=list(getattr(spec, "cluster_i_ps", None) or []) if spec else [],
        ports=[
            (
                p.name or "",
                int(p.port),
                int(p.target_port or 0) if str(p.target_port or "").isdigit() else 0,
                p.protocol or "TCP",
            )
            for p in ((spec.ports if spec else None) or [])
        ],
    )


def endpoints_from_obj(ep) -> Endpoints:
    addresses = []
    for subset in ep.subsets or []:
        ips = [
            AddressIP(
                type="pod" if a.target_ref and a.target_ref.kind == "Pod" else "external",
                id=(a.target_ref.uid if a.target_ref else ""),
                name=(a.target_ref.name if a.target_ref else ""),
                namespace=ep.metadata.namespace,
                ip=a.ip,
            )
            for a in (subset.addresses or [])
        ]
        addresses.append(Address(ips=ips))
    return Endpoints(
        uid=ep.metadata.uid,
        name=ep.metadata.name,
        namespace=ep.metadata.namespace,
        addresses=addresses,
    )


def _workload_from_obj(obj, cls):
    kwargs = dict(
        uid=obj.metadata.uid, name=obj.metadata.name, namespace=obj.metadata.namespace
    )
    if cls in (ReplicaSet, Deployment) and getattr(obj.spec, "replicas", None) is not None:
        kwargs["replicas"] = int(obj.spec.replicas)
    return cls(**kwargs)


TRANSLATORS: dict[ResourceType, Callable] = {
    ResourceType.POD: pod_from_obj,
    ResourceType.SERVICE: service_from_obj,
    ResourceType.ENDPOINTS: endpoints_from_obj,
    ResourceType.REPLICASET: lambda o: _workload_from_obj(o, ReplicaSet),
    ResourceType.DEPLOYMENT: lambda o: _workload_from_obj(o, Deployment),
    ResourceType.DAEMONSET: lambda o: _workload_from_obj(o, DaemonSet),
    ResourceType.STATEFULSET: lambda o: _workload_from_obj(o, StatefulSet),
}


def translate_watch_event(kind: ResourceType, raw_event: dict) -> K8sResourceMessage | None:
    """One watch-stream event → K8sResourceMessage (the informer
    Add/Update/Delete handler body). Unknown event types (BOOKMARK, ERROR)
    return None."""
    etype = WATCH_EVENT_MAP.get(raw_event.get("type", ""))
    if etype is None:
        return None
    obj = raw_event.get("object")
    if obj is None or getattr(obj, "metadata", None) is None:
        return None
    try:
        dto = TRANSLATORS[kind](obj)
    except (AttributeError, TypeError, ValueError) as exc:
        log.warning(f"k8s translate failed for {kind}: {exc}")
        return None
    return K8sResourceMessage(kind, etype, dto)


def translate_list(kind: ResourceType, items) -> List[K8sResourceMessage]:
    """A LIST response's items → UPDATE messages (resync semantics)."""
    out = []
    for obj in items:
        msg = translate_watch_event(kind, {"type": "MODIFIED", "object": obj})
        if msg is not None:
            out.append(msg)
    return out


def reconcile_list(
    kind: ResourceType,
    msgs: List[K8sResourceMessage],
    known: dict[str, object],
) -> tuple[List[K8sResourceMessage], dict[str, object]]:
    """Diff a re-LIST against the previously-known objects and synthesize
    DELETEs for objects that vanished while the watch was down — the
    DeltaFIFO Replace semantics of a real informer. Without this, a pod
    deleted during a watch outage keeps its IP in the cluster maps
    forever. Returns (delete messages, new known map)."""
    new_known = {m.object.uid: m.object for m in msgs if getattr(m.object, "uid", "")}
    deletes = [
        K8sResourceMessage(kind, EventType.DELETE, dto)
        for uid, dto in known.items()
        if uid not in new_known
    ]
    return deletes, new_known


class K8sWatchSource:
    def __init__(
        self,
        exclude_namespaces: Iterable[str] = (),
        resync_interval_s: float = 120.0,
        in_cluster: bool = True,
        error_backoff_s: float = 5.0,
        api_server: Optional[str] = None,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
    ):
        self.exclude = set(exclude_namespaces)
        self.resync_interval_s = resync_interval_s
        self.in_cluster = in_cluster
        self.error_backoff_s = error_backoff_s
        self.api_server = api_server
        self.token = token
        self.token_file = token_file
        self.ca_file = ca_file
        self._stop = threading.Event()
        # control-plane lifecycle only: appended in start(), joined in
        # stop(), both on the owner's thread — unlike ingest_server's
        # accept-loop-rebound list this is never touched by the workers
        self._threads: List[threading.Thread] = []
        # live watch streams: kind loops add/discard them concurrently
        # with stop()'s close sweep
        self._watches: set = set()  # guarded-by: self._watch_lock
        self._watch_lock = threading.Lock()
        self._client: Optional[K8sRestClient] = None
        self._service = None  # lockless-ok: attach-once publication in start() before the watch threads exist; readers null-check an atomic reference swap
        self.live = False

    # -- injected mode (tests / replay) ------------------------------------

    def inject(self, msg: K8sResourceMessage) -> None:
        if self._service is None or self._stop.is_set():
            return
        obj = msg.object
        ns = getattr(obj, "namespace", "")
        if ns and ns in self.exclude:
            return
        for m in fan_out_containers(msg):
            self._service.submit_k8s(m)

    # -- live mode ----------------------------------------------------------

    def start(self, service) -> None:
        self._service = service
        self._stop.clear()
        config = self._resolve_config()
        if config is None:
            log.info("no apiserver configured or discovered; k8s source in injected mode")
            return
        self.live = True
        listers = self._make_listers(config)
        for kind in _WATCH_KINDS:
            t = threading.Thread(
                target=self._kind_loop,
                args=(kind, listers[kind], self._watch_factory),
                name=f"alaz-k8s-{kind.value}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _resolve_config(self) -> Optional[ClusterConfig]:
        """Explicit ``api_server`` beats in-cluster serviceaccount
        discovery (client-go rest.InClusterConfig order)."""
        if self.api_server is not None:
            return ClusterConfig(
                base_url=self.api_server,
                token=self.token,
                token_file=self.token_file,
                ca_file=self.ca_file,
            )
        if self.in_cluster:
            return ClusterConfig.in_cluster()
        return None

    def _make_listers(self, config: ClusterConfig) -> dict:
        self._client = K8sRestClient(config)
        return {
            kind: KindEndpoint(self._client, path) for kind, path in KIND_PATHS.items()
        }

    def _watch_factory(self) -> BuiltinWatch:
        """BuiltinWatch with source-level registration so stop() can close
        a stream blocked mid-read from another thread."""
        w = BuiltinWatch()
        with self._watch_lock:
            self._watches.add(w)
        # a kind loop past stop()'s registry drain would otherwise dial an
        # unstoppable stream; marking it stopped makes stream() a no-op
        if self._stop.is_set():
            w.stop()
        return w

    def _kind_loop(self, kind: ResourceType, lister, watch_factory=None) -> None:
        """One informer: LIST (seed + resync, with vanished-object DELETE
        reconciliation), then WATCH re-established from the last-seen
        resourceVersion until the resync deadline; only then re-LIST
        (informer.go:67-157; a LIST is the expensive call, so the stream's
        30s server timeout must NOT trigger one). A 410 Gone from the
        watch means the resourceVersion expired server-side — that IS a
        re-LIST trigger, taken immediately without the error backoff.
        ``watch_factory`` is the client seam: registered BuiltinWatch
        instances in live mode, protocol-faithful fakes in tests."""
        if watch_factory is None:
            watch_factory = BuiltinWatch

        known: dict[str, object] = {}
        while not self._stop.is_set():
            try:
                resp = lister(timeout_seconds=30)
                msgs = translate_list(kind, resp.items)
                deletes, known = reconcile_list(kind, msgs, known)
                for msg in deletes:
                    self.inject(msg)
                for msg in msgs:
                    self.inject(msg)
                rv = resp.metadata.resource_version
                deadline = time.monotonic() + self.resync_interval_s
                expired = False
                while (
                    not expired
                    and not self._stop.is_set()
                    and time.monotonic() < deadline
                ):
                    w = watch_factory()
                    try:
                        for raw in w.stream(
                            lister, resource_version=rv, timeout_seconds=30
                        ):
                            obj = raw.get("object")
                            new_rv = getattr(
                                getattr(obj, "metadata", None), "resource_version", None
                            )
                            if new_rv:
                                rv = new_rv
                            msg = translate_watch_event(kind, raw)
                            if msg is not None:
                                uid = getattr(msg.object, "uid", "")
                                if msg.event_type == EventType.DELETE:
                                    known.pop(uid, None)
                                elif uid:
                                    known[uid] = msg.object
                                self.inject(msg)
                            if self._stop.is_set():
                                break
                    except Exception as exc:
                        if getattr(exc, "status", None) == 410:
                            # expired rv: the server forgot this history
                            # window; re-seed via LIST right away
                            log.info(f"k8s watch {kind.value}: 410 Gone, re-listing")
                            expired = True
                        else:
                            raise
                    finally:
                        w.stop()
                        with self._watch_lock:
                            self._watches.discard(w)
                    # stream timeout: loop re-watches from the last rv
            except Exception as exc:
                if self._stop.is_set():
                    break  # teardown interrupted the call — not an error
                log.warning(f"k8s watch {kind.value} failed: {exc}")
                self._stop.wait(self.error_backoff_s)

    def stop(self) -> None:
        self._stop.set()
        # close live streams and in-flight LISTs so a loop blocked on a
        # quiet watch or a slow LIST unblocks now, not at socket timeout
        with self._watch_lock:
            watches = list(self._watches)
            self._watches.clear()
        for w in watches:
            w.stop()
        if self._client is not None:
            self._client.close_all()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
