"""Go-TLS uprobe target discovery — the collector.go:319-516 analog (G4).

Go binaries terminate ``crypto/tls.(*Conn).Read`` via multiple RET sites
and crash under uretprobes, so the reference parses the ELF, checks the
Go build info (register ABI needs >= go1.17), locates the
``crypto/tls.(*Conn).Write``/``Read`` symbols, and disassembles the Read
body to attach an exit uprobe at every RET (ARCHITECTURE.md:93-97 of the
reference). This module reproduces that discovery pipeline: a pure-Python
ELF reader (symtab/dynsym + program headers for vaddr→file-offset), a
``.go.buildinfo`` version parser, and RET-offset extraction via objdump
(the binutils disassembler plays the golang.org/x/arch role). The output
is the attach plan an agent needs: enter offsets for Write/Read and one
exit offset per RET of Read.
"""

from __future__ import annotations

import re
import struct
import subprocess
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.gotls")

GO_WRITE_SYMBOL = "crypto/tls.(*Conn).Write"
GO_READ_SYMBOL = "crypto/tls.(*Conn).Read"
MAX_EXE_BYTES = 200 * 1024 * 1024  # collector.go guards >200MB executables

_BUILDINFO_MAGIC = b"\xff Go buildinf:"


@dataclass
class ElfSymbol:
    name: str
    vaddr: int
    size: int
    file_offset: int


@dataclass
class GoTlsPlan:
    """Everything an attach hook needs (collector.go:403-511)."""

    go_version: str
    write: ElfSymbol
    read: ElfSymbol
    read_ret_offsets: List[int] = field(default_factory=list)  # file offsets


class ElfError(Exception):
    pass


def _read_elf_symbols(data: bytes, wanted: set[str]) -> dict[str, ElfSymbol]:
    """Minimal ELF64 little-endian reader: section headers → symtab/dynsym
    entries whose names are in ``wanted``, with vaddr→file-offset resolved
    through PT_LOAD program headers."""
    if len(data) < 64 or data[:4] != b"\x7fELF":
        raise ElfError("not an ELF")
    if data[4] != 2 or data[5] != 1:
        raise ElfError("only ELF64 little-endian supported")
    (e_phoff, e_shoff) = struct.unpack_from("<QQ", data, 0x20)
    (e_phentsize, e_phnum, e_shentsize, e_shnum) = struct.unpack_from(
        "<HHHH", data, 0x36
    )

    # program headers: vaddr → file offset mapping via PT_LOAD
    loads: list[tuple[int, int, int]] = []  # (vaddr, filesz, offset)
    for i in range(e_phnum):
        off = e_phoff + i * e_phentsize
        (p_type,) = struct.unpack_from("<I", data, off)
        if p_type != 1:  # PT_LOAD
            continue
        p_offset, p_vaddr, _p_paddr, p_filesz = struct.unpack_from(
            "<QQQQ", data, off + 8
        )
        loads.append((p_vaddr, p_filesz, p_offset))

    def to_offset(vaddr: int) -> int:
        for p_vaddr, p_filesz, p_offset in loads:
            if p_vaddr <= vaddr < p_vaddr + p_filesz:
                return vaddr - p_vaddr + p_offset
        raise ElfError(f"vaddr {vaddr:#x} not in any PT_LOAD")

    # section headers: find symtab/dynsym + their string tables
    sections = []
    for i in range(e_shnum):
        off = e_shoff + i * e_shentsize
        sh_name, sh_type = struct.unpack_from("<II", data, off)
        sh_offset, sh_size, sh_link = struct.unpack_from("<QQI", data, off + 0x18)
        sh_entsize = struct.unpack_from("<Q", data, off + 0x38)[0]
        sections.append((sh_type, sh_offset, sh_size, sh_link, sh_entsize))

    out: dict[str, ElfSymbol] = {}
    for sh_type, sh_offset, sh_size, sh_link, sh_entsize in sections:
        if sh_type not in (2, 11):  # SHT_SYMTAB, SHT_DYNSYM
            continue
        if sh_entsize == 0 or sh_link >= len(sections):
            continue
        _, str_off, str_size, _, _ = sections[sh_link]
        strtab = data[str_off : str_off + str_size]
        for off in range(sh_offset, sh_offset + sh_size, sh_entsize):
            st_name, _info, _other, _shndx, st_value, st_size = struct.unpack_from(
                "<IBBHQQ", data, off
            )
            end = strtab.find(b"\x00", st_name)
            name = strtab[st_name:end].decode("utf-8", "replace")
            if name in wanted and name not in out and st_value:
                try:
                    out[name] = ElfSymbol(
                        name=name,
                        vaddr=st_value,
                        size=st_size,
                        file_offset=to_offset(st_value),
                    )
                except ElfError:
                    continue
    return out


def go_build_version(source: bytes | str | Path) -> Optional[str]:
    """Parse the Go buildinfo blob (the debug/buildinfo check,
    collector.go:362-401): scan for the magic, then read the version
    string — inline (flags bit 1, go >= 1.18) or via the pointer pair
    (older layouts return None here; the reference also only needs the
    'is this modern Go' answer). ``source`` may be pre-read bytes so the
    caller reads the (possibly 200MB) binary once."""
    data = source if isinstance(source, bytes) else Path(source).read_bytes()
    idx = data.find(_BUILDINFO_MAGIC)
    if idx < 0 or idx + 33 > len(data):
        return None
    flags = data[idx + 15]
    if flags & 0x2:  # inline varint-prefixed strings
        p = idx + 32
        n = 0
        shift = 0
        while p < len(data):
            b = data[p]
            p += 1
            n |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        if p + n <= len(data):
            return data[p : p + n].decode("utf-8", "replace")
    return None


def go_version_at_least(version: str, major: int, minor: int) -> bool:
    m = re.match(r"go(\d+)\.(\d+)", version or "")
    if not m:
        return False
    return (int(m.group(1)), int(m.group(2))) >= (major, minor)


# Match the disassembly line of any return instruction. The byte column
# may carry prefixes before the final c3 ("f3 c3  repz ret" from some
# toolchains/cgo objects, "f2 c3  bnd ret" with CET) and the mnemonic
# varies (ret/retq/repz ret); arm64 objdump prints one 8-hex word
# ("d65f03c0  ret"). Keying on the mnemonic containing a ret token —
# not on a lone "c3 ret" — keeps exit uprobes on every encoding.
_RET_LINE = re.compile(
    r"^\s*([0-9a-f]+):\s+(?:[0-9a-f]{2}\s+)*(?:[0-9a-f]{8}\s+)?"
    r"(?:(?:repz?|bnd)\s+)?retq?\b",
    re.IGNORECASE,
)


def find_ret_offsets(
    path: str | Path, sym: ElfSymbol, objdump: str = "objdump"
) -> List[int]:
    """Disassemble ``sym``'s body and return the FILE offset of every RET
    (collector.go:457-511 attaches an exit uprobe at each; uretprobes
    crash Go because they rewrite the stack the goroutine scheduler
    walks). binutils objdump is the disassembler; a plain 0xC3 byte scan
    would false-positive inside immediates/displacements."""
    if sym.size <= 0:
        return []
    try:
        proc = subprocess.run(
            [
                objdump,
                "-d",
                "--start-address", hex(sym.vaddr),
                "--stop-address", hex(sym.vaddr + sym.size),
                str(path),
            ],
            capture_output=True,
            text=True,
            timeout=60,
        )
    except (OSError, subprocess.TimeoutExpired) as exc:
        log.warning(f"objdump failed for {path}: {exc}")
        return []
    if proc.returncode != 0:
        return []
    out: List[int] = []
    delta = sym.file_offset - sym.vaddr  # vaddr → file offset shift
    for line in proc.stdout.splitlines():
        m = _RET_LINE.match(line)
        if m:
            out.append(int(m.group(1), 16) + delta)
    return out


def discover_go_tls(exe_path: str | Path) -> Optional[GoTlsPlan]:
    """Full discovery pipeline for one executable: modern-Go check, both
    symbols resolved, Read's RET sites disassembled. None when the binary
    is not an eligible Go TLS user."""
    path = Path(exe_path)
    try:
        if path.stat().st_size > MAX_EXE_BYTES:
            return None
        data = path.read_bytes()  # one read shared by both parsers
        version = go_build_version(data)
        if version is None or not go_version_at_least(version, 1, 17):
            return None
        syms = _read_elf_symbols(data, {GO_WRITE_SYMBOL, GO_READ_SYMBOL})
    except (OSError, ElfError):
        return None
    write = syms.get(GO_WRITE_SYMBOL)
    read = syms.get(GO_READ_SYMBOL)
    if write is None or read is None:
        return None
    return GoTlsPlan(
        go_version=version,
        write=write,
        read=read,
        read_ret_offsets=find_ret_offsets(path, read),
    )
