"""Container index — the cri/CRITool analog (G20) + the container-pid sync
loop (G3, collector.go:127-209).

The reference asks the CRI for running containers, resolves each to its
pid set via cgroup walks (cri.go:160-233), filters namespaces (kube-system
excluded by default, cri.go:75-98), and every 30s diffs old/new pid sets,
pushing updates into the kernel ``container_pids`` map and synthesizing
exec/exit proc events. Here the index keeps the same contract against a
pluggable lister: live mode reads /proc + cgroup files when running on a
node; tests register containers programmatically. The diff loop emits the
same synthetic proc events into the Service.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, Optional, Set

import numpy as np

from alaz_tpu.events.schema import PROC_EVENT_DTYPE, ProcEventType
from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.containers")

DEFAULT_EXCLUDED_NAMESPACES = {"kube-system"}


@dataclass
class ContainerInfo:
    container_id: str
    name: str = ""
    namespace: str = "default"
    pod_uid: str = ""
    pids: Set[int] = field(default_factory=set)
    log_path: str = ""


def cgroup_pids(cgroup_procs_path: str | Path) -> Set[int]:
    """Read a cgroup.procs file → pid set (the cgroup v1/v2 walk leaf,
    cri.go:192-233)."""
    try:
        text = Path(cgroup_procs_path).read_text()
    except OSError:
        return set()
    return {int(line) for line in text.split() if line.strip().isdigit()}


class ContainerIndex:
    def __init__(
        self,
        lister: Optional[Callable[[], Iterable[ContainerInfo]]] = None,
        exclude_namespaces: Iterable[str] = DEFAULT_EXCLUDED_NAMESPACES,
        sync_interval_s: float = 30.0,
    ):
        self.lister = lister
        self.exclude = set(exclude_namespaces)
        self.sync_interval_s = sync_interval_s
        self.containers: Dict[str, ContainerInfo] = {}
        self.container_pids: Set[int] = set()
        self._service = None  # lockless-ok: attach-once publication in start() before the sync thread exists; readers null-check an atomic reference swap
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- registration (tests / adapters) -----------------------------------

    def register(self, info: ContainerInfo) -> None:
        if info.namespace in self.exclude:
            return
        with self._lock:
            self.containers[info.container_id] = info

    def remove(self, container_id: str) -> None:
        with self._lock:
            self.containers.pop(container_id, None)

    def get_pids_running_on_containers(self) -> Set[int]:
        """The CRITool.GetPidsRunningOnContainers surface (cri.go:160)."""
        with self._lock:
            out: Set[int] = set()
            for c in self.containers.values():
                out |= c.pids
            return out

    def get_log_path(self, container_id: str) -> str:
        c = self.containers.get(container_id)
        return c.log_path if c else ""

    # -- the 30s diff loop (collector.go:137-197) ---------------------------

    def sync_once(self) -> tuple[Set[int], Set[int]]:
        """Diff current vs known pids → (added, removed); pushes synthetic
        EXEC/EXIT proc events into the service."""
        if self.lister is not None:
            with self._lock:
                self.containers = {
                    c.container_id: c
                    for c in self.lister()
                    if c.namespace not in self.exclude
                }
        new = self.get_pids_running_on_containers()
        added = new - self.container_pids
        removed = self.container_pids - new
        self.container_pids = new
        if self._service is not None and (added or removed):
            ev = np.zeros(len(added) + len(removed), dtype=PROC_EVENT_DTYPE)
            for i, pid in enumerate(sorted(added)):
                ev["pid"][i] = pid
                ev["type"][i] = ProcEventType.EXEC
            for j, pid in enumerate(sorted(removed)):
                ev["pid"][len(added) + j] = pid
                ev["type"][len(added) + j] = ProcEventType.EXIT
            self._service.submit_proc(ev)
        return added, removed

    def start(self, service) -> None:
        self._service = service
        self._stop.clear()

        def run() -> None:
            # sync immediately so startup containers attribute from second
            # one (the reference's loop also syncs before ticking)
            while True:
                try:
                    self.sync_once()
                except Exception as exc:
                    log.warning(f"container sync failed: {exc}")
                if self._stop.wait(self.sync_interval_s):
                    return

        self._thread = threading.Thread(target=run, name="alaz-containers", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
